"""``ray_tpu`` CLI.

Analog of the reference's ``ray …`` commands (python/ray/scripts/scripts.py:
start :537, stop :982, status, memory, timeline, microbenchmark :1818) plus the
state CLI (python/ray/util/state/state_cli.py) and job CLI
(dashboard/modules/job/cli.py). Run as ``python -m ray_tpu <command>``.

``start`` daemonizes by re-exec'ing itself with ``--block`` in a detached
session; the head writes its addresses to ``/tmp/ray_tpu/ray_current_cluster``
(the reference's cluster-address file pattern) so later CLI calls and
``ray_tpu.init(address="auto")`` can find it.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

CLUSTER_FILE = "/tmp/ray_tpu/ray_current_cluster"
NODES_DIR = "/tmp/ray_tpu/nodes"


def _read_cluster_file() -> dict | None:
    try:
        with open(CLUSTER_FILE) as f:
            return json.load(f)
    except Exception:
        return None


def _dashboard_url(args_address: str | None = None) -> str:
    if args_address:
        return args_address
    info = _read_cluster_file()
    if info and info.get("dashboard_address"):
        return "%s:%d" % tuple(info["dashboard_address"])
    raise SystemExit("no running cluster found (is `ray_tpu start --head` up?)")


def _gcs_address(explicit: str | None = None) -> str:
    if explicit:
        return explicit
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    info = _read_cluster_file()
    if info and info.get("gcs_address"):
        return "%s:%d" % tuple(info["gcs_address"])
    raise SystemExit("no running cluster found (is `ray_tpu start --head` up?)")


# ----------------------------------------------------------------------
# start / stop
# ----------------------------------------------------------------------


def cmd_start(args):
    if not args.block:
        # Daemonize: re-exec with --block in a detached session. The child
        # signals readiness by writing a unique ready-file we pass it, so a
        # stale marker from an earlier node can never fake a success.
        import uuid

        os.makedirs(NODES_DIR, exist_ok=True)
        if args.head and os.path.exists(CLUSTER_FILE):
            info = _read_cluster_file()
            if info and _pid_alive(info.get("pid")):
                raise SystemExit(
                    f"a cluster is already running (pid {info['pid']}); run `ray_tpu stop` first"
                )
            os.unlink(CLUSTER_FILE)
        ready_file = os.path.join(NODES_DIR, f"ready_{uuid.uuid4().hex[:12]}")
        cmd = (
            [sys.executable, "-m", "ray_tpu.scripts.scripts"]
            + sys.argv[1:]
            + ["--block", "--ready-file", ready_file]
        )
        log_path = "/tmp/ray_tpu/node_daemon.log"
        with open(log_path, "ab") as log_f:
            proc = subprocess.Popen(
                cmd, stdout=log_f, stderr=subprocess.STDOUT, start_new_session=True
            )
        deadline = time.time() + 60
        try:
            while time.time() < deadline:
                if os.path.exists(ready_file):
                    if args.head:
                        info = _read_cluster_file()
                        print("Started head node.")
                        print("  GCS address:       %s:%d" % tuple(info["gcs_address"]))
                        if info.get("dashboard_address"):
                            print(
                                "  Dashboard:         http://%s:%d"
                                % tuple(info["dashboard_address"])
                            )
                        if info.get("client_server_address"):
                            host_, port_ = info["client_server_address"]
                            if host_ == "0.0.0.0":  # bind-all: show a dialable host
                                host_ = info["gcs_address"][0]
                            print(f"  Ray client:        ray_tpu://{host_}:{port_}")
                        print('  Connect with:      ray_tpu.init(address="auto")')
                    else:
                        print("Started worker node.")
                    return
                if proc.poll() is not None:
                    raise SystemExit(
                        f"node process exited with code {proc.returncode}; see {log_path}"
                    )
                time.sleep(0.2)
            raise SystemExit(f"node did not come up within 60s; see {log_path}")
        finally:
            try:
                os.unlink(ready_file)
            except OSError:
                pass

    # --block: actually run the node in this process.
    import ray_tpu  # noqa: F401  (package import path check)
    from ray_tpu._private.node import Node

    resources = json.loads(args.resources) if args.resources else None
    labels = json.loads(args.labels) if args.labels else None
    if args.head:
        node = Node(
            head=True,
            num_cpus=args.num_cpus,
            num_tpus=args.num_tpus,
            resources=resources,
            labels=labels,
            object_store_memory=args.object_store_memory,
        )
        dashboard = None
        dashboard_addr = None
        if not args.no_dashboard:
            from ray_tpu.dashboard import DashboardHead

            dashboard = DashboardHead(
                node.gcs_address,
                node.session_dir,
                host=args.dashboard_host,
                port=args.dashboard_port,
            )
            dashboard_addr = list(dashboard.address)
        client_server = None
        client_server_addr = None
        driver_cw = None
        if not args.no_ray_client_server:
            from ray_tpu._private.core_worker import DRIVER, CoreWorker
            from ray_tpu.util.client import ClientServer

            driver_cw = CoreWorker(
                mode=DRIVER,
                gcs_address=node.gcs_address,
                raylet_address=node.raylet.address,
                arena_name=node.raylet.arena_name,
                node_id=node.node_id,
                session_dir=node.session_dir,
            )
            client_server = ClientServer(
                driver_cw, host="0.0.0.0", port=args.ray_client_server_port
            )
            client_server_addr = list(client_server.address)
        os.makedirs(os.path.dirname(CLUSTER_FILE), exist_ok=True)
        with open(CLUSTER_FILE, "w") as f:
            json.dump(
                {
                    "gcs_address": list(node.gcs_address),
                    "dashboard_address": dashboard_addr,
                    "client_server_address": client_server_addr,
                    "pid": os.getpid(),
                    "session_dir": node.session_dir,
                },
                f,
            )
        monitor = None
        if args.autoscaling_config:
            with open(args.autoscaling_config) as f:
                as_config = json.load(f)
            as_config.setdefault("provider", {})
            as_config["provider"].setdefault("type", "fake")
            as_config["provider"]["gcs_address"] = "%s:%d" % tuple(node.gcs_address)
            from ray_tpu.autoscaler import Monitor

            monitor = Monitor(as_config)
        marker = CLUSTER_FILE
        if args.ready_file:
            with open(args.ready_file, "w") as f:
                f.write(str(os.getpid()))
    else:
        client_server = None
        driver_cw = None
        gcs = _gcs_address(args.address)
        host, port = gcs.rsplit(":", 1)
        node = Node(
            head=False,
            gcs_address=(host, int(port)),
            num_cpus=args.num_cpus,
            num_tpus=args.num_tpus,
            resources=resources,
            labels=labels,
            object_store_memory=args.object_store_memory,
        )
        dashboard = None
        monitor = None
        os.makedirs(NODES_DIR, exist_ok=True)
        marker = os.path.join(NODES_DIR, f"node_{os.getpid()}.json")
        with open(marker, "w") as f:
            json.dump({"pid": os.getpid(), "node_id": node.node_id}, f)
        if args.ready_file:
            with open(args.ready_file, "w") as f:
                f.write(str(os.getpid()))

    stop_evt = {"stop": False}

    def _sig(_sig, _frm):
        stop_evt["stop"] = True

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)
    try:
        while not stop_evt["stop"]:
            time.sleep(0.5)
    finally:
        if monitor is not None:
            monitor.stop()
        if client_server is not None:
            client_server.stop()
        if driver_cw is not None:
            try:
                driver_cw.shutdown()
            except Exception:
                pass
        if dashboard is not None:
            dashboard.stop()
        node.stop()
        try:
            os.unlink(marker)
        except OSError:
            pass


def _node_files() -> list[str]:
    try:
        return os.listdir(NODES_DIR)
    except OSError:
        return []


def _pid_alive(pid) -> bool:
    if not pid:
        return False
    try:
        os.kill(int(pid), 0)
        return True
    except OSError:
        return False


def cmd_stop(args):
    killed = 0
    for fname in _node_files():
        path = os.path.join(NODES_DIR, fname)
        try:
            with open(path) as f:
                pid = json.load(f).get("pid")
        except Exception:
            pid = None
        if _pid_alive(pid):
            os.kill(int(pid), signal.SIGTERM)
            killed += 1
        try:
            os.unlink(path)
        except OSError:
            pass
    info = _read_cluster_file()
    if info and _pid_alive(info.get("pid")):
        os.kill(int(info["pid"]), signal.SIGTERM)
        killed += 1
    try:
        os.unlink(CLUSTER_FILE)
    except OSError:
        pass
    print(f"Stopped {killed} node process(es).")


# ----------------------------------------------------------------------
# status / memory / timeline / state
# ----------------------------------------------------------------------


def cmd_status(args):
    from ray_tpu._private.state import GlobalState

    host, port = _gcs_address(args.address).rsplit(":", 1)
    state = GlobalState(gcs_address=(host, int(port)))
    try:
        nodes = state.nodes()
        total = state.cluster_resources()
        avail = state.available_resources()
    finally:
        state.close()
    alive = [n for n in nodes if n["state"] == "ALIVE"]
    print(f"Nodes: {len(alive)} alive, {len(nodes) - len(alive)} dead")
    for n in alive:
        print(f"  {n['node_id'][:12]}  {n['address'][0]}:{n['address'][1]}")
    print("Resources:")
    for key in sorted(total):
        used = total[key] - avail.get(key, 0)
        print(f"  {used:g}/{total[key]:g} {key}")


def cmd_memory(args):
    from ray_tpu.util.state import list_objects

    rows = list_objects(address=_gcs_address(args.address))
    total = sum(r.get("size_bytes") or 0 for r in rows)
    print(f"{len(rows)} objects, {total / (1024 * 1024):.1f} MiB total")
    for r in rows[: args.limit]:
        print(
            f"  {r['object_id'][:16]}  {(r.get('size_bytes') or 0) / 1024:8.1f} KiB  "
            f"node={str(r.get('node_id'))[:8]}"
        )


def cmd_timeline(args):
    from ray_tpu._private.state import GlobalState

    host, port = _gcs_address(args.address).rsplit(":", 1)
    state = GlobalState(gcs_address=(host, int(port)))
    try:
        events = state.chrome_tracing_dump(filename=args.output)
    finally:
        state.close()
    print(f"Wrote {len(events)} events to {args.output}")


def cmd_debug(args):
    """``ray_tpu debug dump``: collect every process's flight-recorder ring
    cluster-wide (via the raylets' ``debug_dump`` RPC — mmap-backed rings, so
    SIGKILLed workers' final events are included) and merge them with the
    GCS task events into one Chrome-trace JSON."""
    from ray_tpu._private.state import GlobalState

    host, port = _gcs_address(args.address).rsplit(":", 1)
    state = GlobalState(gcs_address=(host, int(port)))
    try:
        if args.debug_cmd == "dump":
            flight = state.flight_recorder_dump()
            trace = state.chrome_tracing_dump(
                filename=args.output, flight_events=flight
            )
            by_type: dict[str, int] = {}
            for ev in flight:
                by_type[ev["type"]] = by_type.get(ev["type"], 0) + 1
            procs = {(ev.get("node_id"), ev.get("pid"), ev.get("role")) for ev in flight}
            print(
                f"Wrote {len(trace)} trace events ({len(flight)} flight events "
                f"from {len(procs)} processes) to {args.output}"
            )
            for etype in sorted(by_type):
                print(f"  {etype:16} {by_type[etype]}")
    finally:
        state.close()


def cmd_list(args):
    from ray_tpu.util.state import api as state_api

    fn = getattr(state_api, f"list_{args.resource}", None)
    if fn is None:
        raise SystemExit(f"unknown resource {args.resource!r}")
    rows = fn(address=_gcs_address(args.address), limit=args.limit)
    print(json.dumps(rows, indent=2, default=str))


def cmd_summary(args):
    from ray_tpu.util.state import summarize_tasks

    print(json.dumps(summarize_tasks(address=_gcs_address(args.address)), indent=2))


# ----------------------------------------------------------------------
# job
# ----------------------------------------------------------------------


def cmd_job(args):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(_dashboard_url(args.address))
    if args.job_cmd == "submit":
        runtime_env = json.loads(args.runtime_env_json) if args.runtime_env_json else None
        entrypoint = list(args.entrypoint)
        if entrypoint and entrypoint[0] == "--":
            entrypoint = entrypoint[1:]
        if not entrypoint:
            raise SystemExit("job submit requires an entrypoint, e.g. `job submit -- python my.py`")
        import shlex

        # argv → shell string with each arg quoted, so `job submit -- python
        # -c "code with spaces"` survives the round trip through `sh -c`.
        sid = client.submit_job(
            entrypoint=shlex.join(entrypoint), runtime_env=runtime_env, submission_id=args.submission_id
        )
        print(f"Submitted job {sid}")
        if not args.no_wait:
            status = client.wait_until_finished(sid, timeout=args.timeout)
            print(client.get_job_logs(sid), end="")
            print(f"Job {sid} finished: {status}")
            if status != "SUCCEEDED":
                sys.exit(1)
    elif args.job_cmd == "list":
        for j in client.list_jobs():
            print(f"{j['submission_id']}  {j['status']:10}  {j['entrypoint']}")
    elif args.job_cmd == "status":
        print(client.get_job_status(args.submission_id))
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.submission_id), end="")
    elif args.job_cmd == "stop":
        print(client.stop_job(args.submission_id))


# ----------------------------------------------------------------------
# serve (reference: serve/scripts.py — serve run/status/shutdown)
# ----------------------------------------------------------------------


def cmd_serve(args):
    import importlib

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(address=_gcs_address(args.address))
    if args.serve_cmd == "run":
        # import_path "module:app" where app is a bound Application.
        mod_name, _, attr = args.import_path.partition(":")
        sys.path.insert(0, os.getcwd())
        app = getattr(importlib.import_module(mod_name), attr or "app")
        serve.run(app, route_prefix=args.route_prefix or "__from_deployment__")
        host, port = serve.http_address()
        print(f"Serving at http://{host}:{port} (ctrl-c to stop)")
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            # Honor the promise: interrupt tears the application down
            # (reference: `serve run` shuts down on interrupt).
            print("Shutting down serve...")
            serve.shutdown()
    elif args.serve_cmd == "deploy":
        from ray_tpu.serve.schema import apply_config, load_config

        config = load_config(args.config_file)
        routes = apply_config(config)
        host, port = serve.http_address()
        for name, route in routes.items():
            if route:
                print(f"deployed application {name!r} at http://{host}:{port}{route}")
            else:
                print(f"deployed application {name!r} (no HTTP route; use a deployment handle)")
    elif args.serve_cmd == "status":
        for name, st in serve.status().items():
            print(
                f"{name:24} replicas={st['num_replicas']}/{st['target']} "
                f"version={st['version']} route={st['route_prefix']}"
            )
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("Serve shut down.")


# ----------------------------------------------------------------------
# chaos (reference: `ray kill-random-node`, scripts.py:1337)
# ----------------------------------------------------------------------


LAUNCHER_DIR = "/tmp/ray_tpu/clusters"


def _load_cluster_yaml(path: str) -> dict:
    import yaml

    with open(path) as f:
        config = yaml.safe_load(f)
    config.setdefault("cluster_name", "default")
    config.setdefault("provider", {"type": "fake"})
    # Reference configs use available_node_types; the autoscaler's native
    # key is node_types — accept both.
    if "available_node_types" in config and "node_types" not in config:
        config["node_types"] = {
            name: {
                "resources": nt.get("resources", {}),
                "max_workers": nt.get("max_workers", config.get("max_workers", 8)),
                "min_workers": nt.get("min_workers", 0),
            }
            for name, nt in config["available_node_types"].items()
        }
    return config


def _launcher_file(name: str) -> str:
    return os.path.join(LAUNCHER_DIR, f"{name}.json")


def cmd_up(args):
    """Launch a cluster from a YAML config (reference: `ray up`,
    scripts.py:1235). The head starts on this machine; worker nodes come
    from the config's provider (fake = local raylet subprocesses, tpu = TPU
    pods) driven by a detached autoscaler monitor process."""
    config = _load_cluster_yaml(args.cluster_config)
    name = config["cluster_name"]
    os.makedirs(LAUNCHER_DIR, exist_ok=True)
    if os.path.exists(_launcher_file(name)):
        with open(_launcher_file(name)) as f:
            existing = json.load(f)
        if _pid_alive(existing.get("monitor_pid")):
            raise SystemExit(f"cluster {name!r} is already up; run `ray_tpu down {args.cluster_config}` first")
    head = config.get("head_node", {})
    head_res = dict(head.get("resources", {}))
    custom = {k: v for k, v in head_res.items() if k not in ("CPU", "TPU")}
    start_args = [
        sys.executable, "-m", "ray_tpu.scripts.scripts", "start", "--head",
        "--num-cpus", str(int(head_res.get("CPU", os.cpu_count() or 1))),
        "--num-tpus", str(int(head_res.get("TPU", 0))),
    ]
    if custom:
        start_args += ["--resources", json.dumps(custom)]
    subprocess.run(start_args, check=True)
    info = _read_cluster_file()
    gcs_address = "%s:%d" % tuple(info["gcs_address"])
    config.setdefault("provider", {})["gcs_address"] = gcs_address
    cfg_path = os.path.join(LAUNCHER_DIR, f"{name}_autoscaler.json")
    with open(cfg_path, "w") as f:
        json.dump(config, f)
    log_path = os.path.join(LAUNCHER_DIR, f"{name}_monitor.log")
    with open(log_path, "ab") as log_f:
        monitor = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.autoscaler.monitor", "--config-file", cfg_path],
            stdout=log_f, stderr=subprocess.STDOUT, start_new_session=True,
        )
    with open(_launcher_file(name), "w") as f:
        json.dump({
            "cluster_name": name,
            "gcs_address": gcs_address,
            "monitor_pid": monitor.pid,
            "config_file": cfg_path,
        }, f)
    print(f"cluster {name!r} is up: address {gcs_address}, autoscaler pid {monitor.pid}")
    print(f"connect with ray_tpu.init(address='{gcs_address}')")


def cmd_down(args):
    """Tear down a launched cluster (reference: `ray down`)."""
    config = _load_cluster_yaml(args.cluster_config)
    name = config["cluster_name"]
    path = _launcher_file(name)
    if not os.path.exists(path):
        raise SystemExit(f"no launched cluster {name!r} (missing {path})")
    with open(path) as f:
        info = json.load(f)
    if _pid_alive(info.get("monitor_pid")):
        try:
            os.kill(info["monitor_pid"], signal.SIGTERM)
        except OSError:
            pass
        # The monitor terminates its nodes on SIGTERM (it holds the Popen
        # handles); wait for it before the fallback below.
        deadline = time.time() + 20
        while time.time() < deadline and _pid_alive(info["monitor_pid"]):
            time.sleep(0.2)
    # Fallback for providers with external node state (or a dead monitor),
    # then stop every local node process.
    from ray_tpu.autoscaler.autoscaler import _make_provider

    with open(info["config_file"]) as f:
        as_config = json.load(f)
    provider = _make_provider(as_config)
    for nid in provider.non_terminated_nodes():
        provider.terminate_node(nid)
    provider.shutdown()
    subprocess.run([sys.executable, "-m", "ray_tpu.scripts.scripts", "stop"], check=False)
    os.unlink(path)
    print(f"cluster {name!r} is down")


def _cluster_env(args) -> dict:
    config = _load_cluster_yaml(args.cluster_config)
    path = _launcher_file(config["cluster_name"])
    if not os.path.exists(path):
        raise SystemExit(f"cluster {config['cluster_name']!r} is not up")
    with open(path) as f:
        info = json.load(f)
    env = dict(os.environ)
    env["RAY_TPU_ADDRESS"] = info["gcs_address"]
    return env


def cmd_exec(args):
    """Run a shell command against the cluster (reference: `ray exec`) —
    local-provider analog: the command runs here with RAY_TPU_ADDRESS set."""
    rc = subprocess.run(args.command, shell=True, env=_cluster_env(args)).returncode
    raise SystemExit(rc)


def cmd_submit(args):
    """Run a python script as a driver on the cluster (reference:
    `ray submit`)."""
    rc = subprocess.run(
        [sys.executable, args.script] + args.script_args, env=_cluster_env(args)
    ).returncode
    raise SystemExit(rc)


def cmd_attach(args):
    """Open an interactive shell wired to the cluster (reference:
    `ray attach`)."""
    shell = os.environ.get("SHELL", "/bin/bash")
    print(f"attached to cluster (RAY_TPU_ADDRESS set); exit the shell to detach")
    rc = subprocess.run([shell], env=_cluster_env(args)).returncode
    raise SystemExit(rc)


def cmd_stack(args):
    """Dump Python stacks of every live local worker (reference: `ray stack`,
    scripts.py:1786, which shells out to py-spy; here workers self-report via
    a faulthandler SIGUSR1 handler into their .err logs)."""
    import glob

    import psutil

    def _is_worker(p):
        cmd = " ".join(p.info["cmdline"] or [])
        if "ray_tpu._private.worker_main" in cmd:
            return True
        if "ray_tpu._private.zygote" in cmd:
            # Fork-server children keep the zygote's cmdline: a WORKER is a
            # process whose parent is also a zygote process (the fork-server
            # listener itself is a child of the raylet, not of a zygote).
            try:
                parent = p.parent()
                return parent is not None and "ray_tpu._private.zygote" in " ".join(
                    parent.cmdline()
                )
            except Exception:
                return False
        return False

    workers = [p for p in psutil.process_iter(["pid", "cmdline"]) if _is_worker(p)]
    if not workers:
        print("no live ray_tpu workers on this host")
        return
    # Every live session on the host — a local multi-node cluster runs one
    # session dir per node and all their workers get signalled below.
    err_files = sorted(glob.glob("/tmp/ray_tpu/session_*/logs/worker-*.err"))
    # Snapshot sizes BEFORE signalling so only freshly-appended dumps are
    # shown — stale blocks from an earlier `stack` run must not masquerade
    # as live stacks.
    offsets = {}
    for err in err_files:
        try:
            offsets[err] = os.path.getsize(err)
        except OSError:
            offsets[err] = 0
    signalled = 0
    for p in workers:
        try:
            p.send_signal(signal.SIGUSR1)
            signalled += 1
        except psutil.Error:
            pass
    time.sleep(0.5)  # let faulthandler flush
    shown = 0
    for err in err_files:
        try:
            with open(err, "rb") as f:
                f.seek(offsets.get(err, 0))
                fresh = f.read().decode(errors="replace")
        except OSError:
            continue
        if "Thread 0x" not in fresh and "Current thread" not in fresh:
            continue
        print(f"=== {os.path.basename(err)} ===")
        print(fresh.strip())
        print()
        shown += 1
    print(f"stacks from {shown} workers ({signalled} signalled)")


def cmd_kill_random_node(args):
    import random

    # Candidates = local worker-node processes that are actually alive and
    # killable (GCS may still list a just-killed node as ALIVE until the
    # heartbeat timeout; a chaos loop must land one kill per round). The
    # head is never among these markers — only worker nodes write them.
    candidates = []
    for fname in _node_files():
        path = os.path.join(NODES_DIR, fname)
        try:
            with open(path) as f:
                rec = json.load(f)
            node_id, pid = rec.get("node_id"), rec.get("pid")
        except Exception:
            continue
        if node_id and _pid_alive(pid):
            candidates.append((path, node_id, int(pid)))
    if not candidates:
        print("no killable worker-node processes on this host")
        return
    path, node_id, pid = random.choice(candidates)
    os.kill(pid, signal.SIGKILL)
    try:
        os.unlink(path)
    except OSError:
        pass
    print(f"killed node {node_id[:12]} (pid {pid})")


# ----------------------------------------------------------------------
# microbenchmark
# ----------------------------------------------------------------------


def cmd_microbenchmark(args):
    """Single-node task/actor/object throughput suite (reference:
    python/ray/_private/ray_perf.py:93)."""
    import numpy as np

    import ray_tpu

    ray_tpu.init(num_cpus=args.num_cpus, object_store_memory=256 * 1024 * 1024)

    def timeit(name, fn, multiplier=1):
        # warmup
        fn()
        start = time.time()
        count = 0
        while time.time() - start < args.duration:
            fn()
            count += 1
        dt = time.time() - start
        rate = count * multiplier / dt
        print(f"{name:45s} {rate:12.1f} /s")

    @ray_tpu.remote
    def small():
        return b"ok"

    @ray_tpu.remote
    class Actor:
        def ping(self):
            return b"ok"

    a = Actor.remote()
    ray_tpu.get(a.ping.remote())

    timeit("single client task sync (submit+get)", lambda: ray_tpu.get(small.remote()))
    timeit(
        "single client task async (100 in flight)",
        lambda: ray_tpu.get([small.remote() for _ in range(100)]),
        multiplier=100,
    )
    timeit("single client actor call sync", lambda: ray_tpu.get(a.ping.remote()))
    timeit(
        "single client actor calls async (100)",
        lambda: ray_tpu.get([a.ping.remote() for _ in range(100)]),
        multiplier=100,
    )
    arr = np.zeros(1024 * 1024, dtype=np.uint8)
    timeit("put 1MiB numpy", lambda: ray_tpu.put(arr))
    ref_holder = {}

    def put_get():
        r = ray_tpu.put(arr)
        ray_tpu.get(r)

    timeit("put+get 1MiB numpy roundtrip", put_get)
    ray_tpu.shutdown()


# ----------------------------------------------------------------------


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", help="GCS address host:port (worker nodes)")
    p.add_argument("--num-cpus", type=int, default=None)
    p.add_argument("--num-tpus", type=int, default=None)
    p.add_argument("--resources", help="JSON dict of custom resources")
    p.add_argument("--labels", help="JSON dict of node labels")
    p.add_argument("--object-store-memory", type=int, default=None)
    p.add_argument("--dashboard-host", default="127.0.0.1")
    p.add_argument("--dashboard-port", type=int, default=8265)
    p.add_argument("--no-dashboard", action="store_true")
    p.add_argument("--ray-client-server-port", type=int, default=0, help="0 = ephemeral")
    p.add_argument("--no-ray-client-server", action="store_true")
    p.add_argument(
        "--autoscaling-config",
        default=None,
        help="JSON file with autoscaler config (node_types, max_workers, ...)",
    )
    p.add_argument("--block", action="store_true", help="run in the foreground")
    p.add_argument("--ready-file", default=None, help=argparse.SUPPRESS)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="stop all nodes started on this host")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status", help="cluster nodes + resource usage")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("memory", help="object store contents")
    p.add_argument("--address", default=None)
    p.add_argument("--limit", type=int, default=50)
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("timeline", help="dump Chrome trace of task events")
    p.add_argument("--address", default=None)
    p.add_argument("-o", "--output", default="timeline.json")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("debug", help="flight-recorder postmortem tooling")
    dsub = p.add_subparsers(dest="debug_cmd", required=True)
    dd = dsub.add_parser("dump", help="merge cluster flight rings + task events into a Chrome trace")
    dd.add_argument("--address", default=None)
    dd.add_argument("-o", "--output", default="flight_dump.json")
    p.set_defaults(fn=cmd_debug)

    p = sub.add_parser("list", help="state API listing")
    p.add_argument(
        "resource",
        choices=[
            "tasks",
            "actors",
            "nodes",
            "jobs",
            "objects",
            "device_objects",
            "workers",
            "placement_groups",
        ],
    )
    p.add_argument("--address", default=None)
    p.add_argument("--limit", type=int, default=100)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("summary", help="task state summary")
    p.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("job", help="job submission")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--address", default=None, help="dashboard http address")
    js.add_argument("--runtime-env-json", default=None)
    js.add_argument("--submission-id", default=None)
    js.add_argument("--no-wait", action="store_true")
    js.add_argument("--timeout", type=float, default=3600.0)
    js.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("list", "status", "logs", "stop"):
        jp = jsub.add_parser(name)
        jp.add_argument("--address", default=None)
        if name != "list":
            jp.add_argument("submission_id")
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser("serve", help="model serving")
    ssub = p.add_subparsers(dest="serve_cmd", required=True)
    sr = ssub.add_parser("run")
    sr.add_argument("import_path", help="module:bound_app, e.g. my_app:app")
    sr.add_argument("--address", default=None)
    sr.add_argument("--route-prefix", default=None)
    sd = ssub.add_parser("deploy", help="deploy applications from a YAML/JSON config")
    sd.add_argument("config_file")
    sd.add_argument("--address", default=None)
    for name in ("status", "shutdown"):
        sp2 = ssub.add_parser(name)
        sp2.add_argument("--address", default=None)
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("up", help="launch a cluster from a YAML config")
    p.add_argument("cluster_config")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="tear down a launched cluster")
    p.add_argument("cluster_config")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("exec", help="run a shell command against the cluster")
    p.add_argument("cluster_config")
    p.add_argument("command")
    p.set_defaults(fn=cmd_exec)

    p = sub.add_parser("submit", help="run a python script as a cluster driver")
    p.add_argument("cluster_config")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("attach", help="interactive shell wired to the cluster")
    p.add_argument("cluster_config")
    p.set_defaults(fn=cmd_attach)

    p = sub.add_parser("stack", help="dump Python stacks of local workers")
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("kill-random-node", help="chaos: SIGKILL a random local worker node (never the head)")
    p.set_defaults(fn=cmd_kill_random_node)

    p = sub.add_parser("microbenchmark", help="task/actor/object throughput suite")
    p.add_argument("--num-cpus", type=int, default=None)
    p.add_argument("--duration", type=float, default=2.0, help="seconds per case")
    p.set_defaults(fn=cmd_microbenchmark)

    args = parser.parse_args(argv)
    try:
        args.fn(args)
    except BrokenPipeError:
        # stdout piped into e.g. `head` that exited — normal CLI etiquette.
        try:
            sys.stdout.close()
        except Exception:
            pass
        sys.exit(0)


if __name__ == "__main__":
    main()
