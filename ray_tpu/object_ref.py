"""ObjectRef — a future for a value in the distributed object store.

Analog of the reference's ObjectRef (python/ray/_raylet.pyx:208): carries the
28-byte object id plus the owner's core-worker RPC address so any borrower can
reach the owner for inline values and ref-count bookkeeping
(src/ray/core_worker/reference_count.h:61).
"""

from __future__ import annotations

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner_addr", "_registered")

    def __init__(self, object_id: ObjectID, owner_addr: tuple | None = None, *, _register: bool = True):
        self.id = object_id
        self.owner_addr = tuple(owner_addr) if owner_addr else None
        self._registered = False
        if _register:
            from ray_tpu._private import worker_context

            cw = worker_context.get_core_worker_if_initialized()
            if cw is not None:
                cw.register_ref(self)
                self._registered = True

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def __reduce__(self):
        from ray_tpu._private.serialization import record_contained_ref

        record_contained_ref(self)
        return (_deserialize_ref, (self.id.binary(), self.owner_addr))

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.hex()})"

    def __del__(self):
        if self._registered:
            try:
                from ray_tpu._private import worker_context

                cw = worker_context.get_core_worker_if_initialized()
                if cw is not None:
                    cw.deregister_ref(self)
            except Exception:
                pass

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from ray_tpu._private import worker_context

        return worker_context.get_core_worker().as_future(self)

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


def _deserialize_ref(binary: bytes, owner_addr):
    return ObjectRef(ObjectID(binary), owner_addr)


class ObjectRefGenerator:
    """Iterator over a streaming task's dynamically-yielded returns
    (reference: StreamingObjectRefGenerator, _raylet.pyx:227). Yields
    ObjectRefs AS the running task produces them — iteration overlaps with
    the producer; ray_tpu.get each ref (or next_ready()) for the values."""

    def __init__(self, core_worker, task_id: str):
        self._cw = core_worker
        self._task_id = task_id
        self._index = 0

    def __iter__(self):
        return self

    def __next__(self):
        oid_hex = self._cw.stream_next(self._task_id, self._index)
        self._index += 1
        return ObjectRef(ObjectID.from_hex(oid_hex), self._cw.address)

    def next_with_timeout(self, timeout: float):
        """Like next() but raises GetTimeoutError instead of blocking
        indefinitely when the producer stalls."""
        oid_hex = self._cw.stream_next(self._task_id, self._index, timeout=timeout)
        self._index += 1
        return ObjectRef(ObjectID.from_hex(oid_hex), self._cw.address)

    @property
    def task_id(self) -> str:
        return self._task_id
