"""Runtime context — introspection of the current driver/worker process.

TPU-native analog of the reference's ``ray.runtime_context``
(python/ray/runtime_context.py): exposes ids (job/node/task/actor/worker),
namespace, the GCS address, and the resources assigned to the currently
executing task.
"""

from __future__ import annotations

from ray_tpu._private import worker_context


class RuntimeContext:
    """Snapshot-free view onto the process's CoreWorker state."""

    def __init__(self, core_worker):
        self._cw = core_worker

    # ---- ids ----

    def get_job_id(self) -> str:
        # Worker processes carry a placeholder job id; the real submitting
        # job rides on the executing task's spec.
        spec = self._cw.current_task_spec or self._cw._actor_creation_spec
        if spec is not None and spec.job_id:
            return spec.job_id
        return self._cw.job_id.hex()

    def get_node_id(self) -> str:
        return self._cw.node_id

    def get_worker_id(self) -> str:
        return self._cw.worker_id

    def get_task_id(self) -> str | None:
        spec = self._cw.current_task_spec
        return spec.task_id if spec is not None else None

    def get_task_name(self) -> str | None:
        spec = self._cw.current_task_spec
        return spec.name if spec is not None else None

    def get_actor_id(self) -> str | None:
        return self._cw._actor_id

    def get_actor_name(self) -> str | None:
        spec = self._cw._actor_creation_spec
        if spec is None:
            return None
        return spec.actor_name or None

    # ---- environment ----

    @property
    def namespace(self) -> str:
        return self._cw.namespace

    @property
    def gcs_address(self):
        return tuple(self._cw.gcs.address)

    @property
    def worker_mode(self) -> str:
        return self._cw.mode

    def get_assigned_resources(self) -> dict:
        """Resources held by the currently executing task (empty on drivers)."""
        spec = self._cw.current_task_spec
        if spec is None:
            return {}
        return dict(spec.resources or {})

    def get_runtime_env(self) -> dict:
        spec = self._cw.current_task_spec or self._cw._actor_creation_spec
        if spec is None:
            return {}
        return dict(spec.runtime_env or {})

    def get_placement_group_id(self) -> str | None:
        spec = self._cw.current_task_spec
        if spec is None or not spec.placement_group_id:
            return None
        return spec.placement_group_id

    def to_dict(self) -> dict:
        return {
            "job_id": self.get_job_id(),
            "node_id": self.get_node_id(),
            "worker_id": self.get_worker_id(),
            "task_id": self.get_task_id(),
            "actor_id": self.get_actor_id(),
            "namespace": self.namespace,
            "worker_mode": self.worker_mode,
        }


def get_runtime_context() -> RuntimeContext:
    """Return the RuntimeContext of the current process."""
    return RuntimeContext(worker_context.get_core_worker())
