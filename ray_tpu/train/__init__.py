"""ray_tpu.train — distributed training (reference: python/ray/train)."""

from ray_tpu.train.base_trainer import BaseTrainer  # noqa: F401
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer  # noqa: F401
from ray_tpu.train.predictor import BatchPredictor, JaxPredictor, Predictor  # noqa: F401
from ray_tpu.train.sklearn import (  # noqa: F401
    HorovodTrainer,
    LightGBMTrainer,
    LightningTrainer,
    MosaicTrainer,
    SklearnTrainer,
    TensorflowTrainer,
    XGBoostTrainer,
)
