"""BaseTrainer + Result.

Analog of the reference's BaseTrainer (python/ray/train/base_trainer.py:559
fit-via-Tune): ``fit()`` wraps the trainer as a 1-trial Tune experiment when
the tune package is asked for it, or runs directly; both paths share the same
training_loop contract.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig


@dataclass
class Result:
    metrics: dict = field(default_factory=dict)
    checkpoint: Checkpoint | None = None
    error: str | None = None
    path: str | None = None
    metrics_dataframe: object | None = None
    config: dict = field(default_factory=dict)  # the trial's resolved config


class BaseTrainer:
    def __init__(
        self,
        *,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        resume_from_checkpoint: Checkpoint | None = None,
        datasets: dict | None = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}

    def _run_dir(self) -> str:
        return self.run_config.resolve_dir(type(self).__name__)

    def training_loop(self) -> None:
        raise NotImplementedError

    def fit(self) -> Result:
        """Run to completion (reference routes this through a 1-trial Tune
        experiment — tune.Tuner(trainer).fit() does the same here)."""
        return self._fit_direct()

    def _fit_direct(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Adapter so tune.Tuner can run this trainer as a trial
        (reference: base_trainer.py as_trainable)."""
        trainer = self

        from ray_tpu.tune.trainable import FunctionTrainable

        def _train_fn(config):
            from ray_tpu.tune import report as tune_report

            merged = trainer._with_config_overrides(config)
            result = merged._fit_direct()
            if result.error:
                # A failed fit must fail the trial, not complete it with
                # empty metrics (trainers that catch-and-return errors,
                # e.g. SklearnTrainer, land here).
                raise RuntimeError(f"trainer fit failed: {result.error}")
            tune_report(result.metrics, checkpoint=result.checkpoint)

        return _train_fn

    def _with_config_overrides(self, config: dict) -> "BaseTrainer":
        if not config:
            return self
        import copy

        clone = copy.copy(self)
        overrides = config.get("train_loop_config")
        if overrides is not None and hasattr(clone, "train_loop_config"):
            merged = dict(getattr(clone, "train_loop_config") or {})
            merged.update(overrides)
            clone.train_loop_config = merged
        return clone
