"""BackendExecutor — orchestrates the worker gang for one training run.

Analog of the reference's BackendExecutor
(python/ray/train/_internal/backend_executor.py: start:104,
start_training:342) + the backend plugin protocol (train/torch/config.py:155).
Worker-gang LIFECYCLE goes through the shared AIR execution layer
(`ray_tpu.air.execution.ActorManager`): the gang's resources are one
multi-bundle ``ResourceRequest`` (a placement group for TPU gangs — one ICI
domain under STRICT_PACK), each ``TrainWorker`` is a tracked actor pinned to
its bundle, and gang start / gang restart / shutdown are manager operations.
That makes release guaranteed: a gang restart frees the old placement group
before reserving the new one (the pre-manager code leaked one PG per
restart), and ``shutdown()`` leaves nothing in ``GlobalState``.

The run loop itself is unchanged: run the backend's ``on_start``
(mesh/collective bootstrap — the reference's ``dist.init_process_group``
moment, SURVEY.md §3.4 step 5), start the user loop everywhere, poll
reports, and restart the whole gang from the last checkpoint on worker
failure (an XLA collective world is static — membership change means
rebuild, SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

import logging
import time

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.air.execution import (
    ActorManager,
    FixedResourceManager,
    PlacementGroupResourceManager,
    ResourceRequest,
)
from ray_tpu.train._internal.worker_group import TrainWorker, WorkerGroup

logger = logging.getLogger(__name__)


class Backend:
    """Backend plugin protocol (reference: train/_internal/backend.py)."""

    def on_start(self, worker_group: WorkerGroup, scaling_config: ScalingConfig):
        pass

    def on_shutdown(self, worker_group: WorkerGroup):
        pass


class JaxBackend(Backend):
    """Forms the collective plane: the worker gang materialises a Mesh.

    Replaces the reference's `_TorchBackend.on_start` NCCL bootstrap
    (train/torch/config.py:113 dist.init_process_group) with the TPU-native
    equivalent: collective group init -> jax.distributed -> jax.sharding.Mesh.
    """

    def __init__(self, backend: str | None = None, group_name: str = "train"):
        self.backend = backend
        self.group_name = group_name

    def on_start(self, worker_group: WorkerGroup, scaling_config: ScalingConfig):
        n = worker_group.num_workers
        if n == 1:
            ray_tpu.get(worker_group.workers[0].build_local_mesh.remote(), timeout=300)
            return
        backend = self.backend or ("tpu" if scaling_config.use_tpu else "tpu")
        refs = [
            w.init_collective.remote(n, rank, backend, self.group_name)
            for rank, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(refs, timeout=600)


class BackendExecutor:
    def __init__(
        self,
        backend: Backend,
        scaling_config: ScalingConfig,
        max_failures: int = 0,
    ):
        self.backend = backend
        self.scaling_config = scaling_config
        self.max_failures = max_failures
        self.worker_group: WorkerGroup | None = None
        # TPU gangs need atomic co-reservation (one ICI domain); CPU gangs
        # get budget bookkeeping with raylet enforcement.
        resource_manager = (
            PlacementGroupResourceManager()
            if scaling_config.use_tpu
            else FixedResourceManager()
        )
        self._actor_manager = ActorManager(resource_manager)
        self._tracked: list = []
        self.num_gang_restarts = 0

    def start(self):
        sc = self.scaling_config
        n = sc.num_workers
        # One request for the whole gang: N bundles, acquired and released
        # as a unit (refcounted by the manager across the N tracked actors).
        request = ResourceRequest(
            sc.as_placement_group_bundles(), strategy=sc.placement_strategy
        )
        self._tracked = [
            self._actor_manager.add_actor(
                TrainWorker,
                kwargs=dict(rank=rank, world_size=n),
                resource_request=request,
                bundle_index=rank,
                # Whole-gang restart is executor policy (static XLA world):
                # a lone member restarting in place would rejoin a dead
                # collective, so per-actor auto-restart stays off.
                max_restarts=0,
                graceful_stop_method="shutdown",
            )
            for rank in range(n)
        ]
        try:
            self._actor_manager.wait_for_actors(self._tracked, timeout=300)
        except (TimeoutError, RuntimeError):
            # Guaranteed release on failed start: no PG/bundle survives a
            # gang that never came up.
            self._remove_gang()
            raise
        self.worker_group = WorkerGroup.from_handles(
            [t.actor_handle for t in self._tracked]
        )
        self.backend.on_start(self.worker_group, sc)

    def _remove_gang(self):
        """Tear the gang down through the manager: cancels in-flight tasks,
        kills the workers, and frees the gang's resource acquisition (the
        placement group) once the last member is removed."""
        for tracked in self._tracked:
            self._actor_manager.remove_actor(tracked)
        self._tracked = []
        self.worker_group = None

    def run(
        self,
        train_fn,
        config: dict | None = None,
        dataset_shards_per_rank: list | None = None,
        on_report=None,
        checkpoint: Checkpoint | None = None,
    ) -> list[dict]:
        """Run the loop on all workers until completion; returns final
        reports per rank. Restarts the gang on failure (whole-group restart
        from the latest checkpoint)."""
        failures_left = self.max_failures
        latest_checkpoint = checkpoint
        while True:
            try:
                return self._run_once(
                    train_fn, config, dataset_shards_per_rank, on_report, latest_checkpoint
                )
            except _WorkerGroupError as e:
                if failures_left == 0:
                    raise TrainingFailedError(str(e)) from None
                failures_left -= 1 if failures_left > 0 else 0
                latest_checkpoint = e.latest_checkpoint or latest_checkpoint
                logger.warning(
                    "worker group failed (%s); restarting from %s",
                    e,
                    "checkpoint" if latest_checkpoint else "scratch",
                )
                # Gang restart as manager operations: remove (frees the old
                # placement group) then start (reserves a fresh one).
                self._remove_gang()
                self.num_gang_restarts += 1
                self.start()

    def _run_once(self, train_fn, config, shards_per_rank, on_report, checkpoint):
        wg = self.worker_group
        final_reports: list[dict] = [{} for _ in wg.workers]
        done = [False] * len(wg.workers)
        latest_checkpoint = None
        refs = []
        for rank, worker in enumerate(wg.workers):
            shards = shards_per_rank[rank] if shards_per_rank else None
            refs.append(
                worker.run_train_fn.remote(train_fn, config or {}, shards, checkpoint)
            )
        try:
            ray_tpu.get(refs, timeout=600)
        except ray_tpu.exceptions.RayTpuError as e:
            raise _WorkerGroupError(str(e), None) from None
        while not all(done):
            time.sleep(0.1)
            polls = []
            try:
                polls = ray_tpu.get(
                    [w.poll.remote() for w in wg.workers], timeout=60
                )
            except ray_tpu.exceptions.RayTpuError as e:
                raise _WorkerGroupError(str(e), latest_checkpoint) from None
            for rank, p in enumerate(polls):
                for metrics, ckpt_blob in p["reports"]:
                    final_reports[rank] = metrics
                    ckpt = Checkpoint.from_bytes(ckpt_blob) if ckpt_blob else None
                    if rank == 0 and ckpt is not None:
                        latest_checkpoint = ckpt
                    if rank == 0 and on_report is not None:
                        on_report(metrics, ckpt)
                if p["error"]:
                    raise _WorkerGroupError(
                        f"rank {rank} failed: {p['error']}", latest_checkpoint
                    )
                done[rank] = p["done"]
        return final_reports

    def shutdown(self):
        self._remove_gang()
        # Belt-and-braces: clear() force-releases anything still acquired,
        # so the executor cannot leak a placement group on any exit path.
        self._actor_manager.clear()


class TrainingFailedError(RuntimeError):
    """Analog of the reference's TrainingFailedError."""


class _WorkerGroupError(RuntimeError):
    def __init__(self, msg: str, latest_checkpoint=None):
        super().__init__(msg)
        self.latest_checkpoint = latest_checkpoint
