"""BackendExecutor — orchestrates the worker gang for one training run.

Analog of the reference's BackendExecutor
(python/ray/train/_internal/backend_executor.py: start:104,
start_training:342) + the backend plugin protocol (train/torch/config.py:155):
creates the WorkerGroup (under a placement group for TPU gangs), runs the
backend's ``on_start`` (mesh/collective bootstrap — the reference's
``dist.init_process_group`` moment, SURVEY.md §3.4 step 5), starts the user
loop everywhere, polls reports, and restarts the whole gang from the last
checkpoint on worker failure (an XLA collective world is static — membership
change means rebuild, SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

import logging
import time

import ray_tpu
from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train._internal.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class Backend:
    """Backend plugin protocol (reference: train/_internal/backend.py)."""

    def on_start(self, worker_group: WorkerGroup, scaling_config: ScalingConfig):
        pass

    def on_shutdown(self, worker_group: WorkerGroup):
        pass


class JaxBackend(Backend):
    """Forms the collective plane: the worker gang materialises a Mesh.

    Replaces the reference's `_TorchBackend.on_start` NCCL bootstrap
    (train/torch/config.py:113 dist.init_process_group) with the TPU-native
    equivalent: collective group init -> jax.distributed -> jax.sharding.Mesh.
    """

    def __init__(self, backend: str | None = None, group_name: str = "train"):
        self.backend = backend
        self.group_name = group_name

    def on_start(self, worker_group: WorkerGroup, scaling_config: ScalingConfig):
        n = worker_group.num_workers
        if n == 1:
            ray_tpu.get(worker_group.workers[0].build_local_mesh.remote(), timeout=300)
            return
        backend = self.backend or ("tpu" if scaling_config.use_tpu else "tpu")
        refs = [
            w.init_collective.remote(n, rank, backend, self.group_name)
            for rank, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(refs, timeout=600)


class BackendExecutor:
    def __init__(
        self,
        backend: Backend,
        scaling_config: ScalingConfig,
        max_failures: int = 0,
    ):
        self.backend = backend
        self.scaling_config = scaling_config
        self.max_failures = max_failures
        self.worker_group: WorkerGroup | None = None
        self._pg = None

    def start(self):
        sc = self.scaling_config
        if sc.use_tpu:
            from ray_tpu.util.placement_group import placement_group

            self._pg = placement_group(
                sc.as_placement_group_bundles(), strategy=sc.placement_strategy
            )
            self._pg.ready(timeout=300)
        self.worker_group = WorkerGroup(
            sc.num_workers,
            resources_per_worker=sc.worker_resources(),
            placement_group=self._pg,
        )
        self.backend.on_start(self.worker_group, sc)

    def run(
        self,
        train_fn,
        config: dict | None = None,
        dataset_shards_per_rank: list | None = None,
        on_report=None,
        checkpoint: Checkpoint | None = None,
    ) -> list[dict]:
        """Run the loop on all workers until completion; returns final
        reports per rank. Restarts the gang on failure (whole-group restart
        from the latest checkpoint)."""
        failures_left = self.max_failures
        latest_checkpoint = checkpoint
        while True:
            try:
                return self._run_once(
                    train_fn, config, dataset_shards_per_rank, on_report, latest_checkpoint
                )
            except _WorkerGroupError as e:
                if failures_left == 0:
                    raise TrainingFailedError(str(e)) from None
                failures_left -= 1 if failures_left > 0 else 0
                latest_checkpoint = e.latest_checkpoint or latest_checkpoint
                logger.warning(
                    "worker group failed (%s); restarting from %s",
                    e,
                    "checkpoint" if latest_checkpoint else "scratch",
                )
                self.worker_group.shutdown()
                self.start()

    def _run_once(self, train_fn, config, shards_per_rank, on_report, checkpoint):
        wg = self.worker_group
        final_reports: list[dict] = [{} for _ in wg.workers]
        done = [False] * len(wg.workers)
        latest_checkpoint = None
        refs = []
        for rank, worker in enumerate(wg.workers):
            shards = shards_per_rank[rank] if shards_per_rank else None
            refs.append(
                worker.run_train_fn.remote(train_fn, config or {}, shards, checkpoint)
            )
        try:
            ray_tpu.get(refs, timeout=600)
        except ray_tpu.exceptions.RayTpuError as e:
            raise _WorkerGroupError(str(e), None) from None
        while not all(done):
            time.sleep(0.1)
            polls = []
            try:
                polls = ray_tpu.get(
                    [w.poll.remote() for w in wg.workers], timeout=60
                )
            except ray_tpu.exceptions.RayTpuError as e:
                raise _WorkerGroupError(str(e), latest_checkpoint) from None
            for rank, p in enumerate(polls):
                for metrics, ckpt_blob in p["reports"]:
                    final_reports[rank] = metrics
                    ckpt = Checkpoint.from_bytes(ckpt_blob) if ckpt_blob else None
                    if rank == 0 and ckpt is not None:
                        latest_checkpoint = ckpt
                    if rank == 0 and on_report is not None:
                        on_report(metrics, ckpt)
                if p["error"]:
                    raise _WorkerGroupError(
                        f"rank {rank} failed: {p['error']}", latest_checkpoint
                    )
                done[rank] = p["done"]
        return final_reports

    def shutdown(self):
        if self.worker_group is not None:
            self.worker_group.shutdown()
        if self._pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:
                pass


class TrainingFailedError(RuntimeError):
    """Analog of the reference's TrainingFailedError."""


class _WorkerGroupError(RuntimeError):
    def __init__(self, msg: str, latest_checkpoint=None):
        super().__init__(msg)
        self.latest_checkpoint = latest_checkpoint
