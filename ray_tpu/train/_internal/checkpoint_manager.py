"""Driver-side checkpoint retention (analog of the reference's
CheckpointManager, python/ray/train/_internal/checkpoint.py:41 +
air._internal.checkpoint_manager:251): persists rank-0 checkpoints under the
run directory with top-K retention scored by a metric."""

from __future__ import annotations

import os
import shutil
from dataclasses import dataclass

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import CheckpointConfig


@dataclass
class _Tracked:
    path: str
    score: float | None
    index: int


class CheckpointManager:
    def __init__(self, run_dir: str, config: CheckpointConfig | None = None):
        self.run_dir = run_dir
        self.config = config or CheckpointConfig()
        self._tracked: list[_Tracked] = []
        self._index = 0
        os.makedirs(run_dir, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: dict) -> str:
        path = os.path.join(self.run_dir, f"checkpoint_{self._index:06d}")
        checkpoint.to_directory(path)
        attr = self.config.checkpoint_score_attribute
        score = float(metrics[attr]) if attr and attr in metrics else None
        self._tracked.append(_Tracked(path, score, self._index))
        self._index += 1
        self._enforce_retention()
        return path

    def _enforce_retention(self):
        keep = self.config.num_to_keep
        if keep is None or len(self._tracked) <= keep:
            return
        attr = self.config.checkpoint_score_attribute
        if attr:
            reverse = self.config.checkpoint_score_order == "max"
            ordered = sorted(
                self._tracked,
                key=lambda t: (t.score if t.score is not None else float("-inf")),
                reverse=reverse,
            )
        else:
            ordered = sorted(self._tracked, key=lambda t: t.index, reverse=True)
        for victim in ordered[keep:]:
            shutil.rmtree(victim.path, ignore_errors=True)
            self._tracked.remove(victim)

    @property
    def latest(self) -> Checkpoint | None:
        if not self._tracked:
            return None
        newest = max(self._tracked, key=lambda t: t.index)
        return Checkpoint.from_directory(newest.path)

    @property
    def best(self) -> Checkpoint | None:
        attr = self.config.checkpoint_score_attribute
        scored = [t for t in self._tracked if t.score is not None]
        if not attr or not scored:
            return self.latest
        reverse = self.config.checkpoint_score_order == "max"
        best = sorted(scored, key=lambda t: t.score, reverse=reverse)[0]
        return Checkpoint.from_directory(best.path)
