"""WorkerGroup — actor fan-out for distributed training.

Analog of the reference's WorkerGroup (python/ray/train/_internal/worker_group.py:100,
execute/execute_async :260/:233): spawns N TrainWorker actors (optionally under
a placement group so TPU gangs land on one ICI domain), runs functions on all
of them, polls session reports.
"""

from __future__ import annotations

import queue
import threading

import ray_tpu
from ray_tpu.air import session as air_session


@ray_tpu.remote
class TrainWorker:
    """One training worker process (actor). Hosts the user train loop in a
    thread, with an air session bound to it."""

    def __init__(self, rank: int, world_size: int, env: dict | None = None):
        import os

        self.rank = rank
        self.world_size = world_size
        for k, v in (env or {}).items():
            os.environ[k] = str(v)
        self._report_q: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None
        self._error = None
        self._done = False
        self._mesh = None

    def init_collective(self, world, rank, backend, group_name):
        from ray_tpu.util import collective as col

        group = col.init_collective_group(world, rank, backend=backend, group_name=group_name)
        self._mesh = getattr(group, "mesh", None)
        return rank

    def build_local_mesh(self):
        """Single-worker path: mesh over this process's local devices."""
        from ray_tpu.parallel.mesh import single_axis_mesh

        self._mesh = single_axis_mesh("dp")
        return True

    def run_train_fn(self, fn, config, dataset_shards=None, checkpoint=None):
        """Start the user loop in a thread; returns immediately."""
        ctx = air_session.TrainContext(
            world_rank=self.rank,
            world_size=self.world_size,
            local_rank=self.rank,
            config=config or {},
            dataset_shards=dataset_shards or {},
            report_queue=self._report_q,
            checkpoint=checkpoint,
            mesh=self._mesh,
        )

        def runner():
            air_session._set_context(ctx)
            try:
                fn(config) if _wants_config(fn) else fn()
            except BaseException as e:  # noqa: BLE001 — surfaced via poll()
                import traceback

                self._error = f"{e!r}\n{traceback.format_exc()}"
            finally:
                self._done = True

        self._done = False
        self._error = None
        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()
        return True

    def poll(self):
        """Drain queued reports; returns (reports, done, error)."""
        reports = []
        while True:
            try:
                metrics, ckpt = self._report_q.get_nowait()
                blob = ckpt.to_bytes() if ckpt is not None else None
                reports.append((metrics, blob))
            except queue.Empty:
                break
        return {"reports": reports, "done": self._done, "error": self._error}

    def execute(self, fn, *args, **kwargs):
        """Run an arbitrary function in the worker (reference: execute)."""
        return fn(*args, **kwargs)

    def shutdown(self):
        return True


def _wants_config(fn) -> bool:
    import inspect

    try:
        return len(inspect.signature(fn).parameters) >= 1
    except (TypeError, ValueError):
        return False


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: dict | None = None,
        placement_group=None,
        env: dict | None = None,
    ):
        self.num_workers = num_workers
        opts = {}
        self.workers = []
        for rank in range(num_workers):
            actor_cls = TrainWorker
            if resources_per_worker:
                opts["resources"] = dict(resources_per_worker)
            if placement_group is not None:
                from ray_tpu.util.scheduling_strategies import (
                    PlacementGroupSchedulingStrategy,
                )

                opts["scheduling_strategy"] = PlacementGroupSchedulingStrategy(
                    placement_group, rank
                )
            self.workers.append(actor_cls.options(**opts).remote(rank, num_workers, env))

    @classmethod
    def from_handles(cls, workers: list) -> "WorkerGroup":
        """Wrap pre-created TrainWorker handles (the BackendExecutor creates
        the gang through the AIR execution layer's ActorManager; this class
        stays the fan-out/execute surface the Backend plugins see)."""
        group = cls.__new__(cls)
        group.workers = list(workers)
        group.num_workers = len(group.workers)
        return group

    def execute(self, fn, *args, timeout: float | None = 300, **kwargs):
        """Run fn on every worker; returns per-rank results."""
        refs = [w.execute.remote(fn, *args, **kwargs) for w in self.workers]
        return ray_tpu.get(refs, timeout=timeout)

    def execute_single(self, rank: int, fn, *args, **kwargs):
        return ray_tpu.get(self.workers[rank].execute.remote(fn, *args, **kwargs), timeout=300)

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
