"""Sharded model checkpointing for JAX training (orbax-backed).

The TPU-native essential the dict-based ``air.Checkpoint`` doesn't cover:
multi-host sharded params saved WITHOUT gathering to one host, and restored
onto an arbitrary (possibly different) mesh/sharding layout — job resumes
after resizes, and inference loads a training checkpoint under its own tp
layout. (Reference Train checkpoints torch state dicts; its JAX story
delegates to user code — SURVEY.md §2.4.)

- ``save_sharded(path, tree)`` — orbax PyTree save; each host writes only
  its own shards (OCDBT format), safe to call from every process of a
  ``jax.distributed`` world.
- ``restore_sharded(path, like=...)`` — restore placed per ``like``'s
  shardings (a pytree of jax.ShapeDtypeStruct with ``sharding`` set, or of
  concrete arrays whose layout to mirror). With ``like=None`` restores with
  the layout recorded at save time.
- ``TrainCheckpointer`` — step-numbered checkpoint dirs with retention
  (keep the newest K), the shape train loops want.
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any, Optional


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def _proc0() -> bool:
    import jax

    try:
        return jax.process_index() == 0
    except Exception:
        return True


def _barrier(tag: str) -> None:
    """Multi-process sync point; no-op single-process. A FAILED barrier in
    a real multi-host world propagates — proceeding unsynchronized would
    let hosts race the filesystem mutations the barrier fences."""
    import jax

    try:
        multi = jax.process_count() > 1
    except Exception:
        return  # distributed runtime not initialized: single-process
    if multi:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ray_tpu_ckpt_{tag}")


def _recover_interrupted_swap(path: str) -> None:
    """A crash between save_sharded's two renames leaves the data at
    ``path + ".old"`` with nothing at ``path`` — finish the swap."""
    old = path + ".old"
    if not os.path.exists(path) and os.path.exists(old) and _proc0():
        os.rename(old, path)


def save_sharded(path: str, tree: Any) -> str:
    """Write a sharded pytree checkpoint at ``path``.

    Overwrite is durable-then-swap: the new checkpoint is fully written to
    a sibling tmp dir (orbax's own finalize is atomic) BEFORE the old one
    is replaced, so a crash mid-save never loses the previous checkpoint.
    Filesystem mutations happen on process 0 only, fenced by barriers, so
    calling from every process of a ``jax.distributed`` world is safe.
    """
    path = os.path.abspath(path)
    tmp = path + ".saving"
    if _proc0():
        _recover_interrupted_swap(path)
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
    _barrier("pre_save")
    _ckptr().save(tmp, tree)  # collective across processes; blocks to finalize
    _barrier("post_save")
    if _proc0():
        old = path + ".old"
        shutil.rmtree(old, ignore_errors=True)
        if os.path.exists(path):
            os.rename(path, old)
        os.rename(tmp, path)
        shutil.rmtree(old, ignore_errors=True)
    _barrier("swapped")
    return path


def restore_sharded(path: str, like: Any = None) -> Any:
    """Load a checkpoint; ``like`` dictates placement.

    ``like`` leaves may be jax.ShapeDtypeStruct (with ``.sharding``) or
    concrete arrays — each restored array lands on that leaf's sharding
    (resharding across a different mesh than save time is supported; the
    transfer happens at read). ``like=None`` restores the saved layout.
    """
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    _recover_interrupted_swap(path)
    if like is None:
        return _ckptr().restore(path)

    def to_restore_args(leaf):
        sharding = getattr(leaf, "sharding", None)
        return ocp.ArrayRestoreArgs(
            sharding=sharding,
            dtype=getattr(leaf, "dtype", None),
        )

    restore_args = jax.tree.map(to_restore_args, like)
    return _ckptr().restore(path, item=like, restore_args=restore_args)


class TrainCheckpointer:
    """Step-numbered sharded checkpoints with top-K retention.

    save(step, tree) -> <dir>/step_<N>; latest_step()/restore(step, like=)
    pick them back up. Retention and "latest" rank by SAVE RECENCY
    (directory mtime), not step number — after a rollback, save(10) with a
    stale step_12 on disk must neither delete itself nor resume from the
    abandoned future step (the reference CheckpointManager's num_to_keep
    semantics are save-order too).
    """

    _STEP_RE = re.compile(r"^step_(\d+)$")

    def __init__(self, directory: str, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.keep = keep
        os.makedirs(self.directory, exist_ok=True)

    def _steps(self) -> list[int]:
        """Steps ordered oldest-save-first (mtime, step as tiebreak)."""
        out = []
        for name in os.listdir(self.directory):
            m = self._STEP_RE.match(name)
            if m:
                full = os.path.join(self.directory, name)
                try:
                    mtime = os.path.getmtime(full)
                except OSError:
                    continue  # reaped concurrently
                out.append((mtime, int(m.group(1))))
        return [step for _, step in sorted(out)]

    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step}")

    def save(self, step: int, tree: Any) -> str:
        path = save_sharded(self._step_dir(step), tree)
        if _proc0():  # retention is a proc-0 filesystem concern
            for old in self._steps()[: -self.keep] if self.keep > 0 else []:
                shutil.rmtree(self._step_dir(old), ignore_errors=True)
        return path

    def latest_step(self) -> Optional[int]:
        steps = self._steps()
        return steps[-1] if steps else None

    def restore(self, step: Optional[int] = None, like: Any = None) -> Any:
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self.directory}")
        return restore_sharded(self._step_dir(step), like=like)
