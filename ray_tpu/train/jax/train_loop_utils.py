"""Per-worker loop helpers (analog of train/torch/train_loop_utils.py's
prepare_model/prepare_data_loader — but TPU-native: "preparing" data means
placing host numpy shards onto the mesh as sharded jax.Arrays)."""

from __future__ import annotations


def shard_batch(batch: dict, mesh, axis: str = "dp"):
    """Host batch dict -> jax.Arrays sharded over the mesh's data axes."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = [a for a in (axis, "fsdp") if mesh.shape.get(a, 1) > 1] or [axis]
    spec = P(tuple(axes))

    def place(x):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return {k: place(v) for k, v in batch.items()}


def prepare_batch(batch: dict, mesh=None):
    """device_put a host batch; sharded if a mesh is available."""
    import jax

    if mesh is None:
        return {k: jax.device_put(v) for k, v in batch.items()}
    return shard_batch(batch, mesh)
