"""JaxTrainer — the TPU-native DataParallelTrainer.

The centrepiece of the BASELINE targets (JaxTrainer MNIST minimum slice;
ResNet-50 DP over TPU workers): replaces the reference's TorchTrainer +
`_TorchBackend` NCCL bootstrap (python/ray/train/torch/{torch_trainer.py,
config.py:113,155}) with the mesh path: the worker gang forms an XLA world
(util/collective tpu backend), `air.session.get_mesh()` hands the loop its
`jax.sharding.Mesh`, and gradient sync is whatever the user's pjit asks for
(psum over 'dp'/'proc' — compiled onto ICI, not a separate comm library).
"""

from __future__ import annotations

from dataclasses import dataclass

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train._internal.backend_executor import JaxBackend
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer


@dataclass
class JaxConfig:
    """Backend options (analog of train/torch/config.py TorchConfig)."""

    collective_backend: str | None = None  # None => tpu on TPU gangs
    group_name: str = "train"

    def backend(self) -> JaxBackend:
        return JaxBackend(self.collective_backend, self.group_name)


class JaxTrainer(DataParallelTrainer):
    def __init__(
        self,
        train_loop_per_worker,
        *,
        train_loop_config: dict | None = None,
        jax_config: JaxConfig | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        datasets: dict | None = None,
        resume_from_checkpoint=None,
    ):
        jax_config = jax_config or JaxConfig()
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend=jax_config.backend(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
        )
