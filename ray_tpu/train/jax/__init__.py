from ray_tpu.train.jax.jax_trainer import JaxConfig, JaxTrainer
from ray_tpu.train.jax.train_loop_utils import prepare_batch, shard_batch

__all__ = ["JaxConfig", "JaxTrainer", "prepare_batch", "shard_batch"]
