from ray_tpu.train.jax.checkpointing import (
    TrainCheckpointer,
    restore_sharded,
    save_sharded,
)
from ray_tpu.train.jax.jax_trainer import JaxConfig, JaxTrainer
from ray_tpu.train.jax.train_loop_utils import prepare_batch, shard_batch

__all__ = [
    "JaxConfig",
    "JaxTrainer",
    "TrainCheckpointer",
    "prepare_batch",
    "restore_sharded",
    "save_sharded",
    "shard_batch",
]
