"""RLTrainer — RLlib algorithms behind the Train API.

Reference: python/ray/train/rl/rl_trainer.py (RLTrainer wraps an RLlib
algorithm as a Trainer so RL drops into the same fit()/Result/checkpoint
workflow as supervised trainers, and rl_predictor.py serves the trained
policy as a Predictor).
"""

from __future__ import annotations

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.base_trainer import BaseTrainer, Result


class RLTrainer(BaseTrainer):
    """``algorithm`` is an Algorithm class (or name, e.g. "PPO");
    ``config`` maps onto its AlgorithmConfig (env included)."""

    def __init__(
        self,
        *,
        algorithm,
        config: dict,
        stop: dict | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        **kwargs,
    ):
        super().__init__(scaling_config=scaling_config, run_config=run_config, **kwargs)
        if isinstance(algorithm, str):
            import ray_tpu.rllib as rllib

            algorithm = getattr(rllib, algorithm.upper(), None) or getattr(rllib, algorithm)
        self.algorithm_cls = algorithm
        self.algo_config = dict(config)
        self.stop = dict(stop or {})
        if run_config is not None and run_config.stop:
            self.stop.update(run_config.stop)

    def _fit_direct(self) -> Result:
        run_dir = self._run_dir()
        algo = self.algorithm_cls(config=self.algo_config)
        last: dict = {}
        history: list[dict] = []
        try:
            max_iters = int(self.stop.get("training_iteration", 100))
            for i in range(max_iters):
                last = algo.step()
                last["training_iteration"] = i + 1
                history.append(dict(last))
                if any(
                    (v := last.get(k)) is not None and v == v and v >= bound
                    for k, bound in self.stop.items()
                ):
                    break
            ckpt = algo.save_checkpoint()
            ckpt.metadata["algorithm"] = self.algorithm_cls.__name__
            result = Result(metrics=last, checkpoint=ckpt, path=run_dir)
        except Exception as e:
            return Result(metrics=last, error=f"{type(e).__name__}: {e}", path=run_dir)
        finally:
            algo.cleanup()
        try:
            import pandas as pd

            result.metrics_dataframe = pd.DataFrame(history)
        except Exception:
            pass
        return result


class RLPredictor:
    """Serve a trained policy from an RLTrainer checkpoint (reference:
    train/rl/rl_predictor.py)."""

    def __init__(self, algorithm_cls, config: dict, checkpoint: Checkpoint):
        self.algo = algorithm_cls(config=config)
        self.algo.load_checkpoint(checkpoint)

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *, algorithm, config: dict) -> "RLPredictor":
        if isinstance(algorithm, str):
            import ray_tpu.rllib as rllib

            algorithm = getattr(rllib, algorithm.upper(), None) or getattr(rllib, algorithm)
        return cls(algorithm, config, checkpoint)

    def predict(self, obs_batch) -> np.ndarray:
        obs_batch = np.asarray(obs_batch)
        return np.asarray([
            self.algo.compute_single_action(obs, explore=False) for obs in obs_batch
        ])

    def close(self):
        self.algo.cleanup()
