"""HuggingFaceTrainer — transformers.Trainer on the distributed gang.

Reference: python/ray/train/huggingface/huggingface_trainer.py: a
DataParallelTrainer (torch backend) whose per-worker loop materialises the
user's `transformers.Trainer` via `trainer_init_per_worker`, bridges HF
logging into session.report, and checkpoints rank-0's model. The torch
process group the backend formed is what HF's Trainer picks up for DDP
(WORLD_SIZE/RANK env vars are already exported by _init_dist).
"""

from __future__ import annotations

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.torch.config import TorchConfig
from ray_tpu.train.torch.torch_trainer import TorchTrainer


class _RowListDataset:
    """torch-map-style dataset over materialised ray_tpu.data rows."""

    def __init__(self, rows: list):
        self.rows = rows

    def __len__(self) -> int:
        return len(self.rows)

    def __getitem__(self, i: int):
        return self.rows[i]


def _to_torch_dataset(shard):
    if shard is None:
        return None
    if hasattr(shard, "take_all"):
        return _RowListDataset(shard.take_all())
    return shard  # already a torch/HF dataset


def _hf_train_loop(config: dict):
    import transformers

    from ray_tpu.air import session

    trainer_init = config["_trainer_init_per_worker"]
    init_config = config.get("_trainer_init_config") or {}
    train_ds = _to_torch_dataset(session.get_dataset_shard("train"))
    eval_ds = _to_torch_dataset(session.get_dataset_shard("evaluation"))
    trainer: transformers.Trainer = trainer_init(train_ds, eval_ds, **init_config)

    class _ReportCallback(transformers.TrainerCallback):
        def on_log(self, args, state, control, logs=None, **kwargs):
            if logs and state.is_world_process_zero:
                metrics = {k: v for k, v in logs.items() if isinstance(v, (int, float))}
                metrics["step"] = state.global_step
                metrics["epoch"] = float(state.epoch or 0)
                session.report(metrics)

    trainer.add_callback(_ReportCallback())
    result = trainer.train()
    final = dict(result.metrics or {})
    if session.get_world_rank() == 0:
        import io

        import torch

        buf = io.BytesIO()
        torch.save(trainer.model.state_dict(), buf)
        ckpt = Checkpoint.from_dict({
            "model_state": buf.getvalue(),
            "config": getattr(getattr(trainer.model, "config", None), "to_dict", dict)(),
        })
        session.report(final, checkpoint=ckpt)
    else:
        session.report(final)


class HuggingFaceTrainer(TorchTrainer):
    """`trainer_init_per_worker(train_dataset, eval_dataset, **config)` must
    return a `transformers.Trainer` (same contract as the reference)."""

    def __init__(
        self,
        trainer_init_per_worker,
        *,
        trainer_init_config: dict | None = None,
        torch_config: TorchConfig | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        datasets: dict | None = None,
        resume_from_checkpoint=None,
    ):
        super().__init__(
            _hf_train_loop,
            train_loop_config={
                "_trainer_init_per_worker": trainer_init_per_worker,
                "_trainer_init_config": trainer_init_config,
            },
            torch_config=torch_config,
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
        )
