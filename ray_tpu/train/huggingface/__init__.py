from ray_tpu.train.huggingface.huggingface_trainer import HuggingFaceTrainer  # noqa: F401
