"""SklearnTrainer + gated GBDT trainers.

Analog of the reference's train/sklearn/sklearn_trainer.py (fit an estimator
remotely on Ray Data) and train/{xgboost,lightgbm} GBDTTrainers. Sklearn fits
are single-process (the library is not distributed); the trainer runs the fit
in a cluster task so the driver stays responsive, materializes the Dataset to
a feature matrix, scores on validation datasets, and returns an AIR
checkpoint holding the fitted estimator (loadable by SklearnPredictor-style
code via Checkpoint.to_dict()["estimator"]).

XGBoostTrainer / LightGBMTrainer are declared but gated: those libraries are
not in this image; constructing them raises with install guidance (reference
behavior when an optional integration is missing).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.train.base_trainer import BaseTrainer, Result


def _to_xy(ds, label_column: str, feature_columns: Optional[list]):
    rows = ds.take_all()
    if not rows:
        raise ValueError("empty dataset")
    cols = feature_columns or [c for c in rows[0] if c != label_column]
    X = np.asarray([[r[c] for c in cols] for r in rows], dtype=np.float64)
    y = np.asarray([r[label_column] for r in rows])
    return X, y, cols


class SklearnTrainer(BaseTrainer):
    def __init__(
        self,
        *,
        estimator,
        label_column: str,
        datasets: dict,
        feature_columns: Optional[list] = None,
        scoring: Optional[str] = None,
        **kwargs,
    ):
        super().__init__(datasets=datasets, **kwargs)
        self.estimator = estimator
        self.label_column = label_column
        self.feature_columns = feature_columns
        self.scoring = scoring

    def _fit_direct(self) -> Result:
        import ray_tpu

        train_ds = self.datasets.get("train")
        if train_ds is None:
            raise ValueError('datasets must include a "train" Dataset')
        X, y, cols = _to_xy(train_ds, self.label_column, self.feature_columns)
        valid_sets = {
            name: _to_xy(ds, self.label_column, cols)[:2]
            for name, ds in self.datasets.items()
            if name != "train"
        }

        @ray_tpu.remote
        def _fit(estimator, X, y, valid_sets, scoring):
            estimator.fit(X, y)
            metrics = {"train_score": float(estimator.score(X, y))}
            if scoring:
                from sklearn import metrics as skm

                scorer = skm.get_scorer(scoring)
                metrics[f"train_{scoring}"] = float(scorer(estimator, X, y))
            for name, (Xv, yv) in valid_sets.items():
                metrics[f"{name}_score"] = float(estimator.score(Xv, yv))
            return estimator, metrics

        run_dir = self._run_dir()
        try:
            # No fit deadline: long estimator fits are legitimate (the
            # reference imposes none either).
            fitted, metrics = ray_tpu.get(
                _fit.remote(self.estimator, X, y, valid_sets, self.scoring)
            )
        except Exception as e:
            return Result(metrics={}, error=str(e), path=run_dir)
        ckpt = Checkpoint.from_dict(
            {"estimator": fitted, "feature_columns": cols, "label_column": self.label_column}
        )
        return Result(metrics=metrics, checkpoint=ckpt, path=run_dir)

    def training_loop(self) -> None:  # Trainable-path entry
        from ray_tpu.air import session

        result = self._fit_direct()
        if result.error:
            # Surface the remote fit failure instead of reporting an empty
            # successful trial.
            raise RuntimeError(f"SklearnTrainer fit failed: {result.error}")
        if session.in_session():
            session.report(dict(result.metrics), checkpoint=result.checkpoint)


def _gated(name: str, package: str):
    class _Gated(BaseTrainer):
        def __init__(self, *a, **k):
            raise ImportError(
                f"{name} requires the '{package}' package, which is not "
                "installed in this environment. Install it on the node image "
                f"(pip install {package}) to use this trainer."
            )

    _Gated.__name__ = name
    return _Gated


# Gated in this environment (no xgboost/lightgbm in the image); a build
# against the real libraries would replace these with full GBDT trainers.
XGBoostTrainer = _gated("XGBoostTrainer", "xgboost")
LightGBMTrainer = _gated("LightGBMTrainer", "lightgbm")
LightningTrainer = _gated("LightningTrainer", "pytorch_lightning")
MosaicTrainer = _gated("MosaicTrainer", "mosaicml")
HorovodTrainer = _gated("HorovodTrainer", "horovod")
TensorflowTrainer = _gated("TensorflowTrainer", "tensorflow")
