"""TorchTrainer — data-parallel torch training on the actor gang.

Reference: python/ray/train/torch/torch_trainer.py (TorchTrainer is
DataParallelTrainer + _TorchBackend). The train loop runs per worker with a
torch.distributed gloo group already formed; `prepare_model` /
`prepare_data_loader` (train_loop_utils) wrap DDP and DistributedSampler.
"""

from __future__ import annotations

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer
from ray_tpu.train.torch.config import TorchConfig


class TorchTrainer(DataParallelTrainer):
    def __init__(
        self,
        train_loop_per_worker,
        *,
        train_loop_config: dict | None = None,
        torch_config: TorchConfig | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        datasets: dict | None = None,
        resume_from_checkpoint=None,
    ):
        torch_config = torch_config or TorchConfig()
        super().__init__(
            train_loop_per_worker,
            train_loop_config=train_loop_config,
            backend=torch_config.backend_cls(),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
        )
