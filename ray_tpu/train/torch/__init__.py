from ray_tpu.train.torch.config import TorchConfig  # noqa: F401
from ray_tpu.train.torch.torch_trainer import TorchTrainer  # noqa: F401
from ray_tpu.train.torch.train_loop_utils import (  # noqa: F401
    get_device,
    prepare_data_loader,
    prepare_model,
)
