"""Per-worker torch conveniences (reference: train/torch/train_loop_utils.py
prepare_model / prepare_data_loader / prepare_optimizer)."""

from __future__ import annotations

from ray_tpu.air import session


def get_device():
    """CPU in this image (torch CPU wheel); kept for API parity."""
    import torch

    return torch.device("cpu")


def prepare_model(model, *, ddp_kwargs: dict | None = None):
    """Wrap in DistributedDataParallel when a process group is live
    (reference: prepare_model, minus GPU move/amp)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel

    if dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1:
        return DistributedDataParallel(model, **(ddp_kwargs or {}))
    return model


def prepare_data_loader(data_loader, *, add_dist_sampler: bool = True):
    """Re-create the DataLoader with a DistributedSampler so each rank sees
    its shard (reference: prepare_data_loader)."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader, SequentialSampler
    from torch.utils.data.distributed import DistributedSampler

    if not (dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1):
        return data_loader
    if not add_dist_sampler or isinstance(data_loader.sampler, DistributedSampler):
        return data_loader
    sampler = DistributedSampler(
        data_loader.dataset,
        num_replicas=dist.get_world_size(),
        rank=dist.get_rank(),
        shuffle=not isinstance(data_loader.sampler, SequentialSampler),
    )
    return DataLoader(
        data_loader.dataset,
        batch_size=data_loader.batch_size,
        sampler=sampler,
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        pin_memory=data_loader.pin_memory,
        drop_last=data_loader.drop_last,
    )


def accelerate_ready() -> bool:
    """True when HF accelerate can form its state from the env vars the
    torch backend exported (reference: AccelerateTrainer's premise)."""
    try:
        import accelerate  # noqa: F401

        return True
    except ImportError:
        return False


def report(metrics: dict, checkpoint=None) -> None:
    """Alias for air.session.report, for torch loops written against the
    reference's `ray.train.report`."""
    session.report(metrics, checkpoint=checkpoint)
