"""TorchConfig + _TorchBackend — torch.distributed bootstrap over the gang.

Reference: python/ray/train/torch/config.py:155 (_TorchBackend.on_start calls
dist.init_process_group on every worker, :113, with worker-0 as master).
TPU-era note: torch here is the CPU wheel — this backend exists for parity
with the reference's torch training path (data loaders, sklearn-style torch
models, HF Trainer); accelerator compute belongs to the Jax path.
"""

from __future__ import annotations

from dataclasses import dataclass

import ray_tpu
from ray_tpu.train._internal.backend_executor import Backend


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _init_dist(rank: int, world_size: int, master_addr: str, master_port: int,
               backend: str, timeout_s: int):
    import datetime
    import os

    import torch.distributed as dist

    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    os.environ["RANK"] = str(rank)
    os.environ["WORLD_SIZE"] = str(world_size)
    os.environ.setdefault("LOCAL_RANK", str(rank))
    if not dist.is_initialized():
        dist.init_process_group(
            backend=backend,
            rank=rank,
            world_size=world_size,
            timeout=datetime.timedelta(seconds=timeout_s),
        )
    return dist.get_rank()


def _shutdown_dist():
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()
    return True


@dataclass
class TorchConfig:
    """Analog of train/torch/config.py TorchConfig."""

    backend: str = "gloo"  # CPU wheel: gloo; the reference defaults nccl on GPU
    init_timeout_s: int = 300

    def backend_cls(self) -> "_TorchBackend":
        return _TorchBackend(self)


class _TorchBackend(Backend):
    def __init__(self, config: TorchConfig | None = None):
        self.config = config or TorchConfig()

    def on_start(self, worker_group, scaling_config):
        if worker_group.num_workers == 1:
            return  # single worker: no process group needed
        # Worker 0 is the rendezvous master (same scheme as the collective
        # plane's coordinator; single-host address like tpu_group.py).
        master_port = ray_tpu.get(
            worker_group.workers[0].execute.remote(_free_port), timeout=60
        )
        refs = [
            w.execute.remote(
                _init_dist, rank, worker_group.num_workers, "127.0.0.1",
                master_port, self.config.backend, self.config.init_timeout_s,
            )
            for rank, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(refs, timeout=self.config.init_timeout_s + 60)

    def on_shutdown(self, worker_group):
        try:
            worker_group.execute(_shutdown_dist, timeout=30)
        except Exception:
            pass
