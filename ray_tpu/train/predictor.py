"""Predictors — checkpoint → inference callable.

Analog of the reference's ray.train.predictor.Predictor +
batch_predictor.BatchPredictor (python/ray/train/predictor.py,
batch_predictor.py): a Predictor wraps a checkpoint (+ optional fitted
preprocessor) and maps batches to predictions; BatchPredictor scales one over
a Dataset with an actor pool so jit-compiled models stay resident per actor.

TPU-first: JaxPredictor holds params as a device-resident pytree and a jitted
apply function — one compile per actor process, then every batch is a pure
device call.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    def __init__(self, preprocessor=None):
        self._preprocessor = preprocessor

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def get_preprocessor(self):
        return self._preprocessor

    def predict(self, batch: dict) -> dict:
        if self._preprocessor is not None:
            batch = self._preprocessor.transform_batch(batch)
        return self._predict(batch)

    def _predict(self, batch: dict) -> dict:
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Predictor over a jitted apply fn + params pytree.

    ``apply_fn(params, inputs) -> outputs``; inputs are taken from
    ``input_column`` (default: the whole batch if it has one column).
    """

    def __init__(
        self,
        params,
        apply_fn: Callable,
        preprocessor=None,
        input_column: Optional[str] = None,
    ):
        super().__init__(preprocessor)
        import jax

        self.params = params
        self.apply_fn = jax.jit(apply_fn)
        self.input_column = input_column

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint: Checkpoint,
        apply_fn: Callable | None = None,
        input_column: Optional[str] = None,
    ) -> "JaxPredictor":
        data = checkpoint.to_dict()
        params = data.get("params", data.get("pytree"))
        if params is None:
            raise ValueError("checkpoint has no 'params' (or 'pytree') entry")
        fn = apply_fn or data.get("apply_fn")
        if fn is None:
            raise ValueError("pass apply_fn= or store one in the checkpoint")
        return cls(params, fn, preprocessor=data.get("preprocessor"), input_column=input_column)

    def _predict(self, batch: dict) -> dict:
        import jax.numpy as jnp

        if self.input_column is not None:
            inputs = jnp.asarray(batch[self.input_column])
        elif len(batch) == 1:
            inputs = jnp.asarray(next(iter(batch.values())))
        else:
            raise ValueError(
                f"batch has columns {sorted(batch)}; pass input_column= to pick one"
            )
        out = self.apply_fn(self.params, inputs)
        return {"predictions": np.asarray(out)}


class BatchPredictor:
    """Scale a Predictor over a Dataset (reference: batch_predictor.py).

    One predictor instance per map actor: the checkpoint is deserialized and
    the model jitted once per actor, then reused across batches.
    """

    def __init__(self, checkpoint: Checkpoint, predictor_cls, **predictor_kwargs):
        self.checkpoint = checkpoint
        self.predictor_cls = predictor_cls
        self.predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, predictor_cls, **kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **kwargs)

    def predict(
        self,
        ds,
        *,
        batch_size: int = 4096,
        min_scoring_workers: int = 1,
        max_scoring_workers: int = 2,
        num_tpus_per_worker: int = 0,
        keep_columns: Optional[list] = None,
    ):
        from ray_tpu.data import ActorPoolStrategy

        checkpoint_blob = self.checkpoint.to_bytes()
        predictor_cls = self.predictor_cls
        predictor_kwargs = self.predictor_kwargs

        class ScoringActor:
            def __init__(self):
                self.predictor = predictor_cls.from_checkpoint(
                    Checkpoint.from_bytes(checkpoint_blob), **predictor_kwargs
                )

            def __call__(self, batch: dict) -> dict:
                out = self.predictor.predict(dict(batch))
                for col in keep_columns or []:
                    out[col] = batch[col]
                return out

        return ds.map_batches(
            ScoringActor,
            batch_size=batch_size,
            # Actor-pool resources come from the strategy, not ray_remote_args.
            compute=ActorPoolStrategy(
                min_size=min_scoring_workers,
                max_size=max_scoring_workers,
                num_tpus=num_tpus_per_worker,
            ),
        )
