"""DataParallelTrainer (analog of python/ray/train/data_parallel_trainer.py:58,
training_loop :422): N workers run ``train_loop_per_worker`` with an air
session; the backend plugin forms the collective plane."""

from __future__ import annotations

import logging

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train._internal.backend_executor import Backend, BackendExecutor, JaxBackend
from ray_tpu.train._internal.checkpoint_manager import CheckpointManager
from ray_tpu.train.base_trainer import BaseTrainer, Result

logger = logging.getLogger(__name__)


class DataParallelTrainer(BaseTrainer):
    _backend_cls = Backend

    def __init__(
        self,
        train_loop_per_worker,
        *,
        train_loop_config: dict | None = None,
        backend: Backend | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        datasets: dict | None = None,
        resume_from_checkpoint=None,
    ):
        super().__init__(
            scaling_config=scaling_config,
            run_config=run_config,
            resume_from_checkpoint=resume_from_checkpoint,
            datasets=datasets,
        )
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend = backend or self._backend_cls()

    def _shards_per_rank(self):
        """Split datasets into per-rank shards (reference: DataConfig /
        get_dataset_shard; SURVEY.md §2.6 ingest bridge)."""
        n = self.scaling_config.num_workers
        if not self.datasets:
            return None
        per_rank = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "split"):
                shards = ds.split(n)
                for rank in range(n):
                    per_rank[rank][name] = shards[rank]
            else:
                for rank in range(n):
                    per_rank[rank][name] = ds
        return per_rank

    def _fit_direct(self) -> Result:
        run_dir = self._run_dir()
        ckpt_mgr = CheckpointManager(run_dir, self.run_config.checkpoint_config)
        executor = BackendExecutor(
            self.backend,
            self.scaling_config,
            max_failures=self.run_config.failure_config.max_failures,
        )
        executor.start()
        last_metrics: dict = {}
        history: list[dict] = []

        def on_report(metrics, checkpoint):
            nonlocal last_metrics
            last_metrics = metrics
            history.append(metrics)
            if checkpoint is not None:
                ckpt_mgr.register(checkpoint, metrics)

        try:
            final = executor.run(
                self.train_loop_per_worker,
                config=self.train_loop_config,
                dataset_shards_per_rank=self._shards_per_rank(),
                on_report=on_report,
                checkpoint=self.resume_from_checkpoint,
            )
            metrics = final[0] or last_metrics
            result = Result(metrics=metrics, checkpoint=ckpt_mgr.latest, path=run_dir)
        except Exception as e:
            result = Result(metrics=last_metrics, checkpoint=ckpt_mgr.latest, error=str(e), path=run_dir)
            raise
        finally:
            executor.shutdown()
        try:
            import pandas as pd

            result.metrics_dataframe = pd.DataFrame(history)
        except Exception:
            pass
        return result
