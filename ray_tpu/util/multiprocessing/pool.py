"""multiprocessing.Pool API over tasks (reference: python/ray/util/
multiprocessing/pool.py — Pool class, chunking in _map_async)."""

from __future__ import annotations

import itertools

import ray_tpu


class TimeoutError(Exception):
    pass


def _run_chunk(fn, chunk, star: bool, initializer=None, initargs=()):
    if initializer is not None:
        initializer(*initargs)  # once per chunk (tasks are stateless)
    if star:
        return [fn(*item) for item in chunk]
    return [fn(item) for item in chunk]


_run_chunk_remote = ray_tpu.remote(_run_chunk)


class AsyncResult:
    def __init__(self, chunk_refs: list, single: bool = False):
        self._chunk_refs = chunk_refs
        self._single = single

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(list(self._chunk_refs), num_returns=len(self._chunk_refs), timeout=0)
        return len(ready) == len(self._chunk_refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            self.get(timeout=0.001)
            return True
        except Exception:
            return False

    def wait(self, timeout: float | None = None):
        ray_tpu.wait(list(self._chunk_refs), num_returns=len(self._chunk_refs), timeout=timeout)

    def get(self, timeout: float | None = None):
        ready, not_ready = ray_tpu.wait(
            list(self._chunk_refs), num_returns=len(self._chunk_refs), timeout=timeout
        )
        if not_ready:
            raise TimeoutError(f"{len(not_ready)} chunks still pending")
        out = list(itertools.chain.from_iterable(ray_tpu.get(self._chunk_refs)))
        if self._single:
            return out[0]
        return out


class Pool:
    """A task-backed process pool. ``processes`` bounds in-flight chunks."""

    def __init__(self, processes: int | None = None, initializer=None, initargs=(), ray_remote_args: dict | None = None):
        self._initializer, self._initargs = initializer, initargs
        self._processes = processes or 8
        self._remote_args = ray_remote_args or {}
        self._closed = False

    def _chunks(self, iterable, chunksize: int | None):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i : i + chunksize] for i in range(0, len(items), chunksize)], len(items)

    def _submit_chunks(self, fn, chunks, star: bool):
        if self._closed:
            raise ValueError("Pool is closed")
        task = _run_chunk_remote.options(**self._remote_args) if self._remote_args else _run_chunk_remote
        return [
            task.remote(fn, chunk, star, self._initializer, self._initargs) for chunk in chunks
        ]

    # -- apply -------------------------------------------------------------
    def apply(self, fn, args=(), kwds=None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args=(), kwds=None):
        kwds = kwds or {}
        refs = self._submit_chunks(lambda: fn(*args, **kwds), [[()]], star=True)
        return AsyncResult(refs, single=True)

    # -- map ---------------------------------------------------------------
    def map(self, fn, iterable, chunksize: int | None = None):
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize: int | None = None):
        chunks, _ = self._chunks(iterable, chunksize)
        return AsyncResult(self._submit_chunks(fn, chunks, star=False))

    def starmap(self, fn, iterable, chunksize: int | None = None):
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn, iterable, chunksize: int | None = None):
        chunks, _ = self._chunks(iterable, chunksize)
        return AsyncResult(self._submit_chunks(fn, chunks, star=True))

    def imap(self, fn, iterable, chunksize: int | None = None):
        chunks, _ = self._chunks(iterable, chunksize)
        refs = self._submit_chunks(fn, chunks, star=False)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn, iterable, chunksize: int | None = None):
        chunks, _ = self._chunks(iterable, chunksize)
        pending = self._submit_chunks(fn, chunks, star=False)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(ready[0])

    # -- lifecycle ---------------------------------------------------------
    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still open")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
