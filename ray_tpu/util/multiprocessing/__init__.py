"""Drop-in multiprocessing.Pool on the distributed runtime.

Analog of the reference's ray.util.multiprocessing (python/ray/util/
multiprocessing/pool.py): ``Pool`` schedules chunks of work as tasks, so a
pool "process" is any worker in the cluster. Supports apply/apply_async,
map/map_async, imap/imap_unordered, starmap.
"""

from ray_tpu.util.multiprocessing.pool import AsyncResult, Pool, TimeoutError  # noqa: F401

__all__ = ["Pool", "AsyncResult", "TimeoutError"]
