"""User-defined application metrics.

TPU-native analog of the reference's ``ray.util.metrics``
(python/ray/util/metrics.py Counter/Gauge/Histogram) plus the per-node
metrics-agent export path (_private/metrics_agent.py:46 →
prometheus_exporter.py): metric instruments register in a process-local
registry; a background thread pushes snapshots into the GCS KV under
``metrics:<worker_id>``; ``prometheus_text()`` aggregates every process's
snapshot into the Prometheus text exposition format (served by the dashboard
at ``/metrics``).
"""

from __future__ import annotations

import json
import re
import threading
import time

_REGISTRY_LOCK = threading.Lock()
_REGISTRY: dict[str, "Metric"] = {}
_FLUSHER: threading.Thread | None = None
# Collector hooks: called right before every flush/snapshot so cheap plain-int
# hot-path counters (e.g. rpc.WIRE wire stats) can be folded into instruments
# at flush frequency instead of paying instrument-lock costs per frame.
_COLLECTORS: list = []
# Fallback flush target for processes with no CoreWorker (a standalone
# raylet): (gcs_client, node_id, entity_id).
_FALLBACK_TARGET: tuple | None = None


def _tag_key(tags: dict | None) -> tuple:
    return tuple(sorted((tags or {}).items()))


class Metric:
    """Base instrument. Values are kept per tag-set."""

    kind = "untyped"

    def __init__(self, name: str, description: str = "", tag_keys: tuple = ()):
        # Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]* — one bad name
        # would make the whole exposition body unparseable to scrapers.
        if not re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name or ""):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}
        with _REGISTRY_LOCK:
            existing = _REGISTRY.get(name)
            if existing is not None:
                if existing.kind != self.kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {existing.kind}, "
                        f"cannot re-register as {self.kind}"
                    )
                # Same name re-registered (e.g. two actors in one worker):
                # share storage so updates through either instrument export.
                self._share_from(existing)
            _REGISTRY[name] = self
        _ensure_flusher()

    def _share_from(self, existing: "Metric"):
        self._lock = existing._lock
        self._values = existing._values

    def set_default_tags(self, tags: dict):
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags: dict | None) -> dict:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(f"unknown tag keys {extra} for metric {self.name}")
        return merged

    def _snapshot(self) -> dict:
        with self._lock:
            return {
                "kind": self.kind,
                "description": self.description,
                "values": [[list(k), v] for k, v in self._values.items()],
            }


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: dict | None = None):
        if value < 0:
            raise ValueError("Counter.inc() requires value >= 0")
        key = _tag_key(self._merged(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: dict | None = None):
        key = _tag_key(self._merged(tags))
        with self._lock:
            self._values[key] = float(value)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, description="", boundaries=None, tag_keys=()):
        # Histogram storage must exist before super().__init__ publishes this
        # instrument to the registry — a concurrent flush would otherwise
        # snapshot a half-constructed object.
        self.boundaries = sorted(boundaries or [0.01, 0.1, 1, 10, 100])
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}
        self._totals: dict[tuple, int] = {}
        super().__init__(name, description, tag_keys)

    def _share_from(self, existing: "Histogram"):
        if self.boundaries != existing.boundaries:
            raise ValueError(
                f"histogram {self.name!r} already registered with boundaries "
                f"{existing.boundaries}, cannot re-register with {self.boundaries}"
            )
        super()._share_from(existing)
        self._counts = existing._counts
        self._sums = existing._sums
        self._totals = existing._totals

    def observe(self, value: float, tags: dict | None = None):
        key = _tag_key(self._merged(tags))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.boundaries) + 1))
            idx = len(self.boundaries)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    idx = i
                    break
            counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def _snapshot(self) -> dict:
        with self._lock:
            return {
                "kind": self.kind,
                "description": self.description,
                "boundaries": self.boundaries,
                "hist": [
                    [list(k), self._counts[k], self._sums.get(k, 0.0), self._totals.get(k, 0)]
                    for k in self._counts
                ],
            }


def register_collector(fn) -> None:
    """Register a zero-arg hook invoked before every flush/snapshot. Lets
    hot paths keep plain-int counters (no instrument lock per event) that a
    collector folds into Counters/Gauges at flush cadence."""
    with _REGISTRY_LOCK:
        if fn not in _COLLECTORS:
            _COLLECTORS.append(fn)


def set_fallback_flush_target(gcs_client, node_id: str, entity_id: str) -> None:
    """Flush destination for processes that never build a CoreWorker (a
    standalone raylet): snapshots land under ``metrics:<entity_id>`` exactly
    like worker snapshots."""
    global _FALLBACK_TARGET
    _FALLBACK_TARGET = (gcs_client, node_id, entity_id)


def _run_collectors():
    for fn in list(_COLLECTORS):
        try:
            fn()
        except Exception:
            pass


def _ensure_flusher():
    global _FLUSHER
    with _REGISTRY_LOCK:
        if _FLUSHER is not None:
            return
        _FLUSHER = threading.Thread(target=_flush_loop, name="metrics-flush", daemon=True)
        _FLUSHER.start()
    import atexit

    # A short-lived worker's final window must not vanish: the periodic
    # flusher only pushes every metrics_flush_interval_s, so a process that
    # exits mid-window would lose everything it recorded since the last tick.
    atexit.register(_flush_at_exit)


def _flush_at_exit():
    try:
        flush_metrics()
    except Exception:
        pass


def _flush_loop():
    from ray_tpu._private.config import get_config

    first = True
    while True:
        # Re-read each tick: init_config() may replace the Config after the
        # first Metric (and thus this thread) was created. The FIRST flush
        # runs within ~1s of registration — a worker that lives less than a
        # full interval otherwise never exports anything.
        interval = get_config().metrics_flush_interval_s
        time.sleep(min(1.0, interval) if first else interval)
        first = False
        try:
            flush_metrics()
        except Exception:
            pass


def flush_metrics(core_worker=None):
    """Push this process's metric snapshots into the GCS KV (used by tests and
    the background flusher). Falls back to the target registered via
    set_fallback_flush_target when no CoreWorker exists; no-op when neither
    is available."""
    from ray_tpu._private import worker_context

    cw = core_worker or worker_context.get_core_worker_if_initialized()
    if cw is not None:
        gcs, node_id, entity = cw.gcs, cw.node_id, cw.worker_id
    elif _FALLBACK_TARGET is not None:
        gcs, node_id, entity = _FALLBACK_TARGET
    else:
        return
    _run_collectors()
    with _REGISTRY_LOCK:
        snap = {name: m._snapshot() for name, m in _REGISTRY.items()}
    if not snap:
        return
    payload = json.dumps(
        {"ts": time.time(), "node_id": node_id, "metrics": snap}
    ).encode()
    gcs.call(
        "kv_put",
        {"key": f"metrics:{entity}", "value": payload, "overwrite": True},
    )


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def prometheus_text(gcs_client, stale_after_s: float = 60.0) -> str:
    """Aggregate all processes' snapshots from the GCS KV into Prometheus text
    exposition format."""
    keys = gcs_client.call("kv_keys", {"prefix": "metrics:"}).get("keys", [])
    now = time.time()
    merged: dict[str, dict] = {}
    for key in keys:
        resp = gcs_client.call("kv_get", {"key": key})
        if not resp.get("found"):
            continue
        try:
            snap = json.loads(resp["value"])
        except Exception:
            continue
        if now - snap.get("ts", 0) > stale_after_s:
            continue
        wid = key.split(":", 1)[1][:8]
        for name, m in snap.get("metrics", {}).items():
            entry = merged.setdefault(name, {"kind": m["kind"], "description": m.get("description", ""), "series": []})
            base_tags = [("WorkerId", wid), ("NodeId", snap.get("node_id", "")[:8])]
            if m["kind"] == "histogram":
                for tags, counts, total_sum, total in m.get("hist", []):
                    entry["series"].append((base_tags + tags, {"counts": counts, "sum": total_sum, "count": total, "boundaries": m["boundaries"]}))
            else:
                for tags, value in m.get("values", []):
                    entry["series"].append((base_tags + tags, value))
    lines = []
    for name, entry in sorted(merged.items()):
        kind = entry["kind"]
        desc = entry["description"].replace("\\", "\\\\").replace("\n", "\\n")
        lines.append(f"# HELP {name} {desc}")
        lines.append(f"# TYPE {name} {kind}")
        for tags, value in entry["series"]:
            label = ",".join(f'{k}="{_escape(str(v))}"' for k, v in tags)
            if kind == "histogram":
                cumulative = 0
                for i, b in enumerate(value["boundaries"]):
                    cumulative += value["counts"][i]
                    le = f'le="{b}"'
                    lab = ",".join(x for x in (label, le) if x)
                    lines.append(f"{name}_bucket{{{lab}}} {cumulative}")
                lab = ",".join(x for x in (label, 'le="+Inf"') if x)
                lines.append(f"{name}_bucket{{{lab}}} {value['count']}")
                lines.append(f"{name}_sum{{{label}}} {value['sum']}")
                lines.append(f"{name}_count{{{label}}} {value['count']}")
            else:
                lines.append(f"{name}{{{label}}} {value}")
    lines.extend(_node_gauge_lines(gcs_client))
    return "\n".join(lines) + "\n"


def _node_gauge_lines(gcs_client) -> list[str]:
    """Synthesize ``ray_tpu_node_*`` gauges from the dashboard agent's node
    samples (GCS node table ``stats``) — host CPU/memory and per-worker RSS
    were previously reachable only via ``/api/cluster_status``."""
    try:
        nodes = gcs_client.call("get_nodes").get("nodes", {})
    except Exception:
        return []
    host_gauges = [
        ("ray_tpu_node_cpu_percent", "cpu_percent", "Host CPU utilization percent."),
        ("ray_tpu_node_mem_used_bytes", "mem_used", "Host memory used in bytes."),
        ("ray_tpu_node_mem_total_bytes", "mem_total", "Host memory total in bytes."),
        ("ray_tpu_node_disk_used_bytes", "disk_used", "Session-dir disk used in bytes."),
        ("ray_tpu_node_disk_total_bytes", "disk_total", "Session-dir disk total in bytes."),
    ]
    lines: list[str] = []
    for metric, key, help_text in host_gauges:
        samples = []
        for nid, node in nodes.items():
            stats = node.get("stats") or {}
            if node.get("state") == "ALIVE" and key in stats:
                samples.append((nid[:8], stats[key]))
        if samples:
            lines.append(f"# HELP {metric} {help_text}")
            lines.append(f"# TYPE {metric} gauge")
            for nid, value in samples:
                lines.append(f'{metric}{{NodeId="{_escape(nid)}"}} {value}')
    rss = []
    for nid, node in nodes.items():
        stats = node.get("stats") or {}
        if node.get("state") != "ALIVE":
            continue
        for wid, w in (stats.get("workers") or {}).items():
            if "rss" in w:
                rss.append((nid[:8], wid[:8], w.get("pid", 0), w["rss"]))
    if rss:
        lines.append("# HELP ray_tpu_node_worker_rss_bytes Per-worker resident set size in bytes.")
        lines.append("# TYPE ray_tpu_node_worker_rss_bytes gauge")
        for nid, wid, pid, value in rss:
            lines.append(
                f'ray_tpu_node_worker_rss_bytes{{NodeId="{_escape(nid)}",'
                f'WorkerId="{_escape(wid)}",pid="{pid}"}} {value}'
            )
    return lines
