"""JAX version-compat shims shared across the codebase.

One definition instead of a copy per module: ``shard_map()`` moved from
``jax.experimental.shard_map`` into ``jax.shard_map``, and its replication-
checking kwarg was renamed ``check_rep`` -> ``check_vma``. Callers here use
the NEW names; this shim resolves whatever the installed JAX provides.
"""

from __future__ import annotations

import functools
import inspect

_shard_map_cached = None


def shard_map():
    """Return a ``shard_map`` callable accepting the new-style ``check_vma``
    kwarg on any supported JAX version (translated to ``check_rep``, or
    dropped, for older installs)."""
    global _shard_map_cached
    if _shard_map_cached is not None:
        return _shard_map_cached

    import jax

    if hasattr(jax, "shard_map"):
        fn = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as fn

    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):
        _shard_map_cached = fn
        return fn
    if "check_vma" in params:
        _shard_map_cached = fn
        return fn

    @functools.wraps(fn)
    def compat(*args, **kwargs):
        if "check_vma" in kwargs:
            value = kwargs.pop("check_vma")
            if "check_rep" in params:
                kwargs["check_rep"] = value
        return fn(*args, **kwargs)

    _shard_map_cached = compat
    return compat
