"""Scheduling strategies (analog of python/ray/util/scheduling_strategies.py:15,41)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: object
    placement_group_bundle_index: int = 0

    def to_options(self) -> dict:
        return {
            "placement_group_id": self.placement_group.id.hex(),
            "placement_group_bundle_index": self.placement_group_bundle_index,
        }


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False

    def to_options(self) -> dict:
        suffix = ":soft" if self.soft else ""
        return {"scheduling_strategy": f"node:{self.node_id}{suffix}"}


SPREAD = "SPREAD"
DEFAULT = "DEFAULT"
