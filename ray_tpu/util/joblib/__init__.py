"""joblib backend over ray_tpu tasks.

Analog of the reference's ray.util.joblib (register_ray backend): scikit-learn
style ``Parallel(...)`` fan-outs run as ray_tpu tasks instead of local
processes, so a cluster's CPUs serve joblib workloads unchanged:

    from ray_tpu.util.joblib import register_ray
    import joblib
    register_ray()
    with joblib.parallel_backend("ray_tpu"):
        results = joblib.Parallel()(joblib.delayed(f)(x) for x in data)
"""

from __future__ import annotations


def register_ray():
    """Register the 'ray_tpu' joblib parallel backend."""
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", _RayTpuBackend)


def _make_backend():
    from joblib._parallel_backends import ParallelBackendBase

    class RayTpuBackend(ParallelBackendBase):
        """Each joblib batch becomes one ray_tpu task; effective_n_jobs maps
        to the cluster's CPU count (reference: RayBackend in
        util/joblib/ray_backend.py)."""

        supports_timeout = True
        uses_threads = False
        supports_sharedmem = False

        def effective_n_jobs(self, n_jobs):
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")  # joblib semantics
            if n_jobs is not None and n_jobs > 0:
                # Explicit positive n_jobs: no cluster-state RPC needed
                # (joblib calls this repeatedly per dispatch).
                return int(n_jobs)
            import ray_tpu

            if not ray_tpu.is_initialized():
                ray_tpu.init()
            try:
                cpus = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
            except Exception:
                cpus = 1
            if n_jobs is None or n_jobs == -1:
                return cpus
            # joblib semantics: -2 = all CPUs but one, etc.
            return max(1, cpus + 1 + int(n_jobs))

        def submit(self, func, callback=None):
            import cloudpickle

            ref = _remote_batch_fn().remote(cloudpickle.dumps(func))
            return _RayFuture(ref, callback)

        # Older joblib calls apply_async; same semantics.
        apply_async = submit

        def retrieve_result(self, out, timeout=None):
            return out.get(timeout=timeout)

        def configure(self, n_jobs=1, parallel=None, prefer=None, require=None, **kwargs):
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def terminate(self):
            pass

        def abort_everything(self, ensure_ready=True):
            if ensure_ready:
                self.configure(n_jobs=self.parallel.n_jobs, parallel=self.parallel)

    return RayTpuBackend


class _RayFuture:
    """joblib future protocol over an ObjectRef."""

    def __init__(self, ref, callback):
        self._ref = ref
        self._callback = callback
        self._result = None
        self._done = False
        if callback is not None:
            import threading

            threading.Thread(target=self._wait_and_callback, daemon=True).start()

    def _wait_and_callback(self):
        try:
            result = self.get()
        except Exception:
            return
        self._callback(result)

    def get(self, timeout=None):
        import ray_tpu

        if not self._done:
            self._result = ray_tpu.get(self._ref, timeout=timeout)
            self._done = True
        return self._result


_batch_fn = None


def _remote_batch_fn():
    """One shared remote function for all batches (constructing a fresh
    RemoteFunction per submit would pay export cost per task)."""
    global _batch_fn
    if _batch_fn is None:
        import ray_tpu

        @ray_tpu.remote
        def _run_joblib_batch(payload):
            import cloudpickle as _cp

            return _cp.loads(payload)()

        _batch_fn = _run_joblib_batch
    return _batch_fn


_RayTpuBackend = _make_backend()
