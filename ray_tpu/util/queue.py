"""Distributed FIFO queue backed by an actor.

Analog of the reference's ray.util.queue.Queue (python/ray/util/queue.py):
a named actor holds the buffer; producers/consumers on any node share the
handle. Blocking ``put``/``get`` with timeouts are client-side poll loops so
the queue actor itself never blocks its scheduling queue (the reference uses
an asyncio actor for the same reason).
"""

from __future__ import annotations

import time

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote
class _QueueActor:
    def __init__(self, maxsize: int):
        from collections import deque

        self._maxsize = maxsize
        self._buf = deque()

    def qsize(self) -> int:
        return len(self._buf)

    def try_put(self, items: list, atomic: bool = False) -> int:
        """Appends as many items as fit; returns how many were accepted.
        With atomic=True, accepts all or none (batch puts must not leave a
        half-written queue)."""
        if atomic and self._maxsize > 0 and len(self._buf) + len(items) > self._maxsize:
            return 0
        accepted = 0
        for item in items:
            if self._maxsize > 0 and len(self._buf) >= self._maxsize:
                break
            self._buf.append(item)
            accepted += 1
        return accepted

    def try_get(self, n: int = 1) -> list:
        out = []
        while self._buf and len(out) < n:
            out.append(self._buf.popleft())
        return out

    def try_get_exact(self, n: int) -> list | None:
        """Pops exactly n items, or nothing (None) if fewer are queued."""
        if len(self._buf) < n:
            return None
        return [self._buf.popleft() for _ in range(n)]


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: dict | None = None):
        self.maxsize = maxsize
        cls = _QueueActor.options(**actor_options) if actor_options else _QueueActor
        self.actor = cls.remote(maxsize)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def put(self, item, block: bool = True, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray_tpu.get(self.actor.try_put.remote([item])) == 1:
                return
            if not block or (deadline is not None and time.monotonic() >= deadline):
                raise Full
            time.sleep(0.01)

    def put_nowait(self, item):
        self.put(item, block=False)

    def put_nowait_batch(self, items: list):
        accepted = ray_tpu.get(self.actor.try_put.remote(list(items), True))
        if accepted != len(items):
            raise Full(f"batch of {len(items)} does not fit (maxsize={self.maxsize})")

    def get(self, block: bool = True, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            got = ray_tpu.get(self.actor.try_get.remote(1))
            if got:
                return got[0]
            if not block or (deadline is not None and time.monotonic() >= deadline):
                raise Empty
            time.sleep(0.01)

    def get_nowait(self):
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> list:
        got = ray_tpu.get(self.actor.try_get_exact.remote(num_items))
        if got is None:
            raise Empty(f"fewer than {num_items} items available")
        return got

    def shutdown(self):
        ray_tpu.kill(self.actor)
