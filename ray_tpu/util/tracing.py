"""Distributed tracing.

Analog of the reference's util/tracing/tracing_helper.py (560 LoC of OTel
wrapping): opt-in span propagation across task/actor boundaries. Instead of
requiring OpenTelemetry, span context (trace id, span id, parent id) rides
inside every TaskSpec, each task execution records its span into the task
event log, and ``export_spans()`` reconstructs the trace tree from the GCS —
the same data also renders causally in ``ray_tpu timeline``. An OTel exporter
can be layered on top by walking ``export_spans()``.

Enable with ``RAY_TPU_TRACING=1`` (or ``enable_tracing()`` before submitting).
"""

from __future__ import annotations

import contextvars
import os
import uuid

_enabled: bool | None = None
# (trace_id, span_id) of the currently-executing task in this process.
_current: contextvars.ContextVar = contextvars.ContextVar("ray_tpu_trace", default=None)


def tracing_enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("RAY_TPU_TRACING", "0") == "1"
    return _enabled


def enable_tracing():
    """Enable tracing cluster-wide. The flag is stored in the GCS KV so
    workers on EVERY node pick it up at startup (a plain env var would only
    reach workers forked by a same-process raylet)."""
    global _enabled
    _enabled = True
    os.environ["RAY_TPU_TRACING"] = "1"
    _publish_flag_if_connected()


def _publish_flag_if_connected():
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker_if_initialized()
    if cw is None:
        return
    try:
        cw.gcs.call("kv_put", {"key": "tracing:enabled", "value": b"1", "overwrite": True})
    except Exception:
        pass


def get_current_span_context() -> dict | None:
    """(driver or inside a task) the active span context, if tracing."""
    cur = _current.get()
    if cur is None:
        return None
    return {"trace_id": cur[0], "span_id": cur[1]}


def child_span_context() -> dict:
    """Build the span context to attach to an outgoing task submission."""
    cur = _current.get()
    if cur is None:
        # Root: new trace originating at this driver/task.
        return {"trace_id": uuid.uuid4().hex, "span_id": uuid.uuid4().hex[:16], "parent_id": ""}
    return {"trace_id": cur[0], "span_id": uuid.uuid4().hex[:16], "parent_id": cur[1]}


def set_task_context(trace_ctx: dict | None):
    """Called by the worker as a task starts executing. Always sets (clearing
    for untraced tasks so a reused worker can't leak the previous task's
    span); returns a token for contextvars reset."""
    if trace_ctx:
        return _current.set((trace_ctx.get("trace_id"), trace_ctx.get("span_id")))
    return _current.set(None)


def reset_task_context(token):
    _current.reset(token)


# ---------------------------------------------------------------------------
# Hop-level dispatch budget (config.hop_timing / RAY_TPU_HOP_TIMING=1)
# ---------------------------------------------------------------------------
#
# Each completed dispatch leaves a record of monotonic stage timestamps on
# the owner (CoreWorker.hop_records()); the stages chain differently per
# transport path. summarize_hop_records() turns the raw records into the
# per-hop latency budget that microbench.py --hop-budget emits.

# Ordered stage transitions per path. A "hop" that crosses a process
# boundary is a wire frame; the rest are in-process thread/loop handoffs.
_HOP_CHAINS = {
    # Warm-lease / steady-state normal task: owner ships worker-direct, the
    # worker replies owner-direct — the raylet is not on the path at all.
    "lease": [
        ("submit", "ship"),          # user thread -> owner IO loop + stage
        ("ship", "worker_recv"),     # WIRE owner -> worker
        ("worker_recv", "exec_start"),  # worker loop -> main-thread exec queue
        ("exec_start", "exec_end"),  # user code
        ("exec_end", "reply"),       # worker main thread -> worker IO loop
        ("reply", "owner_recv"),     # WIRE worker -> owner
        ("owner_recv", "wake"),      # owner IO loop -> blocked getter thread
    ],
    "actor": [
        ("submit", "ship"),
        ("ship", "worker_recv"),
        ("worker_recv", "exec_start"),
        ("exec_start", "exec_end"),
        ("exec_end", "reply"),
        ("reply", "owner_recv"),
        ("owner_recv", "wake"),
    ],
    # Classic raylet-queued path (PG / SPREAD / affinity / streaming): two
    # extra raylet stages on the way in, plus the task_finished frame.
    "classic": [
        ("submit", "ship"),
        ("ship", "raylet_recv"),         # WIRE owner -> raylet
        ("raylet_recv", "raylet_dispatch"),  # raylet queue + grant
        ("raylet_dispatch", "worker_recv"),  # WIRE raylet -> worker
        ("worker_recv", "exec_start"),
        ("exec_start", "exec_end"),
        ("exec_end", "reply"),
        ("reply", "owner_recv"),         # WIRE worker -> owner
        ("owner_recv", "wake"),
    ],
}

# Serial wire frames (process boundary crossings) on each path's critical
# path. The warm-lease fast path is 2 — matching the reference's steady
# state (owner->worker push, worker->owner reply); classic is 4 (submit,
# dispatch, task_done, piggybacked task_finished push).
_SERIAL_PROCESS_HOPS = {"lease": 2, "actor": 2, "classic": 4, "compiled": 0}
_RAYLET_RPCS = {"lease": 0, "actor": 0, "classic": 2, "compiled": 0}


def _pctl(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def _compiled_transitions(recs: list[dict]) -> tuple[dict, list[float]]:
    """Per-record dynamic chains for compiled-graph iterations: the stage
    set depends on the DAG (``s{i}_recv``/``s{i}_exec`` per stage), so the
    chain is derived from each record's monotonic stamps sorted by time —
    and the very absence of any ``raylet_*`` stamp is the recorded evidence
    that compiled dispatch issues zero raylet RPCs per iteration."""
    trans: dict[str, list[float]] = {}
    totals: list[float] = []
    for rec in recs:
        stamps = sorted((v, k) for k, v in rec.items() if isinstance(v, float))
        for (va, ka), (vb, kb) in zip(stamps, stamps[1:]):
            trans.setdefault(f"{ka}->{kb}", []).append((vb - va) * 1e6)
        if len(stamps) >= 2:
            totals.append((stamps[-1][0] - stamps[0][0]) * 1e6)
    return trans, totals


def summarize_hop_records(records: list[dict]) -> dict:
    """Aggregate raw hop records into a per-path, per-stage µs budget."""
    by_path: dict[str, list[dict]] = {}
    for rec in records:
        by_path.setdefault(rec.get("path", "classic"), []).append(rec)
    out: dict = {}
    for path, recs in by_path.items():
        stages: dict[str, dict] = {}
        totals: list[float] = []
        if path == "compiled":
            trans, totals = _compiled_transitions(recs)
            for key in trans:
                deltas = sorted(trans[key])
                stages[key] = {
                    "p50_us": round(_pctl(deltas, 0.5), 1),
                    "p90_us": round(_pctl(deltas, 0.9), 1),
                    "n": len(deltas),
                }
            totals.sort()
            out[path] = {
                "count": len(recs),
                "stages_us": stages,
                "total_p50_us": round(_pctl(totals, 0.5), 1) if totals else None,
                "total_p90_us": round(_pctl(totals, 0.9), 1) if totals else None,
                "serial_process_hops": _SERIAL_PROCESS_HOPS.get(path),
                "raylet_rpcs_per_call": _RAYLET_RPCS.get(path),
            }
            continue
        chain = _HOP_CHAINS.get(path, _HOP_CHAINS["classic"])
        for a, b in chain:
            deltas = sorted(
                (rec[b] - rec[a]) * 1e6
                for rec in recs
                if a in rec and b in rec and rec[b] >= rec[a]
            )
            if deltas:
                stages[f"{a}->{b}"] = {
                    "p50_us": round(_pctl(deltas, 0.5), 1),
                    "p90_us": round(_pctl(deltas, 0.9), 1),
                    "n": len(deltas),
                }
        for rec in recs:
            first, last = chain[0][0], chain[-1][1]
            if first in rec and last in rec:
                totals.append((rec[last] - rec[first]) * 1e6)
        totals.sort()
        out[path] = {
            "count": len(recs),
            "stages_us": stages,
            "total_p50_us": round(_pctl(totals, 0.5), 1) if totals else None,
            "total_p90_us": round(_pctl(totals, 0.9), 1) if totals else None,
            "serial_process_hops": _SERIAL_PROCESS_HOPS.get(path),
            "raylet_rpcs_per_call": _RAYLET_RPCS.get(path),
        }
    return out


def format_hop_table(summary: dict) -> str:
    """Human-readable per-hop µs table from summarize_hop_records output."""
    lines = []
    for path, info in summary.items():
        lines.append(
            f"[{path}] n={info['count']}  total p50={info['total_p50_us']}us "
            f"p90={info['total_p90_us']}us  serial process hops="
            f"{info['serial_process_hops']}  raylet rpcs/call={info['raylet_rpcs_per_call']}"
        )
        lines.append(f"  {'stage':<30} {'p50 us':>10} {'p90 us':>10} {'n':>6}")
        for stage, s in info["stages_us"].items():
            lines.append(f"  {stage:<30} {s['p50_us']:>10.1f} {s['p90_us']:>10.1f} {s['n']:>6}")
    return "\n".join(lines)


def collect_hop_records() -> list[dict]:
    """Hop records from the connected core worker (empty when hop timing is
    off or nothing has completed)."""
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker_if_initialized()
    if cw is None:
        return []
    return cw.hop_records()


def drain_hop_records() -> list[dict]:
    """collect_hop_records() + clear — use between measurement phases so an
    earlier phase's records can't be evicted from the bounded ring buffer
    by a later, faster phase."""
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker_if_initialized()
    if cw is None:
        return []
    return cw.drain_hop_records()


def hop_trace_events(records: list[dict], mono_to_wall: float | None = None) -> list[dict]:
    """Convert hop records into Chrome-trace events that render causally
    next to task rows: per-stage ``X`` slices on a ``hop:<path>`` track plus
    a flow arrow (``s``/``f``) from submit to wake, so a dispatch's wire
    hops line up under the task that caused them.

    ``mono_to_wall`` converts monotonic stamps onto the wall-clock axis the
    task events use; stamps from every process on a host share
    CLOCK_MONOTONIC, so one offset suffices. Records whose stamps span an
    impossible interval are dropped: a record mixing stamps from hosts with
    different monotonic epochs (multi-node classic dispatch) would sort its
    stages by boot-time delta, not causality, and render garbage."""
    import time as _time

    if mono_to_wall is None:
        mono_to_wall = _time.time() - _time.monotonic()
    events: list[dict] = []
    for n, rec in enumerate(records):
        stamps = sorted((v, k) for k, v in rec.items() if isinstance(v, float))
        if len(stamps) < 2:
            continue
        if stamps[-1][0] - stamps[0][0] > 600.0:
            continue  # cross-host monotonic epochs — not renderable
        path = rec.get("path", "classic")
        pid = f"hop:{path}"
        tid = rec.get("name", "dispatch")
        flow_id = (hash(rec.get("task_id") or f"{tid}:{n}") & 0x7FFFFFFF) or 1
        for (va, ka), (vb, kb) in zip(stamps, stamps[1:]):
            events.append(
                {
                    "name": f"{ka}->{kb}",
                    "cat": "hop",
                    "ph": "X",
                    "ts": (va + mono_to_wall) * 1e6,
                    "dur": max(vb - va, 0) * 1e6,
                    "pid": pid,
                    "tid": tid,
                    "args": {"task_id": rec.get("task_id"), "path": path},
                }
            )
        first_ts = (stamps[0][0] + mono_to_wall) * 1e6
        last_ts = (stamps[-1][0] + mono_to_wall) * 1e6
        events.append(
            {"name": "dispatch", "cat": "hop", "ph": "s", "id": flow_id,
             "ts": first_ts, "pid": pid, "tid": tid}
        )
        events.append(
            {"name": "dispatch", "cat": "hop", "ph": "f", "bp": "e", "id": flow_id,
             "ts": last_ts, "pid": pid, "tid": tid}
        )
    return events


def export_spans(address=None) -> list[dict]:
    """Reconstruct spans from the task-event log: one span per task with
    trace/span/parent ids, name, timestamps, and status."""
    from ray_tpu.util.state import list_tasks

    spans = []
    for row in list_tasks(address=address):
        ctx = row.get("trace_ctx") or {}
        if not ctx.get("trace_id"):
            continue
        spans.append(
            {
                "trace_id": ctx["trace_id"],
                "span_id": ctx.get("span_id"),
                "parent_id": ctx.get("parent_id") or None,
                "name": row.get("name"),
                "task_id": row.get("task_id"),
                "start_time": row.get("start_time"),
                "end_time": row.get("end_time"),
                "status": row.get("state"),
                "node_id": row.get("node_id"),
            }
        )
    return spans
