"""Distributed tracing.

Analog of the reference's util/tracing/tracing_helper.py (560 LoC of OTel
wrapping): opt-in span propagation across task/actor boundaries. Instead of
requiring OpenTelemetry, span context (trace id, span id, parent id) rides
inside every TaskSpec, each task execution records its span into the task
event log, and ``export_spans()`` reconstructs the trace tree from the GCS —
the same data also renders causally in ``ray_tpu timeline``. An OTel exporter
can be layered on top by walking ``export_spans()``.

Enable with ``RAY_TPU_TRACING=1`` (or ``enable_tracing()`` before submitting).
"""

from __future__ import annotations

import contextvars
import os
import uuid

_enabled: bool | None = None
# (trace_id, span_id) of the currently-executing task in this process.
_current: contextvars.ContextVar = contextvars.ContextVar("ray_tpu_trace", default=None)


def tracing_enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("RAY_TPU_TRACING", "0") == "1"
    return _enabled


def enable_tracing():
    """Enable tracing cluster-wide. The flag is stored in the GCS KV so
    workers on EVERY node pick it up at startup (a plain env var would only
    reach workers forked by a same-process raylet)."""
    global _enabled
    _enabled = True
    os.environ["RAY_TPU_TRACING"] = "1"
    _publish_flag_if_connected()


def _publish_flag_if_connected():
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker_if_initialized()
    if cw is None:
        return
    try:
        cw.gcs.call("kv_put", {"key": "tracing:enabled", "value": b"1", "overwrite": True})
    except Exception:
        pass


def get_current_span_context() -> dict | None:
    """(driver or inside a task) the active span context, if tracing."""
    cur = _current.get()
    if cur is None:
        return None
    return {"trace_id": cur[0], "span_id": cur[1]}


def child_span_context() -> dict:
    """Build the span context to attach to an outgoing task submission."""
    cur = _current.get()
    if cur is None:
        # Root: new trace originating at this driver/task.
        return {"trace_id": uuid.uuid4().hex, "span_id": uuid.uuid4().hex[:16], "parent_id": ""}
    return {"trace_id": cur[0], "span_id": uuid.uuid4().hex[:16], "parent_id": cur[1]}


def set_task_context(trace_ctx: dict | None):
    """Called by the worker as a task starts executing. Always sets (clearing
    for untraced tasks so a reused worker can't leak the previous task's
    span); returns a token for contextvars reset."""
    if trace_ctx:
        return _current.set((trace_ctx.get("trace_id"), trace_ctx.get("span_id")))
    return _current.set(None)


def reset_task_context(token):
    _current.reset(token)


def export_spans(address=None) -> list[dict]:
    """Reconstruct spans from the task-event log: one span per task with
    trace/span/parent ids, name, timestamps, and status."""
    from ray_tpu.util.state import list_tasks

    spans = []
    for row in list_tasks(address=address):
        ctx = row.get("trace_ctx") or {}
        if not ctx.get("trace_id"):
            continue
        spans.append(
            {
                "trace_id": ctx["trace_id"],
                "span_id": ctx.get("span_id"),
                "parent_id": ctx.get("parent_id") or None,
                "name": row.get("name"),
                "task_id": row.get("task_id"),
                "start_time": row.get("start_time"),
                "end_time": row.get("end_time"),
                "status": row.get("state"),
                "node_id": row.get("node_id"),
            }
        )
    return spans
