"""Dask-on-ray_tpu scheduler (analog of reference python/ray/util/dask/).

`ray_tpu_dask_get` is a dask custom scheduler: it walks a dask task graph,
submits each task as a ray_tpu task with upstream keys passed as ObjectRefs
(so the object store, not the driver, moves intermediate data), and gathers
the requested keys. The graph protocol is plain dicts/tuples, so the
scheduler works standalone; with dask installed:

    import dask
    from ray_tpu.util.dask import ray_tpu_dask_get, enable_dask_on_ray
    dask.compute(obj, scheduler=ray_tpu_dask_get)   # one-shot
    enable_dask_on_ray()                            # or process-wide
"""

from __future__ import annotations

from typing import Any, Hashable

import ray_tpu

_remote_exec = None


def _exec_fn():
    global _remote_exec
    if _remote_exec is None:
        @ray_tpu.remote
        def _exec_task(fn, args):
            # Refs arrive nested inside the args list (only top-level task
            # args auto-resolve), so materialize them here, inside the task.
            import ray_tpu as _rt

            def mat(x):
                if isinstance(x, _rt.ObjectRef):
                    return _rt.get(x)
                if isinstance(x, list):
                    return [mat(v) for v in x]
                return x

            return fn(*[mat(a) for a in args])

        _remote_exec = _exec_task
    return _remote_exec


def _is_task(x) -> bool:
    return isinstance(x, tuple) and bool(x) and callable(x[0])


def _resolve(expr, refs: dict):
    """Substitute keys with their (ref) results inside a task argument.
    Top-level key references stay as ObjectRefs (the remote executor
    materializes them); a nested inline task runs driver-side, so its
    ref-valued inputs must be fetched before the call."""
    if _is_task(expr):
        fn, *args = expr
        vals = [_resolve(a, refs) for a in args]
        vals = [
            ray_tpu.get(v) if isinstance(v, ray_tpu.ObjectRef) else v for v in vals
        ]
        return fn(*vals)
    if isinstance(expr, list):
        return [_resolve(a, refs) for a in expr]
    if isinstance(expr, Hashable) and expr in refs:
        return refs[expr]
    return expr


def ray_tpu_dask_get(dsk: dict, keys, **kwargs) -> Any:
    """Execute a dask graph on the cluster; returns values for `keys`
    (nested key lists mirror dask's get contract)."""
    import ray_tpu

    refs: dict = {}
    remaining = dict(dsk)
    # Topological submission: a task is ready when all its key-args resolved.
    while remaining:
        progressed = False
        for key in list(remaining):
            expr = remaining[key]
            deps = _find_deps(expr, dsk)
            if any(d not in refs for d in deps):
                continue
            if _is_task(expr):
                fn, *args = expr
                args = [_resolve(a, refs) for a in args]
                refs[key] = _exec_fn().remote(fn, args)
            else:
                refs[key] = _resolve(expr, refs)
            del remaining[key]
            progressed = True
        if not progressed:
            raise ValueError(
                f"dask graph has a cycle or missing keys: {sorted(map(str, remaining))[:5]}"
            )

    def fetch(k):
        if isinstance(k, list):
            return [fetch(x) for x in k]
        v = refs[k]
        return ray_tpu.get(v) if isinstance(v, ray_tpu.ObjectRef) else v

    return fetch(list(keys)) if isinstance(keys, list) else fetch(keys)


def _find_deps(expr, dsk) -> set:
    deps: set = set()
    if _is_task(expr):
        for a in expr[1:]:
            deps |= _find_deps(a, dsk)
    elif isinstance(expr, list):
        for a in expr:
            deps |= _find_deps(a, dsk)
    elif isinstance(expr, Hashable) and expr in dsk:
        deps.add(expr)
    return deps


def enable_dask_on_ray():
    """Set ray_tpu_dask_get as dask's process-wide scheduler (requires the
    dask package, which is not in this image — gated like the reference's
    optional integrations)."""
    try:
        import dask
    except ImportError as e:
        raise ImportError(
            "enable_dask_on_ray requires the 'dask' package (pip install "
            "dask); ray_tpu_dask_get itself works on raw task graphs without it"
        ) from e
    dask.config.set(scheduler=ray_tpu_dask_get)


def disable_dask_on_ray():
    try:
        import dask
    except ImportError as e:
        raise ImportError("dask is not installed") from e
    dask.config.set(scheduler=None)
