"""Client server — runs on the head node, executes for thin clients.

Analog of the reference's util/client/server/server.py: holds a real driver
CoreWorker connected to the cluster; every RPC maps 1:1 to a driver-side API
call. Returned ObjectRefs are pinned in a registry keyed by id so the
cluster-side refcount stays >0 while any client holds the id; clients release
ids explicitly (ObjectRef.__del__ → client_release)."""

from __future__ import annotations

import logging
import threading

from ray_tpu._private import serialization
from ray_tpu._private.rpc import RpcServer

logger = logging.getLogger(__name__)


class ClientServer:
    def __init__(self, core_worker, host: str = "0.0.0.0", port: int = 0):
        """``core_worker`` is a DRIVER-mode CoreWorker already connected."""
        self.cw = core_worker
        self._refs: dict[str, object] = {}  # id hex -> ObjectRef (pin)
        self._lock = threading.Lock()
        self.server = RpcServer(name="client-server")
        self.server.register_all(self, prefix="client_")
        self.server.start(host=host, port=port)
        self.address = self.server.address

    # -- helpers --------------------------------------------------------
    def _pin(self, refs) -> list[str]:
        out = []
        with self._lock:
            for r in refs:
                self._refs[r.hex()] = r
                out.append(r.hex())
        return out

    def _lookup(self, ids: list[str]) -> list:
        with self._lock:
            missing = [i for i in ids if i not in self._refs]
            if missing:
                raise KeyError(f"unknown/released object ids {missing}")
            return [self._refs[i] for i in ids]

    @staticmethod
    async def _off_loop(fn):
        """Every CoreWorker entry point here is synchronous and may itself
        issue blocking RPCs — running it on the IO loop would deadlock the
        process's sockets. Always hop to a worker thread."""
        import asyncio

        return await asyncio.get_event_loop().run_in_executor(None, fn)

    # -- RPC methods ----------------------------------------------------
    async def rpc_task(self, req):
        func = serialization.loads(req["func"])
        args, kwargs = serialization.loads(req["args"])
        opts = req.get("opts") or {}
        refs = await self._off_loop(lambda: self.cw.submit_task(func, args, kwargs, **opts))
        return {"ids": self._pin(refs)}

    async def rpc_create_actor(self, req):
        cls = serialization.loads(req["cls"])
        args, kwargs = serialization.loads(req["args"])
        opts = req.get("opts") or {}
        info = await self._off_loop(lambda: self.cw.create_actor(cls, args, kwargs, **opts))
        return {"info": info}

    async def rpc_actor_call(self, req):
        args, kwargs = serialization.loads(req["args"])
        refs = await self._off_loop(
            lambda: self.cw.submit_actor_task(
                req["actor_id"],
                req["method"],
                args,
                kwargs,
                num_returns=req.get("num_returns", 1),
                max_task_retries=req.get("max_task_retries", 0),
            )
        )
        return {"ids": self._pin(refs)}

    async def rpc_get(self, req):
        refs = self._lookup(req["ids"])
        try:
            values = await self._off_loop(
                lambda: self.cw.get(refs, timeout=req.get("timeout"))
            )
        except Exception as e:
            return {"error": serialization.dumps(e)}
        return {"values": serialization.dumps(values)}

    async def rpc_put(self, req):
        value = serialization.loads(req["value"])
        ref = await self._off_loop(lambda: self.cw.put(value))
        return {"id": self._pin([ref])[0]}

    async def rpc_wait(self, req):
        refs = self._lookup(req["ids"])
        ready, not_ready = await self._off_loop(
            lambda: self.cw.wait(
                refs,
                num_returns=req.get("num_returns", 1),
                timeout=req.get("timeout"),
                fetch_local=req.get("fetch_local", True),
            )
        )
        return {"ready": [r.hex() for r in ready], "not_ready": [r.hex() for r in not_ready]}

    async def rpc_release(self, req):
        with self._lock:
            for i in req.get("ids", []):
                self._refs.pop(i, None)
        return {"ok": True}

    async def rpc_gcs_call(self, req):
        return await self._off_loop(
            lambda: self.cw.gcs.call(req["method"], req.get("payload") or {})
        )

    def stop(self):
        self.server.stop()
