"""Client server — runs on the head node, executes for thin clients.

Analog of the reference's util/client/server/server.py + dataservicer: holds
a real driver CoreWorker connected to the cluster; every RPC maps 1:1 to a
driver-side API call. Returned ObjectRefs are pinned in a per-client session
so the cluster-side refcount stays >0 while any client holds the id; clients
release ids explicitly (ObjectRef.__del__ → client_release).

Reconnect semantics (reference: server/proxier + client reconnect_grace):
every mutating request carries a ``req_id``; the session caches recent
responses so a client that lost its connection mid-call can reconnect and
REPLAY the request without double-submitting (the reference's data channel
achieves the same with acked sequence numbers). Sessions survive connection
loss and are reaped only after ``session_ttl_s`` without any call.

Data channel (reference: dataservicer 64KiB chunking): values larger than
``stream_threshold`` transfer as chunk streams (client_get_chunk /
client_put_begin+chunk+commit) so no single RPC frame carries an unbounded
payload — bounded memory per message is the backpressure story, and the
client pulls chunks strictly sequentially."""

from __future__ import annotations

import collections
import logging
import threading
import time
import uuid

from ray_tpu._private import serialization
from ray_tpu._private.rpc import RpcServer

logger = logging.getLogger(__name__)

CHUNK_SIZE = 256 * 1024


class _Session:
    __slots__ = (
        "refs", "resp_cache", "streams", "uploads", "stream_ts", "inflight",
        "last_seen",
    )

    def __init__(self):
        self.refs: dict[str, object] = {}
        self.resp_cache: "collections.OrderedDict[str, dict]" = collections.OrderedDict()
        self.streams: dict[str, bytes] = {}
        self.uploads: dict[str, list] = {}
        self.stream_ts: dict[str, float] = {}  # sid -> created (both kinds)
        self.inflight: dict[str, object] = {}  # req_id -> asyncio.Future
        self.last_seen = time.time()


class ClientServer:
    def __init__(self, core_worker, host: str = "0.0.0.0", port: int = 0,
                 stream_threshold: int = 1024 * 1024, session_ttl_s: float = 300.0,
                 resp_cache_size: int = 128, stream_ttl_s: float = 180.0,
                 max_stream_bytes: int = 256 * 1024 * 1024):
        """``core_worker`` is a DRIVER-mode CoreWorker already connected.

        ``max_stream_bytes`` caps the bytes buffered in a session's download
        streams: a slow consumer that opens gets faster than it drains them
        BLOCKS further gets (data-channel backpressure) instead of growing
        server memory without bound."""
        self.cw = core_worker
        self.stream_threshold = stream_threshold
        self.session_ttl_s = session_ttl_s
        self.resp_cache_size = resp_cache_size
        self.stream_ttl_s = stream_ttl_s
        self.max_stream_bytes = max_stream_bytes
        self._sessions: dict[str, _Session] = {}
        self._last_reap = 0.0
        self._lock = threading.Lock()
        self.server = RpcServer(name="client-server")
        self.server.register_all(self, prefix="client_")
        self.server.start(host=host, port=port)
        self.address = self.server.address

    # -- session helpers -------------------------------------------------
    def _session(self, client_id: str) -> _Session:
        with self._lock:
            s = self._sessions.get(client_id or "")
            if s is None:
                s = self._sessions[client_id or ""] = _Session()
            now = time.time()
            s.last_seen = now
            # Lazy reap, throttled: the scan is O(sessions + streams) and
            # this method sits on every RPC — once per few seconds is
            # plenty for TTLs measured in minutes.
            if now - self._last_reap >= 5.0:
                self._last_reap = now
                # Sessions silent past the TTL lose their pins — the
                # reconnect grace period for DISCONNECTED clients (live
                # clients stay fresh via their keepalive pings).
                dead = [
                    cid for cid, sess in self._sessions.items()
                    if now - sess.last_seen > self.session_ttl_s
                ]
                for cid in dead:
                    logger.info("client session %s expired; releasing %d refs",
                                cid, len(self._sessions[cid].refs))
                    del self._sessions[cid]
                # Abandoned chunk streams/uploads inside LIVE sessions
                # (aborted transfers) get their own, shorter IDLE ttl —
                # stream_ts refreshes on every chunk access, so only
                # stalled transfers expire, however long the object.
                for sess in self._sessions.values():
                    stale = [
                        sid for sid, ts in sess.stream_ts.items()
                        if now - ts > self.stream_ttl_s
                    ]
                    for sid in stale:
                        sess.stream_ts.pop(sid, None)
                        sess.streams.pop(sid, None)
                        sess.uploads.pop(sid, None)
        return s

    async def _cached_call(self, req: dict, acompute):
        """At-most-once execution for mutating calls: a replayed req_id
        (same client reconnecting and retrying) returns the cached response
        instead of re-running the side effect. A replay that lands while
        the ORIGINAL is still executing awaits the same in-flight future —
        without this, the mid-call-loss window would double-execute."""
        import asyncio

        sess = self._session(req.get("client_id", ""))
        req_id = req.get("req_id")
        fut = None
        if req_id:
            with self._lock:
                cached = sess.resp_cache.get(req_id)
                if cached is not None:
                    return cached
                pending = sess.inflight.get(req_id)
                if pending is None:
                    fut = asyncio.get_event_loop().create_future()
                    sess.inflight[req_id] = fut
            if fut is None:
                return await asyncio.shield(pending)
        try:
            resp = await acompute()
        except Exception as e:
            if fut is not None:
                with self._lock:
                    sess.inflight.pop(req_id, None)
                if not fut.done():
                    fut.set_exception(e)
                    # A waiter consumes the exception; without one, silence
                    # the "exception never retrieved" warning.
                    fut.exception()
            raise
        # "_nocache": the handler judged the response safe to recompute and
        # too big to hold (mid-size get values) — replay just re-executes.
        nocache = resp.pop("_nocache", False)
        if req_id:
            with self._lock:
                sess.inflight.pop(req_id, None)
                if not nocache:
                    sess.resp_cache[req_id] = resp
                    while len(sess.resp_cache) > self.resp_cache_size:
                        sess.resp_cache.popitem(last=False)
            if fut is not None and not fut.done():
                fut.set_result(resp)
        return resp

    def _pin(self, client_id: str, refs) -> list[str]:
        sess = self._session(client_id)
        out = []
        with self._lock:
            for r in refs:
                sess.refs.setdefault(r.hex(), r)
                out.append(r.hex())
        return out

    def _lookup(self, client_id: str, ids: list[str], owners: list | None = None) -> list:
        """Resolve ids to refs. Ids the server never pinned (e.g. ObjectRefs
        nested inside returned values, deserialized client-side) are rebuilt
        from id + owner address and registered with the driver."""
        from ray_tpu._private.ids import ObjectID
        from ray_tpu.object_ref import ObjectRef

        sess = self._session(client_id)
        out = []
        with self._lock:
            for pos, i in enumerate(ids):
                ref = sess.refs.get(i)
                if ref is None:
                    owner = owners[pos] if owners and pos < len(owners) else None
                    ref = ObjectRef(ObjectID.from_hex(i), owner, _register=False)
                    self.cw.register_ref(ref)
                    sess.refs[i] = ref
                out.append(ref)
        return out

    async def _off_loop(self, fn):
        """Every CoreWorker entry point here is synchronous and may itself
        issue blocking RPCs — running it on the IO loop would deadlock the
        process's sockets. Always hop to a worker thread, with worker_context
        bound to the server's driver so (de)serialization hooks (ObjectRef
        borrow registration in particular) land on the right core worker."""
        import asyncio

        from ray_tpu._private import worker_context

        def run():
            with worker_context.override(self.cw):
                return fn()

        return await asyncio.get_event_loop().run_in_executor(None, run)

    # -- RPC methods ----------------------------------------------------
    async def rpc_task(self, req):
        async def compute():
            def compute_sync():
                func = serialization.loads(req["func"])
                args, kwargs = serialization.loads(req["args"])
                opts = req.get("opts") or {}
                return self.cw.submit_task(func, args, kwargs, **opts)

            refs = await self._off_loop(compute_sync)
            from ray_tpu.object_ref import ObjectRefGenerator

            if isinstance(refs, ObjectRefGenerator):
                # num_returns="streaming": the client pulls item refs one at
                # a time (client_gen_next) — values stay IN the cluster until
                # fetched, so a slow consumer buffers nothing server-side.
                return {"gen": refs._task_id}
            return {"ids": self._pin(req.get("client_id", ""), refs)}

        return await self._cached_call(req, compute)

    async def rpc_gen_next(self, req):
        """Next item ref of a streaming generator. Bounded wait per call
        ({"pending": True} when the producer hasn't yielded item `index`
        yet — the client re-polls), {"done": True} past the end."""
        from ray_tpu.exceptions import GetTimeoutError
        from ray_tpu.object_ref import ObjectID, ObjectRef

        def pull():
            try:
                oid_hex = self.cw.stream_next(
                    req["gen"], int(req["index"]),
                    timeout=min(float(req.get("timeout") or 10.0), 30.0),
                )
            except StopIteration:
                return {"done": True}
            except GetTimeoutError:
                return {"pending": True}
            except Exception as e:  # producer raised: surface to the client
                return {"error": serialization.dumps(e)}
            ref = ObjectRef(ObjectID.from_hex(oid_hex), self.cw.address)
            return {"id": self._pin(req.get("client_id", ""), [ref])[0]}

        return await self._off_loop(pull)

    async def rpc_create_actor(self, req):
        async def compute():
            def compute_sync():
                cls = serialization.loads(req["cls"])
                args, kwargs = serialization.loads(req["args"])
                opts = req.get("opts") or {}
                return self.cw.create_actor(cls, args, kwargs, **opts)

            info = await self._off_loop(compute_sync)
            return {"info": info}

        return await self._cached_call(req, compute)

    async def rpc_actor_call(self, req):
        async def compute():
            def compute_sync():
                # loads runs off-loop and inside the worker_context override
                # (big payloads must not stall the loop; nested ObjectRefs
                # must register on this driver).
                args, kwargs = serialization.loads(req["args"])
                return self.cw.submit_actor_task(
                    req["actor_id"],
                    req["method"],
                    args,
                    kwargs,
                    num_returns=req.get("num_returns", 1),
                    max_task_retries=req.get("max_task_retries", 0),
                )

            refs = await self._off_loop(compute_sync)
            return {"ids": self._pin(req.get("client_id", ""), refs)}

        return await self._cached_call(req, compute)

    async def rpc_get(self, req):
        # Routed through the replay cache: a replayed get whose response
        # was lost must return the SAME stream id instead of serializing a
        # second (possibly huge) blob into the session.
        async def compute():
            def fetch_and_dump():
                # get AND serialize off-loop: dumps of a multi-GB value
                # would stall every other client's RPCs on the event loop.
                refs = self._lookup(req.get("client_id", ""), req["ids"], req.get("owners"))
                values = self.cw.get(refs, timeout=req.get("timeout"))
                return serialization.dumps(values)

            try:
                blob = await self._off_loop(fetch_and_dump)
            except Exception as e:
                return {"error": serialization.dumps(e)}
            if len(blob) <= self.stream_threshold:
                resp = {"values": blob}
                if len(blob) > 64 * 1024:
                    # Idempotent to recompute; not worth pinning in the
                    # replay cache (128 entries x up to 1MiB adds up).
                    resp["_nocache"] = True
                return resp
            # Large value: hand back a chunk stream (data channel), gated by
            # the per-session buffer cap — a consumer with undrained streams
            # waits here (backpressure) rather than stacking blobs.
            import asyncio

            sess = self._session(req.get("client_id", ""))
            sid = uuid.uuid4().hex
            deadline = time.time() + self.stream_ttl_s
            while True:
                with self._lock:
                    buffered = sum(len(b) for b in sess.streams.values())
                    if not sess.streams or buffered + len(blob) <= self.max_stream_bytes:
                        sess.streams[sid] = blob
                        sess.stream_ts[sid] = time.time()
                        break
                if time.time() > deadline:
                    return {"error": serialization.dumps(RuntimeError(
                        f"data channel backlog: {buffered} bytes undrained "
                        f"(cap {self.max_stream_bytes}); drain or raise the cap"
                    ))}
                await asyncio.sleep(0.05)
            return {"stream": sid, "size": len(blob), "chunk_size": CHUNK_SIZE}

        return await self._cached_call(req, compute)

    async def rpc_get_chunk(self, req):
        sess = self._session(req.get("client_id", ""))
        sid, offset = req["stream"], int(req["offset"])
        with self._lock:
            blob = sess.streams.get(sid)
            if blob is None:
                return {"error": serialization.dumps(KeyError(f"stream {sid} expired"))}
            sess.stream_ts[sid] = time.time()  # active transfer: not stale
            chunk = blob[offset:offset + CHUNK_SIZE]
            done = offset + len(chunk) >= len(blob)
        # The blob is NOT deleted here: a connection drop after serving the
        # final chunk must leave the replayed request servable. The client
        # acks completion with client_stream_done; the session TTL reaps
        # anything a vanished client never acked.
        return {"data": chunk, "done": done}

    async def rpc_stream_done(self, req):
        sess = self._session(req.get("client_id", ""))
        with self._lock:
            sess.streams.pop(req["stream"], None)
            sess.stream_ts.pop(req["stream"], None)
        return {"ok": True}

    async def rpc_put(self, req):
        async def compute():
            ref = await self._off_loop(
                lambda: self.cw.put(serialization.loads(req["value"]))
            )
            return {"id": self._pin(req.get("client_id", ""), [ref])[0]}

        return await self._cached_call(req, compute)

    # -- chunked upload (data channel, put direction) --------------------
    async def rpc_put_begin(self, req):
        async def compute():
            sess = self._session(req.get("client_id", ""))
            sid = uuid.uuid4().hex
            with self._lock:
                sess.uploads[sid] = []
                sess.stream_ts[sid] = time.time()
            return {"stream": sid, "chunk_size": CHUNK_SIZE}

        # Replay-cached: a lost begin-response must not orphan a buffer.
        return await self._cached_call(req, compute)

    async def rpc_put_chunk(self, req):
        sess = self._session(req.get("client_id", ""))
        with self._lock:
            parts = sess.uploads.get(req["stream"])
            if parts is None:
                return {"error": serialization.dumps(KeyError("upload expired"))}
            sess.stream_ts[req["stream"]] = time.time()  # active: not stale
            # seq makes retried chunk sends idempotent after a reconnect.
            seq = int(req["seq"])
            if seq == len(parts):
                parts.append(req["data"])
            elif seq > len(parts):
                return {"error": serialization.dumps(
                    ValueError(f"chunk gap: got seq {seq}, expected {len(parts)}")
                )}
        return {"ack": True}

    async def rpc_put_commit(self, req):
        async def compute():
            sess = self._session(req.get("client_id", ""))
            with self._lock:
                parts = sess.uploads.pop(req["stream"], None)
                sess.stream_ts.pop(req["stream"], None)
            if parts is None:
                return {"error": serialization.dumps(KeyError("upload expired"))}

            def join_load_put():
                # join + loads off-loop (multi-GB values must not stall the
                # event loop).
                return self.cw.put(serialization.loads(b"".join(parts)))

            ref = await self._off_loop(join_load_put)
            return {"id": self._pin(req.get("client_id", ""), [ref])[0]}

        return await self._cached_call(req, compute)

    async def rpc_wait(self, req):
        refs = self._lookup(req.get("client_id", ""), req["ids"], req.get("owners"))
        ready, not_ready = await self._off_loop(
            lambda: self.cw.wait(
                refs,
                num_returns=req.get("num_returns", 1),
                timeout=req.get("timeout"),
                fetch_local=req.get("fetch_local", True),
            )
        )
        return {"ready": [r.hex() for r in ready], "not_ready": [r.hex() for r in not_ready]}

    async def rpc_release(self, req):
        sess = self._session(req.get("client_id", ""))
        with self._lock:
            for i in req.get("ids", []):
                sess.refs.pop(i, None)
        return {"ok": True}

    async def rpc_put_abort(self, req):
        sess = self._session(req.get("client_id", ""))
        with self._lock:
            sess.uploads.pop(req["stream"], None)
            sess.stream_ts.pop(req["stream"], None)
        return {"ok": True}

    async def rpc_ping(self, req):
        """Keepalive: refreshes the session's last_seen (the reap clock)."""
        self._session(req.get("client_id", ""))
        return {"ok": True}

    async def rpc_disconnect(self, req):
        with self._lock:
            self._sessions.pop(req.get("client_id", ""), None)
        return {"ok": True}

    async def rpc_gcs_call(self, req):
        self._session(req.get("client_id", ""))
        return await self._off_loop(
            lambda: self.cw.gcs.call(req["method"], req.get("payload") or {})
        )

    def stop(self):
        self.server.stop()
