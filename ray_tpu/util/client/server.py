"""Client server — runs on the head node, executes for thin clients.

Analog of the reference's util/client/server/server.py: holds a real driver
CoreWorker connected to the cluster; every RPC maps 1:1 to a driver-side API
call. Returned ObjectRefs are pinned in a registry keyed by id so the
cluster-side refcount stays >0 while any client holds the id; clients release
ids explicitly (ObjectRef.__del__ → client_release)."""

from __future__ import annotations

import logging
import threading

from ray_tpu._private import serialization
from ray_tpu._private.rpc import RpcServer

logger = logging.getLogger(__name__)


class ClientServer:
    def __init__(self, core_worker, host: str = "0.0.0.0", port: int = 0):
        """``core_worker`` is a DRIVER-mode CoreWorker already connected."""
        self.cw = core_worker
        # client_id -> {id hex -> ObjectRef}. One pin per (client, id); the
        # client releases when its LAST local ref for the id dies, so a
        # release from one client can never unpin another's objects.
        self._refs: dict[str, dict[str, object]] = {}
        self._lock = threading.Lock()
        self.server = RpcServer(name="client-server")
        self.server.register_all(self, prefix="client_")
        self.server.start(host=host, port=port)
        self.address = self.server.address

    # -- helpers --------------------------------------------------------
    def _pin(self, client_id: str, refs) -> list[str]:
        out = []
        with self._lock:
            table = self._refs.setdefault(client_id or "", {})
            for r in refs:
                table.setdefault(r.hex(), r)
                out.append(r.hex())
        return out

    def _lookup(self, client_id: str, ids: list[str], owners: list | None = None) -> list:
        """Resolve ids to refs. Ids the server never pinned (e.g. ObjectRefs
        nested inside returned values, deserialized client-side) are rebuilt
        from id + owner address and registered with the driver."""
        from ray_tpu._private.ids import ObjectID
        from ray_tpu.object_ref import ObjectRef

        out = []
        with self._lock:
            table = self._refs.setdefault(client_id or "", {})
            for pos, i in enumerate(ids):
                ref = table.get(i)
                if ref is None:
                    owner = owners[pos] if owners and pos < len(owners) else None
                    ref = ObjectRef(ObjectID.from_hex(i), owner, _register=False)
                    self.cw.register_ref(ref)
                    table[i] = ref
                out.append(ref)
        return out

    async def _off_loop(self, fn):
        """Every CoreWorker entry point here is synchronous and may itself
        issue blocking RPCs — running it on the IO loop would deadlock the
        process's sockets. Always hop to a worker thread, with worker_context
        bound to the server's driver so (de)serialization hooks (ObjectRef
        borrow registration in particular) land on the right core worker."""
        import asyncio

        from ray_tpu._private import worker_context

        def run():
            with worker_context.override(self.cw):
                return fn()

        return await asyncio.get_event_loop().run_in_executor(None, run)

    # -- RPC methods ----------------------------------------------------
    async def rpc_task(self, req):
        func = serialization.loads(req["func"])
        args, kwargs = serialization.loads(req["args"])
        opts = req.get("opts") or {}
        refs = await self._off_loop(lambda: self.cw.submit_task(func, args, kwargs, **opts))
        return {"ids": self._pin(req.get("client_id", ""), refs)}

    async def rpc_create_actor(self, req):
        cls = serialization.loads(req["cls"])
        args, kwargs = serialization.loads(req["args"])
        opts = req.get("opts") or {}
        info = await self._off_loop(lambda: self.cw.create_actor(cls, args, kwargs, **opts))
        return {"info": info}

    async def rpc_actor_call(self, req):
        args, kwargs = serialization.loads(req["args"])
        refs = await self._off_loop(
            lambda: self.cw.submit_actor_task(
                req["actor_id"],
                req["method"],
                args,
                kwargs,
                num_returns=req.get("num_returns", 1),
                max_task_retries=req.get("max_task_retries", 0),
            )
        )
        return {"ids": self._pin(req.get("client_id", ""), refs)}

    async def rpc_get(self, req):
        try:
            refs = self._lookup(req.get("client_id", ""), req["ids"], req.get("owners"))
            values = await self._off_loop(
                lambda: self.cw.get(refs, timeout=req.get("timeout"))
            )
        except Exception as e:
            return {"error": serialization.dumps(e)}
        return {"values": serialization.dumps(values)}

    async def rpc_put(self, req):
        value = serialization.loads(req["value"])
        ref = await self._off_loop(lambda: self.cw.put(value))
        return {"id": self._pin(req.get("client_id", ""), [ref])[0]}

    async def rpc_wait(self, req):
        refs = self._lookup(req.get("client_id", ""), req["ids"], req.get("owners"))
        ready, not_ready = await self._off_loop(
            lambda: self.cw.wait(
                refs,
                num_returns=req.get("num_returns", 1),
                timeout=req.get("timeout"),
                fetch_local=req.get("fetch_local", True),
            )
        )
        return {"ready": [r.hex() for r in ready], "not_ready": [r.hex() for r in not_ready]}

    async def rpc_release(self, req):
        with self._lock:
            table = self._refs.get(req.get("client_id", ""), {})
            for i in req.get("ids", []):
                table.pop(i, None)
        return {"ok": True}

    async def rpc_gcs_call(self, req):
        return await self._off_loop(
            lambda: self.cw.gcs.call(req["method"], req.get("payload") or {})
        )

    def stop(self):
        self.server.stop()
