"""Ray-Client-style remote driver.

Analog of the reference's Ray Client (python/ray/util/client/: worker.py:81
thin client, util/client/server/ proxy): ``ray_tpu.init(address=
"ray_tpu://host:port")`` (or ``util.client.connect``) attaches this process
as a THIN client — no local raylet, no shared-memory arena; every API call is
proxied over one TCP connection to a client server on the head node, which
executes it in a real driver attached to the cluster.

Use when the driver machine is not a cluster node (laptop → TPU pod). The
public API (`remote/get/put/wait/actors/kill/get_actor/nodes`, the GCS-backed
state/placement-group helpers) works unchanged; anything needing local shm
(zero-copy plasma reads) transparently falls back to value shipping over the
connection.
"""

from ray_tpu.util.client.client import ClientContext, ClientCoreWorker, connect  # noqa: F401
from ray_tpu.util.client.server import ClientServer  # noqa: F401
