"""Thin-client CoreWorker.

Implements the slice of the CoreWorker surface the public API touches
(submit_task / create_actor / submit_actor_task / get / put / wait /
register_ref / gcs.call) by proxying every call to a ClientServer on the head
node. Installed into worker_context so `ray_tpu.remote/get/put/...` work
unchanged (reference: util/client/worker.py:81 + client-mode API swap).

Ref lifetime: the server holds one pin per (client, object id). The client
counts its local ObjectRef instances per id; when the LAST local instance for
an id is GC'd the id is queued for release, and queued releases ride along
with the next API call — ``__del__`` never blocks on the network.
"""

from __future__ import annotations

import threading
import uuid

from ray_tpu._private import serialization
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.rpc import ConnectionLost, RpcClient
from ray_tpu.object_ref import ObjectRef


class _GcsProxy:
    def __init__(self, client: "ClientCoreWorker"):
        self._client = client

    def call(self, method: str, payload: dict | None = None, **kwargs) -> dict:
        # timeout/retries knobs apply to the server's GCS hop, which the
        # proxy cannot steer; accept and drop them so direct-mode callers
        # (e.g. ray_tpu.kill's bounded single attempt) work unchanged.
        return self._client._call("client_gcs_call", {"method": method, "payload": payload or {}})


class ClientCoreWorker:
    mode = "CLIENT"

    # Methods that get a req_id so a reconnect-replay is at-most-once on
    # the server (reference: dataclient acked sequence numbers). get and
    # put_begin are included because their responses create server-side
    # stream state that a blind replay would duplicate.
    _MUTATING = {
        "client_task", "client_create_actor", "client_actor_call",
        "client_put", "client_put_commit", "client_put_begin", "client_get",
    }

    def __init__(self, address: tuple, namespace: str = "",
                 reconnect_retries: int = 5, reconnect_backoff_s: float = 0.5):
        self._address = tuple(address)
        self._rpc = self._new_rpc()
        self._client_id = uuid.uuid4().hex
        self.namespace = namespace
        self.gcs = _GcsProxy(self)
        self._released: list[str] = []
        self._local_counts: dict[str, int] = {}
        self._release_lock = threading.Lock()
        self._req_seq = 0
        self._reconnect_retries = reconnect_retries
        self._reconnect_backoff_s = reconnect_backoff_s
        self._reconnect_lock = threading.Lock()
        self._reconnects = 0  # observability; tests assert on it
        # Keepalive: the server reaps sessions by last_seen; an idle-but-
        # connected client must not lose its pins, so ping periodically.
        self._keepalive_stop = threading.Event()
        self._keepalive = threading.Thread(
            target=self._keepalive_loop, daemon=True, name="ray-client-keepalive"
        )
        self._keepalive.start()

    def _keepalive_loop(self, interval_s: float = 60.0):
        while not self._keepalive_stop.wait(interval_s):
            try:
                self._rpc.call("client_ping", {"client_id": self._client_id})
            except Exception:
                pass  # next real call reconnects; the TTL is the backstop

    # -- plumbing -------------------------------------------------------
    def _new_rpc(self) -> RpcClient:
        """Transport with its internal retry disabled: the transport layer
        re-sends on ConnectionLost AND on timeout, which would both multiply
        this class's own reconnect loop and replay timed-out requests —
        retries belong to exactly one layer, and _call owns them here."""
        rpc = RpcClient(self._address, label="ray-client")
        rpc._retries = 0
        return rpc

    def _next_req_id(self) -> str:
        with self._release_lock:
            self._req_seq += 1
            return f"{self._client_id}:{self._req_seq}"

    def _call(self, method: str, payload: dict, timeout: float | None = None):
        """RPC with the client id and any queued ref releases piggybacked.
        On a lost connection the SAME request (same req_id) is replayed
        after reconnecting — the server's response cache makes mutating
        calls at-most-once (reference: client reconnect grace period)."""
        import time as _time

        with self._release_lock:
            batch, self._released = self._released, []
        payload["client_id"] = self._client_id
        if method in self._MUTATING and "req_id" not in payload:
            payload["req_id"] = self._next_req_id()
        if batch:
            try:
                self._rpc.call("client_release", {"client_id": self._client_id, "ids": batch})
            except Exception:
                with self._release_lock:
                    self._released = batch + self._released
        last_err: Exception | None = None
        for attempt in range(self._reconnect_retries + 1):
            rpc = self._rpc
            try:
                return rpc.call(method, payload, timeout=timeout)
            except TimeoutError:
                # A timeout is an application outcome, not a transport
                # failure — tearing down a healthy connection and replaying
                # would multiply the caller's wait.
                raise
            except (ConnectionError, OSError) as e:
                last_err = e
            except ConnectionLost as e:
                # The RPC layer's in-flight-loss error; application-level
                # RpcErrors (handler exceptions) are NOT retriable.
                last_err = e
            if attempt == self._reconnect_retries:
                break
            _time.sleep(self._reconnect_backoff_s * (attempt + 1))
            # Reconnect once per failed transport object: if another thread
            # already swapped in a fresh client, reuse it instead of closing
            # the connection it just opened.
            with self._reconnect_lock:
                if self._rpc is rpc:
                    try:
                        rpc.close()
                    except Exception:
                        pass
                    self._rpc = self._new_rpc()
                    self._reconnects += 1
        raise ConnectionError(
            f"ray client lost its server after {self._reconnect_retries} "
            f"reconnect attempts: {last_err}"
        )

    @staticmethod
    def _pack_args(args, kwargs) -> bytes:
        return serialization.dumps((tuple(args), dict(kwargs or {})))

    def _refs_from_ids(self, ids: list[str]) -> list[ObjectRef]:
        # No owner addr: these ids are pinned in the server's registry for as
        # long as we hold them, so the server never needs owner resolution.
        return [ObjectRef(ObjectID.from_hex(i)) for i in ids]

    # -- task / actor API ----------------------------------------------
    def submit_task(self, func, args, kwargs, **opts):
        resp = self._call(
            "client_task",
            {
                "func": serialization.dumps(func),
                "args": self._pack_args(args, kwargs),
                "opts": _plain_opts(opts),
            },
        )
        if "gen" in resp:
            # num_returns="streaming" through the proxy (reference:
            # util/client/worker.py streaming generators): item refs are
            # pulled one at a time, so iteration overlaps the producer and
            # the server buffers no values for slow consumers.
            return ClientObjectRefGenerator(self, resp["gen"])
        return self._refs_from_ids(resp["ids"])

    def stream_next(self, gen_id: str, index: int, timeout: float | None = None):
        """Pull item `index` of a remote streaming generator; returns the
        pinned ref id (hex). Raises StopIteration / GetTimeoutError /
        the producer's error like the in-cluster generator."""
        import time as _time

        from ray_tpu.exceptions import GetTimeoutError

        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            per_call = 10.0
            if deadline is not None:
                per_call = max(0.05, min(per_call, deadline - _time.monotonic()))
            resp = self._call(
                "client_gen_next",
                {"gen": gen_id, "index": index, "timeout": per_call},
                timeout=per_call + 30.0,
            )
            if resp.get("done"):
                raise StopIteration
            if "error" in resp:
                raise serialization.loads(resp["error"])
            if resp.get("pending"):
                if deadline is not None and _time.monotonic() >= deadline:
                    raise GetTimeoutError(
                        f"stream item {index} not produced within {timeout}s"
                    )
                continue
            return resp["id"]

    def create_actor(self, cls, args, kwargs, **opts):
        resp = self._call(
            "client_create_actor",
            {
                "cls": serialization.dumps(cls),
                "args": self._pack_args(args, kwargs),
                "opts": _plain_opts(opts),
            },
        )
        return resp["info"]

    def submit_actor_task(self, actor_id, method_name, args, kwargs, num_returns=1, max_task_retries=0):
        resp = self._call(
            "client_actor_call",
            {
                "actor_id": actor_id,
                "method": method_name,
                "args": self._pack_args(args, kwargs),
                "num_returns": num_returns,
                "max_task_retries": max_task_retries,
            },
        )
        return self._refs_from_ids(resp["ids"])

    # -- object API -----------------------------------------------------
    def get(self, refs, timeout=None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        resp = self._call(
            "client_get",
            {
                "ids": [r.hex() for r in ref_list],
                "owners": [r.owner_addr for r in ref_list],
                "timeout": timeout,
            },
            timeout=(timeout + 30) if timeout else None,
        )
        if resp.get("error") is not None:
            raise serialization.loads(resp["error"])
        if "stream" in resp:
            # Data channel: pull the value in bounded chunks, sequentially
            # (the pull cadence IS the backpressure — the server holds one
            # blob, the wire carries one chunk at a time).
            parts = []
            offset = 0
            while True:
                c = self._call(
                    "client_get_chunk", {"stream": resp["stream"], "offset": offset}
                )
                if c.get("error") is not None:
                    raise serialization.loads(c["error"])
                parts.append(c["data"])
                offset += len(c["data"])
                if c["done"]:
                    break
            # Ack completion so the server frees the blob now rather than
            # at session TTL (chunks stay replayable until this lands).
            try:
                self._call("client_stream_done", {"stream": resp["stream"]})
            except Exception:
                pass
            values = serialization.loads(b"".join(parts))
        else:
            values = serialization.loads(resp["values"])
        return values[0] if single else values

    # Values above this upload through the chunked data channel.
    _PUT_STREAM_THRESHOLD = 1024 * 1024

    def put(self, value, tensor_transport: str | None = None) -> ObjectRef:
        if tensor_transport:
            # Device residency means the PUTTING process holds the array for
            # later out-of-band transfer; a thin client disconnects and has
            # no serving plane — the option would silently degrade to a host
            # copy, so reject it loudly.
            raise NotImplementedError(
                "tensor_transport= is not supported over the ray_tpu:// thin "
                "client: the client process cannot serve as a device-object "
                "holder. put() from a driver or actor on the cluster instead."
            )
        blob = serialization.dumps(value)
        if len(blob) <= self._PUT_STREAM_THRESHOLD:
            resp = self._call("client_put", {"value": blob})
            return self._refs_from_ids([resp["id"]])[0]
        begin = self._call("client_put_begin", {})
        sid = begin["stream"]
        chunk_size = int(begin.get("chunk_size", 256 * 1024))
        try:
            for seq, off in enumerate(range(0, len(blob), chunk_size)):
                c = self._call(
                    "client_put_chunk",
                    {"stream": sid, "seq": seq, "data": blob[off:off + chunk_size]},
                )
                if c.get("error") is not None:
                    raise serialization.loads(c["error"])
        except BaseException:
            # Don't leave a partial multi-MB buffer pinned server-side
            # until the stream TTL.
            try:
                self._call("client_put_abort", {"stream": sid})
            except Exception:
                pass
            raise
        resp = self._call("client_put_commit", {"stream": sid})
        if resp.get("error") is not None:
            raise serialization.loads(resp["error"])
        return self._refs_from_ids([resp["id"]])[0]

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        by_id = {r.hex(): r for r in refs}
        resp = self._call(
            "client_wait",
            {
                "ids": list(by_id),
                "owners": [by_id[i].owner_addr for i in by_id],
                "num_returns": num_returns,
                "timeout": timeout,
                "fetch_local": fetch_local,
            },
            timeout=(timeout + 30) if timeout else None,
        )
        return (
            [by_id[i] for i in resp["ready"]],
            [by_id[i] for i in resp["not_ready"]],
        )

    # -- ref bookkeeping (ObjectRef.__init__/__del__ hooks) -------------
    def register_ref(self, ref: ObjectRef):
        with self._release_lock:
            self._local_counts[ref.hex()] = self._local_counts.get(ref.hex(), 0) + 1

    def deregister_ref(self, ref: ObjectRef):
        # Queue-only (no RPC): __del__ can fire on any thread, including the
        # IO loop thread, where a blocking call would deadlock the process.
        with self._release_lock:
            i = ref.hex()
            n = self._local_counts.get(i, 0) - 1
            if n > 0:
                self._local_counts[i] = n
            else:
                self._local_counts.pop(i, None)
                self._released.append(i)

    def as_future(self, ref: ObjectRef):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            try:
                fut.set_result(self.get(ref))
            except Exception as e:
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def shutdown(self, job_state: str | None = None):
        self._keepalive_stop.set()
        with self._release_lock:
            batch, self._released = self._released, []
        try:
            if batch:
                self._rpc.call("client_release", {"client_id": self._client_id, "ids": batch})
            # Explicit goodbye frees the server session immediately instead
            # of waiting out the reconnect grace TTL.
            self._rpc.call("client_disconnect", {"client_id": self._client_id})
        except Exception:
            pass
        self._rpc.close()


def _plain_opts(opts: dict) -> dict:
    """Options must be msgpack-able; drop Nones."""
    return {k: v for k, v in opts.items() if v is not None}


class ClientObjectRefGenerator:
    """Client-side iterator over a remote streaming task's returns (the
    proxy analog of ObjectRefGenerator): each __next__ pulls one pinned item
    ref from the server, overlapping iteration with the remote producer."""

    def __init__(self, cw: ClientCoreWorker, gen_id: str):
        self._cw = cw
        self._gen_id = gen_id
        self._index = 0

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        oid_hex = self._cw.stream_next(self._gen_id, self._index)
        self._index += 1
        return self._cw._refs_from_ids([oid_hex])[0]

    def next_with_timeout(self, timeout: float) -> ObjectRef:
        oid_hex = self._cw.stream_next(self._gen_id, self._index, timeout=timeout)
        self._index += 1
        return self._cw._refs_from_ids([oid_hex])[0]


class ClientContext:
    def __init__(self, core_worker: ClientCoreWorker):
        self._cw = core_worker

    def disconnect(self):
        from ray_tpu._private import worker_context

        self._cw.shutdown()
        worker_context.set_core_worker(None)


def connect(address: str, namespace: str = "") -> ClientContext:
    """Attach this process as a thin client. ``address`` is
    ``host:port`` of the head's client server (also accepts the
    ``ray_tpu://host:port`` form)."""
    from ray_tpu._private import worker_context

    if address.startswith("ray_tpu://"):
        address = address[len("ray_tpu://") :]
    host, port = address.rsplit(":", 1)
    if worker_context.get_core_worker_if_initialized() is not None:
        raise RuntimeError("already connected; call ray_tpu.shutdown() first")
    cw = ClientCoreWorker((host, int(port)), namespace=namespace)
    # Probe the connection early for a clear error.
    cw.gcs.call("get_nodes")
    worker_context.set_core_worker(cw)
    return ClientContext(cw)
