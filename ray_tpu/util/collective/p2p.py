"""Point-to-point transfer plane for collective groups and channel payloads.

Analog of the reference's ``ray.util.collective`` ``send``/``recv``
(python/ray/util/collective/collective.py:531/594): a 2-party transfer
between two ranks of an initialized group, OUT OF BAND with respect to the
shm object store — this is the wire the device-object plane
(experimental/device_object/) rides for actor-to-actor tensor handoff.

Two rendezvous mechanisms share this seam:

- **GCS-KV mailbox** (``mailbox_send``/``mailbox_recv``): the group-rank
  path. The sender posts the serialized value under a single-use tagged key
  in the group's GCS KV (the same control plane the CPU ring collectives
  and the TPU world bootstrap already use); the receiver polls it down and
  deletes it. Needs no peer address — ranks are the only names.
- **Direct mailbox** (``direct_send``/``direct_recv`` + ``P2PInbox``): the
  address-direct path the descriptor channel plane (PR 12,
  experimental/channel/device_envelope.py) streams microbatch payloads
  over. The sender pushes chunked one-way ``p2p_data`` frames straight at
  the consumer core worker's RPC server (no GCS round trips, no polling);
  the receiver waits on its process-local inbox. Keys are caller-scoped
  (``chdev/<cid>/<seq>`` for channel slots), delivery is at-most-once —
  callers fall back to a pull (resolve.py) on a missed grace window.

Device arrays serialize through ``_private/serialization`` so sharding
layout survives either hop and the receiver's ``device_put`` lands shards
back on the matching devices.

On real TPU hardware the collectives INSIDE jitted programs ride ICI; both
host mailboxes are correctness stand-ins until jax exposes a cross-process
device-to-device transfer API in this image (the reference's NCCL p2p
equivalent). The seams are ``TpuCollectiveGroup.send/recv`` and
``direct_send/direct_recv`` — swap in the device path there without
touching any caller.
"""

from __future__ import annotations

import threading
import time

from ray_tpu._private.concurrency import any_thread, blocking, loop_only
from ray_tpu.util.collective.types import ReduceOp

_POLL_S = 0.003
# Direct-mailbox chunk size: one-way frames on the existing worker pipe,
# bounded like the chunked object-push path.
_DIRECT_CHUNK_BYTES = 512 * 1024
# Unclaimed inbox entries (consumer died / tore down between the eager push
# and the read) are swept after this age so a long-lived worker's inbox
# cannot grow without bound on lost readers.
_INBOX_SWEEP_AGE_S = 180.0


def mailbox_key(group_name: str, src_rank: int, dst_rank: int, tag: str) -> str:
    """Public so senders can janitor abandoned transfers (a recv that timed
    out or died never deletes the key; without cleanup the serialized
    payload would sit in the GCS KV forever)."""
    return f"collective/{group_name}/p2p/{src_rank}->{dst_rank}/{tag}"


_key = mailbox_key


@blocking
def mailbox_send(gcs, group_name: str, src_rank: int, dst_rank: int, tag: str, value) -> int:
    """Serialize ``value`` and post it for ``dst_rank``; returns byte size.
    Single-use: the receiver deletes the key after pickup."""
    from ray_tpu._private import serialization

    data = serialization.dumps(value)
    gcs.call(
        "kv_put",
        {"key": _key(group_name, src_rank, dst_rank, tag), "value": data},
    )
    return len(data)


@blocking
def mailbox_recv(gcs, group_name: str, src_rank: int, dst_rank: int, tag: str, timeout: float = 120.0):
    """Block until the tagged value from ``src_rank`` arrives; deserializes
    (device arrays reassemble with their original sharding) and deletes the
    mailbox key."""
    from ray_tpu._private import serialization

    key = _key(group_name, src_rank, dst_rank, tag)
    deadline = time.monotonic() + timeout
    while True:
        resp = gcs.call("kv_get", {"key": key})
        if resp.get("found"):
            gcs.call("kv_del", {"key": key})
            return serialization.loads(resp["value"])
        if time.monotonic() > deadline:
            from ray_tpu.exceptions import CollectiveTimeoutError

            raise CollectiveTimeoutError(
                f"p2p recv on group {group_name!r} tag {tag!r} from rank "
                f"{src_rank} timed out after {timeout}s",
                group=group_name, ranks=[src_rank], tag=tag,
            )
        time.sleep(_POLL_S)


# ---------------------------------------------------------------------------
# Direct mailbox (address-directed, no GCS round trips)
# ---------------------------------------------------------------------------


class P2PInbox:
    """Per-process landing zone for ``p2p_data`` frames (one per core
    worker; the ``rpc_p2p_data`` handler deposits into it). Chunked frames
    reassemble here; a waiter blocks on a per-key event. All state behind
    one lock; methods never block — deposit runs on the IO loop."""

    def __init__(self):
        from ray_tpu._private.ids import BoundedIdSet

        self._lock = threading.Lock()
        self._parts: dict[str, dict] = {}    # key -> {idx: bytes}
        self._parts_ts: dict[str, float] = {}  # key -> first-chunk monotonic ts
        self._done: dict[str, tuple] = {}    # key -> (bytes, monotonic ts)
        self._waiters: dict[str, threading.Event] = {}
        self._deposits = 0
        # Recently-COMPLETED keys: delivery of p2p_data frames is
        # at-least-once under connection blips (and chaos dup injection),
        # and a duplicate chunk arriving AFTER its payload completed used
        # to re-open a partial reassembly that could never complete
        # (leaked until the age sweep) — or, for a single-chunk payload,
        # resurrect a consumed ``_done`` entry, breaking the at-most-once
        # take() contract. Tombstoned keys drop silently.
        self._completed = BoundedIdSet(cap=1024)

    @any_thread
    def deposit(self, key: str, idx: int, total: int, data: bytes) -> bool:
        """Returns True when the payload is COMPLETE (all chunks landed).
        Idempotent under duplicated/reordered chunks: a repeat of a
        still-assembling chunk overwrites in place, and any chunk of an
        already-completed key is dropped."""
        complete = False
        with self._lock:
            if key in self._completed or key in self._done:
                self._deposits += 1
                return False  # duplicate of a completed payload
            parts = self._parts.get(key)
            if parts is None:
                parts = self._parts[key] = {}
                self._parts_ts[key] = time.monotonic()
            parts[idx] = data
            if len(parts) == total:
                self._completed.add(key)
                self._parts.pop(key)
                self._parts_ts.pop(key, None)
                self._done[key] = (
                    data if total == 1 else b"".join(parts[i] for i in range(total)),
                    time.monotonic(),
                )
                waiter = self._waiters.get(key)
                if waiter is not None:
                    waiter.set()
                complete = True
            self._deposits += 1
            sweep = self._deposits & 255 == 0
        if sweep:
            self.sweep()
        return complete

    @any_thread
    def take(self, key: str) -> bytes | None:
        with self._lock:
            entry = self._done.pop(key, None)
            return None if entry is None else entry[0]

    @any_thread
    def _waiter(self, key: str) -> threading.Event:
        with self._lock:
            if key in self._done:
                ev = threading.Event()
                ev.set()
                return ev
            ev = self._waiters.get(key)
            if ev is None:
                ev = self._waiters[key] = threading.Event()
            return ev

    @any_thread
    def _drop_waiter(self, key: str) -> None:
        with self._lock:
            self._waiters.pop(key, None)

    @any_thread
    def completed(self, key: str) -> bool:
        """True once every chunk of ``key`` has landed — stays true after a
        take() (the tombstone remembers), which is exactly the delivery
        acknowledgement ``p2p_ack`` needs: 'the payload reached this
        process', not 'it is still unclaimed'."""
        with self._lock:
            return key in self._completed or key in self._done

    @blocking
    def wait_complete(self, key: str, timeout: float) -> bool:
        """Block (bounded) until ``key``'s payload has fully landed. Used by
        the ``p2p_ack`` RPC: the ack rides the same connection as the data
        frames, but handlers are dispatched as tasks, so a bounded wait
        covers the (rare) reorder instead of trusting scheduling order."""
        deadline = time.monotonic() + timeout
        ev = self._waiter(key)
        try:
            while True:
                if self.completed(key):
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                ev.wait(min(0.05, remaining))
                ev.clear()
        finally:
            self._drop_waiter(key)

    @any_thread
    def purge_prefix(self, prefix: str) -> int:
        """Drop every entry/partial under a key prefix (channel teardown:
        cids are dead, nobody will ever take these payloads)."""
        with self._lock:
            victims = [k for k in self._done if k.startswith(prefix)]
            for k in victims:
                del self._done[k]
            for k in [k for k in self._parts if k.startswith(prefix)]:
                del self._parts[k]
                self._parts_ts.pop(k, None)
                victims.append(k)
            return len(victims)

    @any_thread
    def sweep(self, max_age_s: float = _INBOX_SWEEP_AGE_S) -> int:
        """Age out unclaimed payloads AND stale partial reassemblies (a
        producer that died mid-push leaves chunks that will never
        complete — lost writers must not leak any more than lost
        readers)."""
        cutoff = time.monotonic() - max_age_s
        with self._lock:
            victims = [k for k, (_, ts) in self._done.items() if ts < cutoff]
            for k in victims:
                del self._done[k]
            stale = [k for k, ts in self._parts_ts.items() if ts < cutoff]
            for k in stale:
                self._parts.pop(k, None)
                del self._parts_ts[k]
            return len(victims) + len(stale)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._done),
                "partials": len(self._parts),
                "bytes": sum(len(d) for d, _ in self._done.values()),
            }


@any_thread
def direct_send(cw, addr: tuple, key: str, data: bytes) -> None:
    """Push serialized payload bytes at ``addr``'s inbox under ``key`` as
    chunked ONE-WAY frames on the existing worker pipe (fire-and-forget,
    like the channel doorbell): zero round trips on the hot path. Loss is
    recoverable — the consumer's grace window expires and it falls back to
    the pull path (resolve.py), where the holder still pins the payload."""
    client = cw._owner_client(tuple(addr))
    total = max(1, (len(data) + _DIRECT_CHUNK_BYTES - 1) // _DIRECT_CHUNK_BYTES)

    async def _push_all():
        try:
            for i in range(total):
                await client.apush(
                    "p2p_data",
                    {
                        "key": key,
                        "idx": i,
                        "total": total,
                        "data": data[
                            i * _DIRECT_CHUNK_BYTES : (i + 1) * _DIRECT_CHUNK_BYTES
                        ],
                    },
                )
        except Exception:
            pass  # consumer unreachable: its grace window handles it

    cw._io.spawn(_push_all())


# ---------------------------------------------------------------------------
# Modeled egress link (bench-only)
# ---------------------------------------------------------------------------

# When set, every outbound payload chunk on the group plane (root fan-out,
# relay forwards, reduce up-pushes) serializes through ONE per-process
# asyncio.Lock and sleeps bytes/bandwidth. This is the PR 10 convention
# (PERF_NOTES.md): loopback has no per-NIC budget, so an unthrottled A/B
# cannot show what a relay tree buys — the modeled link is the honest
# stand-in for the per-host egress bandwidth the tree divides on a real
# fleet. Off (None) outside the bench.
_EGRESS_BPS: float | None = None
_EGRESS_LOCK = None  # created lazily on the IO loop


@any_thread
def set_modeled_egress(mib_per_s: float | None) -> None:
    """Install (or clear, with None) the modeled per-process egress link."""
    global _EGRESS_BPS
    _EGRESS_BPS = None if not mib_per_s else float(mib_per_s) * 1024 * 1024


async def _gate_egress(nbytes: int) -> None:
    global _EGRESS_LOCK
    bps = _EGRESS_BPS
    if not bps:
        return
    import asyncio

    if _EGRESS_LOCK is None:
        _EGRESS_LOCK = asyncio.Lock()
    async with _EGRESS_LOCK:
        await asyncio.sleep(nbytes / bps)


# ---------------------------------------------------------------------------
# Binomial relay tree
# ---------------------------------------------------------------------------


def _binomial_children(pos: int, n: int) -> list[int]:
    """Child POSITIONS of ``pos`` in the binomial broadcast tree over ``n``
    positions rooted at 0: ``pos + 2**k`` for every power of two strictly
    greater than ``pos`` (depth ceil(log2 n), root degree floor(log2 n) —
    the classic recursive-doubling shape, so the root writes O(log K)
    streams instead of K)."""
    kids = []
    step = 1
    while step <= pos:
        step <<= 1
    while pos + step < n:
        kids.append(pos + step)
        step <<= 1
    return kids


class RelayTable:
    """Per-process cut-through relay sessions for TREE group broadcasts
    (one per core worker; ``rpc_p2p_data`` feeds it when a chunk frame
    carries a ``relay`` spec). Each landed chunk is forwarded to this
    member's own tree children the moment the contiguous prefix reaches it
    — the ``push_manager.stream_from_session`` watermark pattern, NOT
    store-and-forward, so the next hop starts before this one finishes.
    All state lives on the IO loop (deposits and forwarder tasks alike):
    no lock. The inbox keeps its own copy for the local take()."""

    def __init__(self):
        from ray_tpu._private.ids import BoundedIdSet

        self._sessions: dict[str, _RelaySession] = {}
        # Delivery is at-least-once under connection blips (and chaos dup
        # injection): a duplicate chunk landing after the session finished
        # must not resurrect it.
        self._finished = BoundedIdSet(cap=512)

    @loop_only
    def feed(self, cw, key: str, idx: int, total: int, data: bytes, relay: dict) -> None:
        st = self._sessions.get(key)
        if st is None:
            if key in self._finished:
                return
            st = self._sessions[key] = _RelaySession(key, int(total), relay)
            st.start(cw, self)
        st.chunks[idx] = data
        while st.contig in st.chunks:
            st.contig += 1
        st.event.set()

    @loop_only
    def finish(self, key: str) -> None:
        if self._sessions.pop(key, None) is not None:
            self._finished.add(key)

    def stats(self) -> dict:
        return {"sessions": len(self._sessions)}


class _RelaySession:
    """One in-flight relay: the chunks as they land, the contiguous-prefix
    watermark, and a forwarder task per tree child racing it."""

    __slots__ = ("key", "total", "relay", "chunks", "contig", "event",
                 "pending", "bytes_forwarded", "forwarders", "watchdog")

    def __init__(self, key: str, total: int, relay: dict):
        import asyncio

        self.key = key
        self.total = total
        self.relay = relay
        self.chunks: dict[int, bytes] = {}
        self.contig = 0
        self.event = asyncio.Event()
        self.pending = len(relay.get("children") or [])
        self.bytes_forwarded = 0
        self.forwarders: list = []
        self.watchdog = None

    def start(self, cw, table: RelayTable) -> None:
        import asyncio

        for child in self.relay.get("children") or []:
            self.forwarders.append(
                asyncio.ensure_future(_relay_forward(cw, table, self, child))
            )
        self.watchdog = asyncio.ensure_future(_relay_watchdog(table, self))


async def _relay_forward(cw, table: RelayTable, st: _RelaySession, child: dict) -> None:
    """Forward every chunk of ``st`` to ONE tree child as it becomes
    contiguous. A dead child is swallowed on purpose: the ROOT's per-rank
    ack round is what detects the orphaned subtree and re-delivers it
    directly (flat fallback) — a relay has no policy of its own."""
    try:
        client = cw._owner_client(tuple(child["addr"]))
        sub = child.get("children") or []
        relay = {"rank": child["rank"], "children": sub} if sub else None
        for idx in range(st.total):
            while st.contig <= idx:
                st.event.clear()
                await st.event.wait()
            data = st.chunks[idx]
            payload = {"key": st.key, "idx": idx, "total": st.total, "data": data}
            if relay is not None:
                payload["relay"] = relay
            await _gate_egress(len(data))
            await client.apush("p2p_data", payload)
            st.bytes_forwarded += len(data)
            COLL.relay_bytes += len(data)
        COLL.relay_forwards += 1
    except Exception:
        pass
    finally:
        st.pending -= 1
        if st.pending <= 0:
            _relay_finish(table, st)


async def _relay_watchdog(table: RelayTable, st: _RelaySession) -> None:
    """A relay whose payload never completes (root died mid-push) must not
    park its forwarders and chunks forever."""
    import asyncio

    await asyncio.sleep(_INBOX_SWEEP_AGE_S)
    for t in st.forwarders:
        if not t.done():
            t.cancel()
    _relay_finish(table, st)


def _relay_finish(table: RelayTable, st: _RelaySession) -> None:
    if table._sessions.get(st.key) is not st:
        return  # already recorded (forwarder finallys race the watchdog)
    if st.watchdog is not None and not st.watchdog.done():
        st.watchdog.cancel()
    try:
        from ray_tpu._private import flight_recorder

        parts = st.key.split("/", 2)  # collbcast/<group>/<tag>
        group = parts[1] if len(parts) == 3 else ""
        tag = parts[2] if len(parts) == 3 else st.key
        flight_recorder.record(
            "coll_relay",
            f"{tag[:12]}:{group}:{st.relay.get('rank')}:"
            f"{len(st.relay.get('children') or [])}:{st.bytes_forwarded}",
        )
    except Exception:
        pass
    table.finish(st.key)


class ChunkStreams:
    """Landing pads for tree-REDUCE partial streams (``collred/`` keys).
    Unlike :class:`P2PInbox`, chunks are consumed ONE AT A TIME by the
    member combining them into its own slice (cut-through combine at every
    relay hop) — nothing ever reassembles into a full payload. Combiners
    run on executor threads while deposits land on the IO loop, so state
    sits behind a lock with per-key events."""

    def __init__(self):
        self._lock = threading.Lock()
        self._chunks: dict[str, dict[int, bytes]] = {}
        self._events: dict[str, threading.Event] = {}
        self._ts: dict[str, float] = {}
        self._deposits = 0

    @any_thread
    def deposit(self, key: str, idx: int, data: bytes) -> None:
        with self._lock:
            self._chunks.setdefault(key, {})[idx] = data
            self._ts[key] = time.monotonic()
            ev = self._events.get(key)
            if ev is None:
                ev = self._events[key] = threading.Event()
            self._deposits += 1
            sweep = self._deposits & 255 == 0
        ev.set()
        if sweep:
            self.sweep()

    @blocking
    def wait_chunk(self, key: str, idx: int, deadline: float) -> bytes | None:
        """Pop chunk ``idx`` of stream ``key`` (each chunk is consumed
        exactly once), or None once ``deadline`` passes."""
        while True:
            with self._lock:
                ev = self._events.get(key)
                if ev is None:
                    ev = self._events[key] = threading.Event()
                ev.clear()  # before the check: a deposit between check and
                # wait must leave the event set
                d = self._chunks.get(key)
                if d is not None and idx in d:
                    return d.pop(idx)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            ev.wait(min(0.05, remaining))

    @any_thread
    def purge(self, key: str) -> None:
        with self._lock:
            self._chunks.pop(key, None)
            self._events.pop(key, None)
            self._ts.pop(key, None)

    @any_thread
    def sweep(self, max_age_s: float = _INBOX_SWEEP_AGE_S) -> int:
        """Age out streams nobody is combining (a reduce that raised on
        this member leaves its children's later chunks behind)."""
        cutoff = time.monotonic() - max_age_s
        with self._lock:
            stale = [k for k, ts in self._ts.items() if ts < cutoff]
            for k in stale:
                self._chunks.pop(k, None)
                self._events.pop(k, None)
                del self._ts[k]
            return len(stale)

    def stats(self) -> dict:
        with self._lock:
            return {
                "streams": len(self._chunks),
                "chunks": sum(len(d) for d in self._chunks.values()),
            }


# ---------------------------------------------------------------------------
# Group broadcast (ONE group op fanning a payload to every member)
# ---------------------------------------------------------------------------

# Per-member budget for the delivery acknowledgement round trip. The ack is
# what turns the fire-and-forget chunk frames into a delivery receipt: it
# rides the same FIFO connection as the data, so by the time the member
# answers, its inbox either has the payload or never will.
_BCAST_ACK_S = 10.0


class _CollStats:
    """Plain-int hot-path counters for the group-collective plane, folded
    into ``ray_tpu_collective_*`` instruments by self_metrics at flush time
    (same pattern as DEVOBJ_STATS — no instrument lock on the send path)."""

    __slots__ = (
        "bcast_sends",        # group broadcasts fanned out by this process
        "bcast_send_bytes",   # serialized payload bytes × delivered ranks
        "bcast_recvs",        # descriptor resolves served from a broadcast
        "bcast_fallbacks",    # per-rank deliveries that fell back to the GCS mailbox
        "bcast_failed_ranks", # ranks a broadcast could not deliver to
        "timeouts",           # typed collective timeouts raised here
        "tree_sends",         # broadcasts that rode the binomial relay tree
        "bcast_retries",      # ranks re-delivered directly after a relay failure
        "root_egress_bytes",  # payload bytes THIS process pushed as broadcast root
        "relay_forwards",     # relay legs completed here (all chunks to one child)
        "relay_bytes",        # payload bytes forwarded mid-tree by this process
        "reduce_sends",       # tree-reduce participations by this process
        "reduce_bytes",       # partial-combine bytes pushed up the tree
        "allreduces",         # allreduce participations (reduce + down-broadcast)
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, 0)


COLL = _CollStats()


def bcast_key(group_name: str, tag: str) -> str:
    """Inbox key of a group-broadcast payload. Deterministic from (group,
    tag) and deliberately RANK-FREE: inboxes are per-process, so every
    member gets the same key — which is what lets the fan-out encode each
    chunk frame once and write identical bytes to every connection.
    Device-object broadcasts use the object id as the tag, so one broadcast
    per object id (the inbox tombstones a repeated key as a duplicate)."""
    return f"collbcast/{group_name}/{tag}"


def member_addr_key(group_name: str, rank: int) -> str:
    return f"collective/{group_name}/addr/{rank}"


def register_member_addr(gcs, group_name: str, rank: int, addr) -> None:
    """Publish this member's core-worker RPC address so a group broadcast
    can push payload frames straight at its inbox (no GCS mailbox on the
    fan-out path). Best-effort: a member without a row just gets the
    mailbox fallback."""
    import json

    try:
        gcs.call(
            "kv_put",
            {"key": member_addr_key(group_name, rank), "value": json.dumps(list(addr)).encode()},
        )
    except Exception:
        pass


def unregister_member_addr(gcs, group_name: str, rank: int) -> None:
    try:
        gcs.call("kv_del", {"key": member_addr_key(group_name, rank)})
    except Exception:
        pass


@blocking
def fetch_member_addrs(gcs, group_name: str, world_size: int) -> dict:
    """{rank: (host, port)} for every member that registered an address.
    Callers cache this per group epoch — membership is static.

    The ``world_size`` lookups are batched CONCURRENTLY on the IO loop
    (the serial per-rank round scaled the fetch O(K) in GCS RTTs), and a
    GCS transport error PROPAGATES: a partitioned GCS must surface as a
    failure the caller can see, not read as "nobody registered" — which
    silently degraded every rank to the mailbox fallback. Only a per-row
    decode problem skips that one rank (it keeps the fallback path)."""
    import asyncio
    import json

    from ray_tpu._private.rpc import EventLoopThread

    keys = [member_addr_key(group_name, rank) for rank in range(world_size)]

    async def _fetch_all():
        return await asyncio.gather(*(gcs.acall("kv_get", {"key": k}) for k in keys))

    responses = EventLoopThread.get().run(_fetch_all(), timeout=30.0)
    addrs: dict = {}
    for rank, resp in enumerate(responses):
        if not resp.get("found"):
            continue
        try:
            addrs[rank] = tuple(json.loads(bytes(resp["value"]).decode()))
        except Exception:
            continue  # malformed row: that rank keeps the mailbox fallback
    return addrs


@blocking
def group_bcast_send(
    cw,
    gcs,
    group_name: str,
    src_rank: int,
    world_size: int,
    tag: str,
    value,
    member_addrs: dict | None = None,
    timeout: float = 30.0,
    mailbox_fallback: bool = True,
    topology: str = "tree",
) -> dict:
    """Fan ``value`` to every OTHER rank of the group as ONE group
    operation: one serialize, each chunk frame ENCODED ONCE
    (``RpcClient.pack_push_frame`` — the rank-free inbox key is what makes
    the bytes identical), every rank confirmed by a ``p2p_ack`` round trip.
    Ranks without a registered address fall back to the GCS-KV mailbox
    under the same logical tag. Never raises for a dead member: the result
    names it so the caller owns the policy —
    ``{"ok_ranks": [...], "fallback_ranks": [...], "failed": {rank: reason},
    "bytes": payload_bytes, "topology": ..., "root_egress_bytes": ...,
    "retried_ranks": [...]}``.

    ``topology="tree"`` (default, ≥2 addressed ranks): the root pushes
    chunk frames only to its BINOMIAL-TREE children, each frame carrying
    the child's relay spec; mid-tree members forward every chunk to their
    own children the moment it lands (cut-through — :class:`RelayTable`),
    so root egress is O(log K) streams instead of K. The per-member
    contract is unchanged: the root still acks EVERY rank directly, and
    any rank whose ack fails (a dead relay orphans its whole subtree) is
    retried DIRECTLY with a flat resend — one dead relay costs one named
    failure plus re-delivered orphans, not K/2 failed members. A rank
    still failing after the direct retry is named with its orphaned
    subtree. ``topology="flat"`` is PR 15's fan-out (every rank pushed
    from the root), kept for the bench A/B and as the retry primitive.

    This is the cpu-backend group op behind device_object.broadcast(); on
    TPU hardware the same seam maps to an ICI broadcast (tpu_group.py)."""
    import asyncio

    from ray_tpu._private import serialization
    from ray_tpu._private.rpc import RpcClient

    data = serialization.dumps(value)
    if member_addrs is None:
        member_addrs = fetch_member_addrs(gcs, group_name, world_size)
    total = max(1, (len(data) + _DIRECT_CHUNK_BYTES - 1) // _DIRECT_CHUNK_BYTES)
    targets = [r for r in range(world_size) if r != src_rank]
    addressed = [r for r in targets if r in member_addrs]
    use_tree = topology == "tree" and len(addressed) >= 2
    result = {
        "ok_ranks": [], "fallback_ranks": [], "failed": {}, "bytes": len(data),
        "topology": "tree" if use_tree else "flat",
        "root_egress_bytes": 0, "retried_ranks": [],
    }
    key = bcast_key(group_name, tag)
    chunks = [
        data[i * _DIRECT_CHUNK_BYTES : (i + 1) * _DIRECT_CHUNK_BYTES]
        for i in range(total)
    ]
    frames = [
        RpcClient.pack_push_frame(
            "p2p_data",
            {"key": key, "idx": i, "total": total, "data": chunks[i]},
        )
        for i in range(total)
    ]

    # Tree positions: [root] + addressed ranks in rank order — every rank
    # appears exactly once, so parent/child is a pure function of the
    # (group, membership) pair. ``subtree`` maps each rank to its
    # descendant ranks for the orphan annotation on failures.
    subtree: dict[int, list[int]] = {}
    root_specs: list[dict] = []
    if use_tree:
        order = [src_rank] + sorted(addressed)

        def _spec(pos: int) -> dict:
            rank = order[pos]
            kids = [_spec(c) for c in _binomial_children(pos, len(order))]
            desc: list[int] = []
            for k in kids:
                desc.append(k["rank"])
                desc.extend(subtree[k["rank"]])
            subtree[rank] = sorted(desc)
            return {"rank": rank, "addr": list(member_addrs[rank]), "children": kids}

        root_specs = [_spec(c) for c in _binomial_children(0, len(order))]
        result["root_children"] = sorted(s["rank"] for s in root_specs)

    # Ack wait scales with the caller's budget (clamped by the server at
    # 30s): a slow-but-healthy member still reassembling a large payload
    # must not be branded a failed rank by a fixed small bound.
    ack_wait = max(_BCAST_ACK_S, min(30.0, timeout))

    async def _push_direct(rank: int):
        client = cw._owner_client(tuple(member_addrs[rank]))
        for i, frame in enumerate(frames):
            await _gate_egress(len(chunks[i]))
            await client.apush_packed("p2p_data", frame)
        result["root_egress_bytes"] += len(data)

    async def _ack(rank: int, wait: float):
        client = cw._owner_client(tuple(member_addrs[rank]))
        resp = await client.acall(
            "p2p_ack", {"key": key, "timeout": wait},
            timeout=wait + 5.0, retries=0,
        )
        if not resp.get("ok"):
            raise RuntimeError("p2p_ack reported the payload never landed")

    async def _deliver(rank: int):
        await _push_direct(rank)
        await _ack(rank, ack_wait)

    async def _deliver_tree_child(spec: dict):
        client = cw._owner_client(tuple(spec["addr"]))
        if spec["children"]:
            relay = {"rank": spec["rank"], "children": spec["children"]}
            # Relay spec rides EVERY chunk frame: whichever lands first
            # opens the session, so loss/reorder of any one frame cannot
            # stall the whole subtree.
            for i in range(total):
                await _gate_egress(len(chunks[i]))
                await client.apush(
                    "p2p_data",
                    {"key": key, "idx": i, "total": total,
                     "data": chunks[i], "relay": relay},
                )
        else:
            for i, frame in enumerate(frames):
                await _gate_egress(len(chunks[i]))
                await client.apush_packed("p2p_data", frame)
        result["root_egress_bytes"] += len(data)
        await _ack(spec["rank"], ack_wait)

    async def _fan_out():
        tasks: dict = {}
        if use_tree:
            for spec in root_specs:
                tasks[spec["rank"]] = asyncio.ensure_future(
                    asyncio.wait_for(_deliver_tree_child(spec), timeout)
                )
            for rank in addressed:
                if rank not in tasks:  # delivered by a relay: ack only
                    tasks[rank] = asyncio.ensure_future(
                        asyncio.wait_for(_ack(rank, ack_wait), timeout)
                    )
        else:
            for rank in addressed:
                tasks[rank] = asyncio.ensure_future(
                    asyncio.wait_for(_deliver(rank), timeout)
                )
        if tasks:
            await asyncio.wait(tasks.values())
        outcomes = {rank: t.exception() for rank, t in tasks.items()}
        if use_tree:
            round1 = [r for r, e in outcomes.items() if e is not None]
            if round1:
                # Orphan recovery: a failed ack means the rank is dead OR a
                # relay above it died — re-deliver DIRECTLY (flat resend;
                # duplicate chunks overwrite partials in the inbox) so one
                # dead relay doesn't fail its whole healthy subtree.
                retry_ack = max(5.0, min(ack_wait, 10.0))

                async def _retry(rank: int):
                    await _push_direct(rank)
                    await _ack(rank, retry_ack)

                rtasks = {
                    r: asyncio.ensure_future(
                        asyncio.wait_for(_retry(r), retry_ack + 10.0)
                    )
                    for r in round1
                }
                await asyncio.wait(rtasks.values())
                for r, t in rtasks.items():
                    if t.exception() is None:
                        outcomes[r] = None
                        result["retried_ranks"].append(r)
                        COLL.bcast_retries += 1
        return outcomes

    # Outer bound is a backstop over the per-member wait_for; each member's
    # delivery is already clamped to ``timeout`` individually (plus the
    # bounded retry round in tree mode).
    outer = timeout + 15.0 + (20.0 if use_tree else 0.0)
    outcomes = cw._io.run(_fan_out(), timeout=outer) if targets else {}
    for rank in targets:
        if rank not in member_addrs:
            # Never registered an address (old-style member): the GCS
            # mailbox is its normal path, not a failure — but ONLY for
            # callers whose receivers actually poll it
            # (bcast_recv_payload). The device-object descriptor path
            # resolves from the direct inbox alone, so there a mailbox
            # drop would be dead weight in the KV and a false "delivered"
            # — it reports the rank failed instead.
            if not mailbox_fallback:
                result["failed"][rank] = "no registered member address"
                COLL.bcast_failed_ranks += 1
                continue
            try:
                mailbox_send(gcs, group_name, src_rank, rank, f"bcast/{tag}", value)
                _schedule_bcast_janitor(cw, gcs, mailbox_key(group_name, src_rank, rank, f"bcast/{tag}"))
                result["fallback_ranks"].append(rank)
                COLL.bcast_fallbacks += 1
            except Exception as e:
                result["failed"][rank] = repr(e)
                COLL.bcast_failed_ranks += 1
            continue
        exc = outcomes.get(rank)
        if exc is None:
            result["ok_ranks"].append(rank)
        else:
            # A REGISTERED member we could not deliver to is dead, severed,
            # or wedged — a GCS mailbox drop would "succeed" against a
            # corpse (the KV is alive either way), so the honest outcome is
            # a named failure the caller can act on.
            reason = repr(exc)
            orphans = subtree.get(rank) or []
            if orphans:
                recovered = sorted(set(orphans) & set(result["retried_ranks"]))
                reason += (
                    f" [tree relay: orphaned subtree ranks {orphans}"
                    + (f"; re-delivered directly: {recovered}" if recovered else "")
                    + "]"
                )
            result["failed"][rank] = reason
            COLL.bcast_failed_ranks += 1
    result["retried_ranks"].sort()
    COLL.bcast_sends += 1
    if use_tree:
        COLL.tree_sends += 1
    COLL.root_egress_bytes += result["root_egress_bytes"]
    COLL.bcast_send_bytes += len(data) * (
        len(result["ok_ranks"]) + len(result["fallback_ranks"])
    )
    return result


def _schedule_bcast_janitor(cw, gcs, key: str, delay_s: float = 180.0) -> None:
    """A mailbox-fallback payload a dead/slow member never claims must not
    sit in the GCS KV forever (same janitor shape as
    DeviceObjectManager._schedule_mailbox_janitor)."""
    async def _sweep():
        import asyncio

        await asyncio.sleep(delay_s)
        try:
            await gcs.acall("kv_del", {"key": key})
        except Exception:
            pass

    try:
        cw._io.spawn(_sweep())
    except Exception:
        pass


@blocking
def group_bcast_recv(cw, gcs, group_name: str, src_rank: int, my_rank: int, tag: str, timeout: float = 120.0):
    """Member-side receive of a group broadcast: watch BOTH landing zones —
    the direct mailbox (steady state: the payload is already here, or
    arrives whenever the sender's chunk pushes finish) and the GCS mailbox
    (the sender's fallback for members it could not dial) — until the
    deadline; typed timeout naming group/rank/tag otherwise. Interleaved
    on purpose: a receiver that blocks before the sender starts (normal
    collective ordering) must catch a direct delivery landing at ANY point
    in the window, not just the first second."""
    from ray_tpu._private import serialization
    from ray_tpu.exceptions import CollectiveTimeoutError

    deadline = time.monotonic() + timeout
    key = bcast_key(group_name, tag)
    gcs_key = mailbox_key(group_name, src_rank, my_rank, f"bcast/{tag}")
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            COLL.timeouts += 1
            raise CollectiveTimeoutError(
                f"group broadcast recv on {group_name!r} tag {tag!r}: nothing "
                f"from rank {src_rank} within {timeout}s (direct mailbox and "
                "GCS fallback both empty)",
                group=group_name, ranks=[src_rank], tag=tag,
            )
        data = direct_recv(cw, key, timeout=min(0.25, remaining))
        if data is not None:
            COLL.bcast_recvs += 1
            return serialization.loads(data)
        try:
            resp = gcs.call("kv_get", {"key": gcs_key})
            if resp.get("found"):
                gcs.call("kv_del", {"key": gcs_key})
                COLL.bcast_recvs += 1
                return serialization.loads(resp["value"])
        except Exception:
            pass  # GCS hiccup: the direct-path wait keeps the clock


@blocking
def direct_recv(cw, key: str, timeout: float, abort_check=None) -> bytes | None:
    """Wait for a direct-mailbox payload under ``key``. Returns the bytes,
    or None when ``timeout`` expires (caller falls back to the pull path)
    or ``abort_check()`` goes true (teardown / poison: caller surfaces its
    own typed error). Steady state returns without sleeping — for channel
    payloads the deposit itself is what woke the reader, so the bytes are
    already here by the time the consumer resolves the slot."""
    inbox = cw.p2p_inbox
    deadline = time.monotonic() + timeout
    ev = inbox._waiter(key)
    try:
        while True:
            data = inbox.take(key)
            if data is not None:
                return data
            if abort_check is not None and abort_check():
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            ev.wait(min(0.05, remaining))
            ev.clear()
    finally:
        inbox._drop_waiter(key)


# ---------------------------------------------------------------------------
# Group reduce / allreduce (chunk-wise combine at every relay hop)
# ---------------------------------------------------------------------------


def reduce_key(group_name: str, tag: str, src_rank: int) -> str:
    """Stream key for ONE member's partial chunks flowing up the reduce
    tree. Rank-scoped (unlike :func:`bcast_key`): a parent combining k
    children must tell their streams apart. The ``collred/`` prefix routes
    these frames into :class:`ChunkStreams` instead of the inbox."""
    return f"collred/{group_name}/{tag}/{src_rank}"


async def _push_reduce_chunk(client, key: str, idx: int, total: int, data: bytes):
    await _gate_egress(len(data))
    await client.apush(
        "p2p_data", {"key": key, "idx": idx, "total": total, "data": data}
    )


@blocking
def group_reduce_send(
    cw,
    gcs,
    group_name: str,
    my_rank: int,
    world_size: int,
    tag: str,
    value,
    op: ReduceOp = ReduceOp.SUM,
    dst_rank: int = 0,
    member_addrs: dict | None = None,
    timeout: float = 60.0,
):
    """One member's share of a TREE reduce toward ``dst_rank``: wait per
    chunk index for each tree child's combined partial, merge it into this
    rank's own slice ELEMENTWISE, and push the result to the parent the
    moment it's ready (cut-through combine — a chunk flows up while later
    chunks are still arriving below). Every rank of the group must call
    this with the same (tag, op, dst_rank); chunks travel as dense
    ``dtype`` bytes (NOT serialized objects) so relay hops can combine
    without a deserialize round trip.

    Returns the reduced ``np.ndarray`` on ``dst_rank``, None elsewhere.
    MEAN sums up the tree and divides ONCE at the root (matching
    ``np.stack(...).mean(axis=0)`` bit-for-bit on exact inputs). Requires
    every member to have a registered address — callers (cpu_group) fall
    back to the GCS ring otherwise. A silent child raises a typed
    CollectiveTimeoutError NAMING it; a shape/dtype disagreement surfaces
    as a CollectiveError naming both ranks."""
    import numpy as np

    from ray_tpu.exceptions import CollectiveError, CollectiveTimeoutError

    if member_addrs is None:
        member_addrs = fetch_member_addrs(gcs, group_name, world_size)
    missing = [
        r for r in range(world_size) if r != my_rank and r not in member_addrs
    ]
    if missing:
        raise CollectiveError(
            f"tree reduce on group {group_name!r} needs a registered address "
            f"for every member; missing ranks {missing}"
        )
    arr = np.ascontiguousarray(value)
    combine = {
        ReduceOp.SUM: np.add,
        ReduceOp.PRODUCT: np.multiply,
        ReduceOp.MIN: np.minimum,
        ReduceOp.MAX: np.maximum,
        ReduceOp.MEAN: np.add,  # summed at every hop; the root divides once
    }[op]
    # Same deterministic shape as the broadcast tree, rooted at dst_rank.
    order = [dst_rank] + sorted(r for r in range(world_size) if r != dst_rank)
    pos = order.index(my_rank)
    kid_ranks = [order[c] for c in _binomial_children(pos, world_size)]
    parent_client = None
    if pos:
        parent_rank = order[pos - (1 << (pos.bit_length() - 1))]
        parent_client = cw._owner_client(tuple(member_addrs[parent_rank]))
    data = arr.tobytes()
    # Chunk on element boundaries so every chunk is a dense dtype slice.
    itemsize = max(1, arr.dtype.itemsize)
    chunk_bytes = max(itemsize, (_DIRECT_CHUNK_BYTES // itemsize) * itemsize)
    total = max(1, (len(data) + chunk_bytes - 1) // chunk_bytes)
    deadline = time.monotonic() + timeout
    streams = cw.p2p_streams
    up_key = reduce_key(group_name, tag, my_rank)
    out_parts: list = []
    try:
        for idx in range(total):
            own = np.frombuffer(
                data[idx * chunk_bytes : (idx + 1) * chunk_bytes], dtype=arr.dtype
            )
            acc = own
            for kr in kid_ranks:
                chunk = streams.wait_chunk(reduce_key(group_name, tag, kr), idx, deadline)
                if chunk is None:
                    COLL.timeouts += 1
                    raise CollectiveTimeoutError(
                        f"tree reduce on group {group_name!r} tag {tag!r} "
                        f"(rank {my_rank}): no chunk {idx}/{total} from child "
                        f"rank {kr} within {timeout}s",
                        group=group_name, ranks=[kr], tag=tag,
                    )
                if len(chunk) != own.nbytes:
                    raise CollectiveError(
                        f"tree reduce on group {group_name!r} tag {tag!r}: "
                        f"chunk {idx} from rank {kr} is {len(chunk)} bytes, "
                        f"rank {my_rank} expects {own.nbytes} — members "
                        "disagree on shape/dtype"
                    )
                acc = combine(acc, np.frombuffer(chunk, dtype=arr.dtype))
            if parent_client is None:
                out_parts.append(acc)
            else:
                payload = acc.tobytes()
                cw._io.run(
                    _push_reduce_chunk(parent_client, up_key, idx, total, payload),
                    timeout=30.0,
                )
                COLL.reduce_bytes += len(payload)
    finally:
        for kr in kid_ranks:
            streams.purge(reduce_key(group_name, tag, kr))
    COLL.reduce_sends += 1
    if parent_client is not None:
        return None
    out = np.concatenate(out_parts) if len(out_parts) > 1 else out_parts[0]
    out = np.array(out).reshape(arr.shape)
    if op is ReduceOp.MEAN:
        out = out / world_size
    return out


@blocking
def group_allreduce(
    cw,
    gcs,
    group_name: str,
    my_rank: int,
    world_size: int,
    tag: str,
    value,
    op: ReduceOp = ReduceOp.SUM,
    member_addrs: dict | None = None,
    timeout: float = 60.0,
    finalize=None,
):
    """Tree allreduce: reduce up to rank 0, then tree-broadcast the
    combined result back down — every rank returns the same reduced value
    after 2·depth hops instead of a K-wide ring epoch. ``finalize``
    (optional) runs ON THE ROOT before the down-broadcast (e.g. a jnp
    conversion), so output placement is decided once and every rank
    receives the finalized payload — placement parity with ``broadcast``.
    Raises CollectiveBroadcastError if the down-broadcast misses a rank
    (an allreduce is all-or-nothing: a member without the result would
    silently diverge)."""
    from ray_tpu.exceptions import CollectiveBroadcastError

    red = group_reduce_send(
        cw, gcs, group_name, my_rank, world_size, tag, value,
        op=op, dst_rank=0, member_addrs=member_addrs, timeout=timeout,
    )
    COLL.allreduces += 1
    down_tag = f"allred/{tag}"
    if my_rank == 0:
        out = finalize(red) if finalize is not None else red
        res = group_bcast_send(
            cw, gcs, group_name, 0, world_size, down_tag, out,
            member_addrs=member_addrs, timeout=timeout, mailbox_fallback=False,
        )
        if res["failed"]:
            raise CollectiveBroadcastError(
                f"allreduce down-broadcast on group {group_name!r} failed for "
                f"ranks {sorted(res['failed'])}",
                group=group_name, failed=res["failed"], info=res,
            )
        return out
    return group_bcast_recv(cw, gcs, group_name, 0, my_rank, down_tag, timeout)
