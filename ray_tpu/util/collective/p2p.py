"""Point-to-point transfer plane for collective groups and channel payloads.

Analog of the reference's ``ray.util.collective`` ``send``/``recv``
(python/ray/util/collective/collective.py:531/594): a 2-party transfer
between two ranks of an initialized group, OUT OF BAND with respect to the
shm object store — this is the wire the device-object plane
(experimental/device_object/) rides for actor-to-actor tensor handoff.

Two rendezvous mechanisms share this seam:

- **GCS-KV mailbox** (``mailbox_send``/``mailbox_recv``): the group-rank
  path. The sender posts the serialized value under a single-use tagged key
  in the group's GCS KV (the same control plane the CPU ring collectives
  and the TPU world bootstrap already use); the receiver polls it down and
  deletes it. Needs no peer address — ranks are the only names.
- **Direct mailbox** (``direct_send``/``direct_recv`` + ``P2PInbox``): the
  address-direct path the descriptor channel plane (PR 12,
  experimental/channel/device_envelope.py) streams microbatch payloads
  over. The sender pushes chunked one-way ``p2p_data`` frames straight at
  the consumer core worker's RPC server (no GCS round trips, no polling);
  the receiver waits on its process-local inbox. Keys are caller-scoped
  (``chdev/<cid>/<seq>`` for channel slots), delivery is at-most-once —
  callers fall back to a pull (resolve.py) on a missed grace window.

Device arrays serialize through ``_private/serialization`` so sharding
layout survives either hop and the receiver's ``device_put`` lands shards
back on the matching devices.

On real TPU hardware the collectives INSIDE jitted programs ride ICI; both
host mailboxes are correctness stand-ins until jax exposes a cross-process
device-to-device transfer API in this image (the reference's NCCL p2p
equivalent). The seams are ``TpuCollectiveGroup.send/recv`` and
``direct_send/direct_recv`` — swap in the device path there without
touching any caller.
"""

from __future__ import annotations

import threading
import time

from ray_tpu._private.concurrency import any_thread, blocking

_POLL_S = 0.003
# Direct-mailbox chunk size: one-way frames on the existing worker pipe,
# bounded like the chunked object-push path.
_DIRECT_CHUNK_BYTES = 512 * 1024
# Unclaimed inbox entries (consumer died / tore down between the eager push
# and the read) are swept after this age so a long-lived worker's inbox
# cannot grow without bound on lost readers.
_INBOX_SWEEP_AGE_S = 180.0


def mailbox_key(group_name: str, src_rank: int, dst_rank: int, tag: str) -> str:
    """Public so senders can janitor abandoned transfers (a recv that timed
    out or died never deletes the key; without cleanup the serialized
    payload would sit in the GCS KV forever)."""
    return f"collective/{group_name}/p2p/{src_rank}->{dst_rank}/{tag}"


_key = mailbox_key


@blocking
def mailbox_send(gcs, group_name: str, src_rank: int, dst_rank: int, tag: str, value) -> int:
    """Serialize ``value`` and post it for ``dst_rank``; returns byte size.
    Single-use: the receiver deletes the key after pickup."""
    from ray_tpu._private import serialization

    data = serialization.dumps(value)
    gcs.call(
        "kv_put",
        {"key": _key(group_name, src_rank, dst_rank, tag), "value": data},
    )
    return len(data)


@blocking
def mailbox_recv(gcs, group_name: str, src_rank: int, dst_rank: int, tag: str, timeout: float = 120.0):
    """Block until the tagged value from ``src_rank`` arrives; deserializes
    (device arrays reassemble with their original sharding) and deletes the
    mailbox key."""
    from ray_tpu._private import serialization

    key = _key(group_name, src_rank, dst_rank, tag)
    deadline = time.monotonic() + timeout
    while True:
        resp = gcs.call("kv_get", {"key": key})
        if resp.get("found"):
            gcs.call("kv_del", {"key": key})
            return serialization.loads(resp["value"])
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"p2p recv on group {group_name!r} tag {tag!r} from rank "
                f"{src_rank} timed out after {timeout}s"
            )
        time.sleep(_POLL_S)


# ---------------------------------------------------------------------------
# Direct mailbox (address-directed, no GCS round trips)
# ---------------------------------------------------------------------------


class P2PInbox:
    """Per-process landing zone for ``p2p_data`` frames (one per core
    worker; the ``rpc_p2p_data`` handler deposits into it). Chunked frames
    reassemble here; a waiter blocks on a per-key event. All state behind
    one lock; methods never block — deposit runs on the IO loop."""

    def __init__(self):
        from ray_tpu._private.ids import BoundedIdSet

        self._lock = threading.Lock()
        self._parts: dict[str, dict] = {}    # key -> {idx: bytes}
        self._parts_ts: dict[str, float] = {}  # key -> first-chunk monotonic ts
        self._done: dict[str, tuple] = {}    # key -> (bytes, monotonic ts)
        self._waiters: dict[str, threading.Event] = {}
        self._deposits = 0
        # Recently-COMPLETED keys: delivery of p2p_data frames is
        # at-least-once under connection blips (and chaos dup injection),
        # and a duplicate chunk arriving AFTER its payload completed used
        # to re-open a partial reassembly that could never complete
        # (leaked until the age sweep) — or, for a single-chunk payload,
        # resurrect a consumed ``_done`` entry, breaking the at-most-once
        # take() contract. Tombstoned keys drop silently.
        self._completed = BoundedIdSet(cap=1024)

    @any_thread
    def deposit(self, key: str, idx: int, total: int, data: bytes) -> bool:
        """Returns True when the payload is COMPLETE (all chunks landed).
        Idempotent under duplicated/reordered chunks: a repeat of a
        still-assembling chunk overwrites in place, and any chunk of an
        already-completed key is dropped."""
        complete = False
        with self._lock:
            if key in self._completed or key in self._done:
                self._deposits += 1
                return False  # duplicate of a completed payload
            parts = self._parts.get(key)
            if parts is None:
                parts = self._parts[key] = {}
                self._parts_ts[key] = time.monotonic()
            parts[idx] = data
            if len(parts) == total:
                self._completed.add(key)
                self._parts.pop(key)
                self._parts_ts.pop(key, None)
                self._done[key] = (
                    data if total == 1 else b"".join(parts[i] for i in range(total)),
                    time.monotonic(),
                )
                waiter = self._waiters.get(key)
                if waiter is not None:
                    waiter.set()
                complete = True
            self._deposits += 1
            sweep = self._deposits & 255 == 0
        if sweep:
            self.sweep()
        return complete

    @any_thread
    def take(self, key: str) -> bytes | None:
        with self._lock:
            entry = self._done.pop(key, None)
            return None if entry is None else entry[0]

    @any_thread
    def _waiter(self, key: str) -> threading.Event:
        with self._lock:
            if key in self._done:
                ev = threading.Event()
                ev.set()
                return ev
            ev = self._waiters.get(key)
            if ev is None:
                ev = self._waiters[key] = threading.Event()
            return ev

    @any_thread
    def _drop_waiter(self, key: str) -> None:
        with self._lock:
            self._waiters.pop(key, None)

    @any_thread
    def purge_prefix(self, prefix: str) -> int:
        """Drop every entry/partial under a key prefix (channel teardown:
        cids are dead, nobody will ever take these payloads)."""
        with self._lock:
            victims = [k for k in self._done if k.startswith(prefix)]
            for k in victims:
                del self._done[k]
            for k in [k for k in self._parts if k.startswith(prefix)]:
                del self._parts[k]
                self._parts_ts.pop(k, None)
                victims.append(k)
            return len(victims)

    @any_thread
    def sweep(self, max_age_s: float = _INBOX_SWEEP_AGE_S) -> int:
        """Age out unclaimed payloads AND stale partial reassemblies (a
        producer that died mid-push leaves chunks that will never
        complete — lost writers must not leak any more than lost
        readers)."""
        cutoff = time.monotonic() - max_age_s
        with self._lock:
            victims = [k for k, (_, ts) in self._done.items() if ts < cutoff]
            for k in victims:
                del self._done[k]
            stale = [k for k, ts in self._parts_ts.items() if ts < cutoff]
            for k in stale:
                self._parts.pop(k, None)
                del self._parts_ts[k]
            return len(victims) + len(stale)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._done),
                "partials": len(self._parts),
                "bytes": sum(len(d) for d, _ in self._done.values()),
            }


@any_thread
def direct_send(cw, addr: tuple, key: str, data: bytes) -> None:
    """Push serialized payload bytes at ``addr``'s inbox under ``key`` as
    chunked ONE-WAY frames on the existing worker pipe (fire-and-forget,
    like the channel doorbell): zero round trips on the hot path. Loss is
    recoverable — the consumer's grace window expires and it falls back to
    the pull path (resolve.py), where the holder still pins the payload."""
    client = cw._owner_client(tuple(addr))
    total = max(1, (len(data) + _DIRECT_CHUNK_BYTES - 1) // _DIRECT_CHUNK_BYTES)

    async def _push_all():
        try:
            for i in range(total):
                await client.apush(
                    "p2p_data",
                    {
                        "key": key,
                        "idx": i,
                        "total": total,
                        "data": data[
                            i * _DIRECT_CHUNK_BYTES : (i + 1) * _DIRECT_CHUNK_BYTES
                        ],
                    },
                )
        except Exception:
            pass  # consumer unreachable: its grace window handles it

    cw._io.spawn(_push_all())


@blocking
def direct_recv(cw, key: str, timeout: float, abort_check=None) -> bytes | None:
    """Wait for a direct-mailbox payload under ``key``. Returns the bytes,
    or None when ``timeout`` expires (caller falls back to the pull path)
    or ``abort_check()`` goes true (teardown / poison: caller surfaces its
    own typed error). Steady state returns without sleeping — for channel
    payloads the deposit itself is what woke the reader, so the bytes are
    already here by the time the consumer resolves the slot."""
    inbox = cw.p2p_inbox
    deadline = time.monotonic() + timeout
    ev = inbox._waiter(key)
    try:
        while True:
            data = inbox.take(key)
            if data is not None:
                return data
            if abort_check is not None and abort_check():
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            ev.wait(min(0.05, remaining))
            ev.clear()
    finally:
        inbox._drop_waiter(key)
