"""Point-to-point transfer plane for collective groups.

Analog of the reference's ``ray.util.collective`` ``send``/``recv``
(python/ray/util/collective/collective.py:531/594): a 2-party transfer
between two ranks of an initialized group, OUT OF BAND with respect to the
shm object store — this is the wire the device-object plane
(experimental/device_object/) rides for actor-to-actor tensor handoff.

The mailbox rendezvous runs over the group's GCS KV (the same control plane
the CPU ring collectives and the TPU world bootstrap already use): the
sender posts the serialized value under a single-use tagged key, the
receiver polls it down and deletes it. Device arrays serialize through
``_private/serialization`` so sharding layout survives the hop and the
receiver's ``device_put`` lands shards back on the matching devices.

On real TPU hardware the collectives INSIDE jitted programs ride ICI; this
2-party object mailbox stays on the host control plane until jax exposes a
cross-process device-to-device transfer API in this image (the reference's
NCCL p2p equivalent). The seam is ``TpuCollectiveGroup.send/recv`` — swap
the mailbox for the device path there without touching any caller.
"""

from __future__ import annotations

import time

from ray_tpu._private.concurrency import blocking

_POLL_S = 0.003


def mailbox_key(group_name: str, src_rank: int, dst_rank: int, tag: str) -> str:
    """Public so senders can janitor abandoned transfers (a recv that timed
    out or died never deletes the key; without cleanup the serialized
    payload would sit in the GCS KV forever)."""
    return f"collective/{group_name}/p2p/{src_rank}->{dst_rank}/{tag}"


_key = mailbox_key


@blocking
def mailbox_send(gcs, group_name: str, src_rank: int, dst_rank: int, tag: str, value) -> int:
    """Serialize ``value`` and post it for ``dst_rank``; returns byte size.
    Single-use: the receiver deletes the key after pickup."""
    from ray_tpu._private import serialization

    data = serialization.dumps(value)
    gcs.call(
        "kv_put",
        {"key": _key(group_name, src_rank, dst_rank, tag), "value": data},
    )
    return len(data)


@blocking
def mailbox_recv(gcs, group_name: str, src_rank: int, dst_rank: int, tag: str, timeout: float = 120.0):
    """Block until the tagged value from ``src_rank`` arrives; deserializes
    (device arrays reassemble with their original sharding) and deletes the
    mailbox key."""
    from ray_tpu._private import serialization

    key = _key(group_name, src_rank, dst_rank, tag)
    deadline = time.monotonic() + timeout
    while True:
        resp = gcs.call("kv_get", {"key": key})
        if resp.get("found"):
            gcs.call("kv_del", {"key": key})
            return serialization.loads(resp["value"])
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"p2p recv on group {group_name!r} tag {tag!r} from rank "
                f"{src_rank} timed out after {timeout}s"
            )
        time.sleep(_POLL_S)
