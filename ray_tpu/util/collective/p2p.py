"""Point-to-point transfer plane for collective groups and channel payloads.

Analog of the reference's ``ray.util.collective`` ``send``/``recv``
(python/ray/util/collective/collective.py:531/594): a 2-party transfer
between two ranks of an initialized group, OUT OF BAND with respect to the
shm object store — this is the wire the device-object plane
(experimental/device_object/) rides for actor-to-actor tensor handoff.

Two rendezvous mechanisms share this seam:

- **GCS-KV mailbox** (``mailbox_send``/``mailbox_recv``): the group-rank
  path. The sender posts the serialized value under a single-use tagged key
  in the group's GCS KV (the same control plane the CPU ring collectives
  and the TPU world bootstrap already use); the receiver polls it down and
  deletes it. Needs no peer address — ranks are the only names.
- **Direct mailbox** (``direct_send``/``direct_recv`` + ``P2PInbox``): the
  address-direct path the descriptor channel plane (PR 12,
  experimental/channel/device_envelope.py) streams microbatch payloads
  over. The sender pushes chunked one-way ``p2p_data`` frames straight at
  the consumer core worker's RPC server (no GCS round trips, no polling);
  the receiver waits on its process-local inbox. Keys are caller-scoped
  (``chdev/<cid>/<seq>`` for channel slots), delivery is at-most-once —
  callers fall back to a pull (resolve.py) on a missed grace window.

Device arrays serialize through ``_private/serialization`` so sharding
layout survives either hop and the receiver's ``device_put`` lands shards
back on the matching devices.

On real TPU hardware the collectives INSIDE jitted programs ride ICI; both
host mailboxes are correctness stand-ins until jax exposes a cross-process
device-to-device transfer API in this image (the reference's NCCL p2p
equivalent). The seams are ``TpuCollectiveGroup.send/recv`` and
``direct_send/direct_recv`` — swap in the device path there without
touching any caller.
"""

from __future__ import annotations

import threading
import time

from ray_tpu._private.concurrency import any_thread, blocking, loop_only
from ray_tpu.util.collective.types import ReduceOp

_POLL_S = 0.003
# Direct-mailbox chunk size: one-way frames on the existing worker pipe,
# bounded like the chunked object-push path.
_DIRECT_CHUNK_BYTES = 512 * 1024
# Unclaimed inbox entries (consumer died / tore down between the eager push
# and the read) are swept after this age so a long-lived worker's inbox
# cannot grow without bound on lost readers.
_INBOX_SWEEP_AGE_S = 180.0


def mailbox_key(group_name: str, src_rank: int, dst_rank: int, tag: str) -> str:
    """Public so senders can janitor abandoned transfers (a recv that timed
    out or died never deletes the key; without cleanup the serialized
    payload would sit in the GCS KV forever)."""
    return f"collective/{group_name}/p2p/{src_rank}->{dst_rank}/{tag}"


_key = mailbox_key


@blocking
def mailbox_send(gcs, group_name: str, src_rank: int, dst_rank: int, tag: str, value) -> int:
    """Serialize ``value`` and post it for ``dst_rank``; returns byte size.
    Single-use: the receiver deletes the key after pickup."""
    from ray_tpu._private import serialization

    data = serialization.dumps(value)
    gcs.call(
        "kv_put",
        {"key": _key(group_name, src_rank, dst_rank, tag), "value": data},
    )
    return len(data)


@blocking
def mailbox_recv(gcs, group_name: str, src_rank: int, dst_rank: int, tag: str, timeout: float = 120.0):
    """Block until the tagged value from ``src_rank`` arrives; deserializes
    (device arrays reassemble with their original sharding) and deletes the
    mailbox key."""
    from ray_tpu._private import serialization

    key = _key(group_name, src_rank, dst_rank, tag)
    deadline = time.monotonic() + timeout
    while True:
        resp = gcs.call("kv_get", {"key": key})
        if resp.get("found"):
            gcs.call("kv_del", {"key": key})
            return serialization.loads(resp["value"])
        if time.monotonic() > deadline:
            from ray_tpu.exceptions import CollectiveTimeoutError

            raise CollectiveTimeoutError(
                f"p2p recv on group {group_name!r} tag {tag!r} from rank "
                f"{src_rank} timed out after {timeout}s",
                group=group_name, ranks=[src_rank], tag=tag,
            )
        time.sleep(_POLL_S)


# ---------------------------------------------------------------------------
# Direct mailbox (address-directed, no GCS round trips)
# ---------------------------------------------------------------------------


class P2PInbox:
    """Per-process landing zone for ``p2p_data`` frames (one per core
    worker; the ``rpc_p2p_data`` handler deposits into it). Chunked frames
    reassemble here; a waiter blocks on a per-key event. All state behind
    one lock; methods never block — deposit runs on the IO loop."""

    def __init__(self):
        from ray_tpu._private.ids import BoundedIdSet

        self._lock = threading.Lock()
        self._parts: dict[str, dict] = {}    # key -> {idx: bytes}
        self._parts_ts: dict[str, float] = {}  # key -> first-chunk monotonic ts
        self._done: dict[str, tuple] = {}    # key -> (bytes, monotonic ts)
        self._waiters: dict[str, threading.Event] = {}
        self._deposits = 0
        # Recently-COMPLETED keys: delivery of p2p_data frames is
        # at-least-once under connection blips (and chaos dup injection),
        # and a duplicate chunk arriving AFTER its payload completed used
        # to re-open a partial reassembly that could never complete
        # (leaked until the age sweep) — or, for a single-chunk payload,
        # resurrect a consumed ``_done`` entry, breaking the at-most-once
        # take() contract. Tombstoned keys drop silently.
        self._completed = BoundedIdSet(cap=1024)

    @any_thread
    def deposit(self, key: str, idx: int, total: int, data: bytes) -> bool:
        """Returns True when the payload is COMPLETE (all chunks landed).
        Idempotent under duplicated/reordered chunks: a repeat of a
        still-assembling chunk overwrites in place, and any chunk of an
        already-completed key is dropped."""
        complete = False
        with self._lock:
            if key in self._completed or key in self._done:
                self._deposits += 1
                return False  # duplicate of a completed payload
            parts = self._parts.get(key)
            if parts is None:
                parts = self._parts[key] = {}
                self._parts_ts[key] = time.monotonic()
            parts[idx] = data
            if len(parts) == total:
                self._completed.add(key)
                self._parts.pop(key)
                self._parts_ts.pop(key, None)
                self._done[key] = (
                    data if total == 1 else b"".join(parts[i] for i in range(total)),
                    time.monotonic(),
                )
                waiter = self._waiters.get(key)
                if waiter is not None:
                    waiter.set()
                complete = True
            self._deposits += 1
            sweep = self._deposits & 255 == 0
        if sweep:
            self.sweep()
        return complete

    @any_thread
    def take(self, key: str) -> bytes | None:
        with self._lock:
            entry = self._done.pop(key, None)
            return None if entry is None else entry[0]

    @any_thread
    def _waiter(self, key: str) -> threading.Event:
        with self._lock:
            if key in self._done:
                ev = threading.Event()
                ev.set()
                return ev
            ev = self._waiters.get(key)
            if ev is None:
                ev = self._waiters[key] = threading.Event()
            return ev

    @any_thread
    def _drop_waiter(self, key: str) -> None:
        with self._lock:
            self._waiters.pop(key, None)

    @any_thread
    def completed(self, key: str) -> bool:
        """True once every chunk of ``key`` has landed — stays true after a
        take() (the tombstone remembers), which is exactly the delivery
        acknowledgement ``p2p_ack`` needs: 'the payload reached this
        process', not 'it is still unclaimed'."""
        with self._lock:
            return key in self._completed or key in self._done

    @blocking
    def wait_complete(self, key: str, timeout: float) -> bool:
        """Block (bounded) until ``key``'s payload has fully landed. Used by
        the ``p2p_ack`` RPC: the ack rides the same connection as the data
        frames, but handlers are dispatched as tasks, so a bounded wait
        covers the (rare) reorder instead of trusting scheduling order."""
        deadline = time.monotonic() + timeout
        ev = self._waiter(key)
        try:
            while True:
                if self.completed(key):
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                ev.wait(min(0.05, remaining))
                ev.clear()
        finally:
            self._drop_waiter(key)

    @any_thread
    def purge_prefix(self, prefix: str) -> int:
        """Drop every entry/partial under a key prefix (channel teardown:
        cids are dead, nobody will ever take these payloads)."""
        with self._lock:
            victims = [k for k in self._done if k.startswith(prefix)]
            for k in victims:
                del self._done[k]
            for k in [k for k in self._parts if k.startswith(prefix)]:
                del self._parts[k]
                self._parts_ts.pop(k, None)
                victims.append(k)
            return len(victims)

    @any_thread
    def sweep(self, max_age_s: float = _INBOX_SWEEP_AGE_S) -> int:
        """Age out unclaimed payloads AND stale partial reassemblies (a
        producer that died mid-push leaves chunks that will never
        complete — lost writers must not leak any more than lost
        readers)."""
        cutoff = time.monotonic() - max_age_s
        with self._lock:
            victims = [k for k, (_, ts) in self._done.items() if ts < cutoff]
            for k in victims:
                del self._done[k]
            stale = [k for k, ts in self._parts_ts.items() if ts < cutoff]
            for k in stale:
                self._parts.pop(k, None)
                del self._parts_ts[k]
            return len(victims) + len(stale)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._done),
                "partials": len(self._parts),
                "bytes": sum(len(d) for d, _ in self._done.values()),
            }


@any_thread
def direct_send(cw, addr: tuple, key: str, data: bytes) -> None:
    """Push serialized payload bytes at ``addr``'s inbox under ``key`` as
    chunked ONE-WAY frames on the existing worker pipe (fire-and-forget,
    like the channel doorbell): zero round trips on the hot path. Loss is
    recoverable — the consumer's grace window expires and it falls back to
    the pull path (resolve.py), where the holder still pins the payload."""
    client = cw._owner_client(tuple(addr))
    total = max(1, (len(data) + _DIRECT_CHUNK_BYTES - 1) // _DIRECT_CHUNK_BYTES)

    async def _push_all():
        try:
            for i in range(total):
                await client.apush(
                    "p2p_data",
                    {
                        "key": key,
                        "idx": i,
                        "total": total,
                        "data": data[
                            i * _DIRECT_CHUNK_BYTES : (i + 1) * _DIRECT_CHUNK_BYTES
                        ],
                    },
                )
        except Exception:
            pass  # consumer unreachable: its grace window handles it

    cw._io.spawn(_push_all())


# ---------------------------------------------------------------------------
# Modeled egress link (bench-only)
# ---------------------------------------------------------------------------

# When set, every outbound payload chunk on the group plane (root fan-out,
# relay forwards, reduce up-pushes) serializes through ONE per-process
# asyncio.Lock and sleeps bytes/bandwidth. This is the PR 10 convention
# (PERF_NOTES.md): loopback has no per-NIC budget, so an unthrottled A/B
# cannot show what a relay tree buys — the modeled link is the honest
# stand-in for the per-host egress bandwidth the tree divides on a real
# fleet. Off (None) outside the bench.
_EGRESS_BPS: float | None = None
_EGRESS_LOCK = None  # created lazily on the IO loop


@any_thread
def set_modeled_egress(mib_per_s: float | None) -> None:
    """Install (or clear, with None) the modeled per-process egress link."""
    global _EGRESS_BPS
    _EGRESS_BPS = None if not mib_per_s else float(mib_per_s) * 1024 * 1024


async def _gate_egress(nbytes: int) -> None:
    global _EGRESS_LOCK
    bps = _EGRESS_BPS
    if not bps:
        return
    import asyncio

    if _EGRESS_LOCK is None:
        _EGRESS_LOCK = asyncio.Lock()
    async with _EGRESS_LOCK:
        await asyncio.sleep(nbytes / bps)


# ---------------------------------------------------------------------------
# Binomial relay tree
# ---------------------------------------------------------------------------


def _binomial_children(pos: int, n: int) -> list[int]:
    """Child POSITIONS of ``pos`` in the binomial broadcast tree over ``n``
    positions rooted at 0: ``pos + 2**k`` for every power of two strictly
    greater than ``pos`` (depth ceil(log2 n), root degree floor(log2 n) —
    the classic recursive-doubling shape, so the root writes O(log K)
    streams instead of K)."""
    kids = []
    step = 1
    while step <= pos:
        step <<= 1
    while pos + step < n:
        kids.append(pos + step)
        step <<= 1
    return kids


class RelayTable:
    """Per-process cut-through relay sessions for TREE group broadcasts
    (one per core worker; ``rpc_p2p_data`` feeds it when a chunk frame
    carries a ``relay`` spec). Each landed chunk is forwarded to this
    member's own tree children the moment the contiguous prefix reaches it
    — the ``push_manager.stream_from_session`` watermark pattern, NOT
    store-and-forward, so the next hop starts before this one finishes.
    All state lives on the IO loop (deposits and forwarder tasks alike):
    no lock. The inbox keeps its own copy for the local take()."""

    def __init__(self):
        from ray_tpu._private.ids import BoundedIdSet

        self._sessions: dict[str, _RelaySession] = {}
        # Delivery is at-least-once under connection blips (and chaos dup
        # injection): a duplicate chunk landing after the session finished
        # must not resurrect it.
        self._finished = BoundedIdSet(cap=512)

    @loop_only
    def feed(self, cw, key: str, idx: int, total: int, data: bytes, relay: dict) -> None:
        st = self._sessions.get(key)
        if st is None:
            if key in self._finished:
                return
            st = self._sessions[key] = _RelaySession(key, int(total), relay)
            st.start(cw, self)
        st.chunks[idx] = data
        while st.contig in st.chunks:
            st.contig += 1
        st.event.set()

    @loop_only
    def finish(self, key: str) -> None:
        if self._sessions.pop(key, None) is not None:
            self._finished.add(key)

    def stats(self) -> dict:
        return {"sessions": len(self._sessions)}


class _RelaySession:
    """One in-flight relay: the chunks as they land, the contiguous-prefix
    watermark, and a forwarder task per tree child racing it."""

    __slots__ = ("key", "total", "relay", "chunks", "contig", "event",
                 "pending", "bytes_forwarded", "forwarders", "watchdog")

    def __init__(self, key: str, total: int, relay: dict):
        import asyncio

        self.key = key
        self.total = total
        self.relay = relay
        self.chunks: dict[int, bytes] = {}
        self.contig = 0
        self.event = asyncio.Event()
        self.pending = len(relay.get("children") or [])
        self.bytes_forwarded = 0
        self.forwarders: list = []
        self.watchdog = None

    def start(self, cw, table: RelayTable) -> None:
        import asyncio

        for child in self.relay.get("children") or []:
            self.forwarders.append(
                asyncio.ensure_future(_relay_forward(cw, table, self, child))
            )
        self.watchdog = asyncio.ensure_future(_relay_watchdog(table, self))


async def _relay_forward(cw, table: RelayTable, st: _RelaySession, child: dict) -> None:
    """Forward every chunk of ``st`` to ONE tree child as it becomes
    contiguous. A dead child is swallowed on purpose: the ROOT's per-rank
    ack round is what detects the orphaned subtree and re-delivers it
    directly (flat fallback) — a relay has no policy of its own."""
    try:
        client = cw._owner_client(tuple(child["addr"]))
        sub = child.get("children") or []
        relay = {"rank": child["rank"], "children": sub} if sub else None
        for idx in range(st.total):
            while st.contig <= idx:
                st.event.clear()
                await st.event.wait()
            data = st.chunks[idx]
            payload = {"key": st.key, "idx": idx, "total": st.total, "data": data}
            if relay is not None:
                payload["relay"] = relay
            await _gate_egress(len(data))
            await client.apush("p2p_data", payload)
            st.bytes_forwarded += len(data)
            COLL.relay_bytes += len(data)
        COLL.relay_forwards += 1
    except Exception:
        pass
    finally:
        st.pending -= 1
        if st.pending <= 0:
            _relay_finish(table, st)


async def _relay_watchdog(table: RelayTable, st: _RelaySession) -> None:
    """A relay whose payload never completes (root died mid-push) must not
    park its forwarders and chunks forever."""
    import asyncio

    await asyncio.sleep(_INBOX_SWEEP_AGE_S)
    for t in st.forwarders:
        if not t.done():
            t.cancel()
    _relay_finish(table, st)


def _relay_finish(table: RelayTable, st: _RelaySession) -> None:
    if table._sessions.get(st.key) is not st:
        return  # already recorded (forwarder finallys race the watchdog)
    if st.watchdog is not None and not st.watchdog.done():
        st.watchdog.cancel()
    try:
        from ray_tpu._private import flight_recorder

        parts = st.key.split("/", 2)  # collbcast/<group>/<tag>
        group = parts[1] if len(parts) == 3 else ""
        tag = parts[2] if len(parts) == 3 else st.key
        flight_recorder.record(
            "coll_relay",
            f"{tag[:12]}:{group}:{st.relay.get('rank')}:"
            f"{len(st.relay.get('children') or [])}:{st.bytes_forwarded}",
        )
    except Exception:
        pass
    table.finish(st.key)


class ChunkStreams:
    """Landing pads for tree-REDUCE partial streams (``collred/`` keys).
    Unlike :class:`P2PInbox`, chunks are consumed ONE AT A TIME by the
    member combining them into its own slice (cut-through combine at every
    relay hop) — nothing ever reassembles into a full payload. Combiners
    run on executor threads while deposits land on the IO loop, so state
    sits behind a lock with per-key events."""

    def __init__(self):
        self._lock = threading.Lock()
        self._chunks: dict[str, dict[int, bytes]] = {}
        self._events: dict[str, threading.Event] = {}
        self._ts: dict[str, float] = {}
        self._deposits = 0

    @any_thread
    def deposit(self, key: str, idx: int, data: bytes) -> None:
        with self._lock:
            self._chunks.setdefault(key, {})[idx] = data
            self._ts[key] = time.monotonic()
            ev = self._events.get(key)
            if ev is None:
                ev = self._events[key] = threading.Event()
            self._deposits += 1
            sweep = self._deposits & 255 == 0
        ev.set()
        if sweep:
            self.sweep()

    @blocking
    def wait_chunk(self, key: str, idx: int, deadline: float) -> bytes | None:
        """Pop chunk ``idx`` of stream ``key`` (each chunk is consumed
        exactly once), or None once ``deadline`` passes."""
        while True:
            with self._lock:
                ev = self._events.get(key)
                if ev is None:
                    ev = self._events[key] = threading.Event()
                ev.clear()  # before the check: a deposit between check and
                # wait must leave the event set
                d = self._chunks.get(key)
                if d is not None and idx in d:
                    return d.pop(idx)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            ev.wait(min(0.05, remaining))

    @any_thread
    def purge(self, key: str) -> None:
        with self._lock:
            self._chunks.pop(key, None)
            self._events.pop(key, None)
            self._ts.pop(key, None)

    @any_thread
    def sweep(self, max_age_s: float = _INBOX_SWEEP_AGE_S) -> int:
        """Age out streams nobody is combining (a reduce that raised on
        this member leaves its children's later chunks behind)."""
        cutoff = time.monotonic() - max_age_s
        with self._lock:
            stale = [k for k, ts in self._ts.items() if ts < cutoff]
            for k in stale:
                self._chunks.pop(k, None)
                self._events.pop(k, None)
                del self._ts[k]
            return len(stale)

    def stats(self) -> dict:
        with self._lock:
            return {
                "streams": len(self._chunks),
                "chunks": sum(len(d) for d in self._chunks.values()),
            }


# ---------------------------------------------------------------------------
# Group broadcast (ONE group op fanning a payload to every member)
# ---------------------------------------------------------------------------

# Per-member budget for the delivery acknowledgement round trip. The ack is
# what turns the fire-and-forget chunk frames into a delivery receipt: it
# rides the same FIFO connection as the data, so by the time the member
# answers, its inbox either has the payload or never will.
_BCAST_ACK_S = 10.0


class _CollStats:
    """Plain-int hot-path counters for the group-collective plane, folded
    into ``ray_tpu_collective_*`` instruments by self_metrics at flush time
    (same pattern as DEVOBJ_STATS — no instrument lock on the send path)."""

    __slots__ = (
        "bcast_sends",        # group broadcasts fanned out by this process
        "bcast_send_bytes",   # serialized payload bytes × delivered ranks
        "bcast_recvs",        # descriptor resolves served from a broadcast
        "bcast_fallbacks",    # per-rank deliveries that fell back to the GCS mailbox
        "bcast_failed_ranks", # ranks a broadcast could not deliver to
        "timeouts",           # typed collective timeouts raised here
        "tree_sends",         # broadcasts that rode the binomial relay tree
        "bcast_retries",      # ranks re-delivered directly after a relay failure
        "root_egress_bytes",  # payload bytes THIS process pushed as broadcast root
        "relay_forwards",     # relay legs completed here (all chunks to one child)
        "relay_bytes",        # payload bytes forwarded mid-tree by this process
        "reduce_sends",       # tree-reduce participations by this process
        "reduce_bytes",       # partial-combine bytes pushed up the tree
        "allreduces",         # allreduce participations (reduce + down-broadcast)
        "reducescatters",     # reduce-scatter participations (reduce + shard fan-out)
        "scatter_bytes",      # serialized shard bytes the root pushed to members
        "host_sync_fallbacks",  # group members that resolved a broadcast payload
                                # via the pull path (off the fast path: the
                                # elastic-roster degradation signal)
        "member_changes",     # roster epoch advances published by this process
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, 0)


COLL = _CollStats()


def bcast_key(group_name: str, tag: str) -> str:
    """Inbox key of a group-broadcast payload. Deterministic from (group,
    tag) and deliberately RANK-FREE: inboxes are per-process, so every
    member gets the same key — which is what lets the fan-out encode each
    chunk frame once and write identical bytes to every connection.
    Device-object broadcasts use the object id as the tag, so one broadcast
    per object id (the inbox tombstones a repeated key as a duplicate)."""
    return f"collbcast/{group_name}/{tag}"


def member_addr_key(group_name: str, rank: int) -> str:
    return f"collective/{group_name}/addr/{rank}"


def register_member_addr(gcs, group_name: str, rank: int, addr) -> None:
    """Publish this member's core-worker RPC address so a group broadcast
    can push payload frames straight at its inbox (no GCS mailbox on the
    fan-out path). Best-effort: a member without a row just gets the
    mailbox fallback."""
    import json

    try:
        gcs.call(
            "kv_put",
            {"key": member_addr_key(group_name, rank), "value": json.dumps(list(addr)).encode()},
        )
    except Exception:
        pass


def unregister_member_addr(gcs, group_name: str, rank: int) -> None:
    try:
        gcs.call("kv_del", {"key": member_addr_key(group_name, rank)})
    except Exception:
        pass


@blocking
def fetch_member_addrs(gcs, group_name: str, world_size: int, ranks=None) -> dict:
    """{rank: (host, port)} for every member that registered an address.
    Callers key their cache on the ROSTER EPOCH (``fetch_roster_epoch``)
    and drop it on any roster bump — membership is elastic, and a member
    that re-registered at the same coordinator epoch has a NEW address
    under the same rank row.

    ``ranks`` (optional) restricts the lookup to a roster snapshot's
    member set; default is ``range(world_size)`` (static-world callers).
    The lookups are batched CONCURRENTLY on the IO loop
    (the serial per-rank round scaled the fetch O(K) in GCS RTTs), and a
    GCS transport error PROPAGATES: a partitioned GCS must surface as a
    failure the caller can see, not read as "nobody registered" — which
    silently degraded every rank to the mailbox fallback. Only a per-row
    decode problem skips that one rank (it keeps the fallback path)."""
    import asyncio
    import json

    from ray_tpu._private.rpc import EventLoopThread

    ranks = list(ranks) if ranks is not None else list(range(world_size))
    keys = [member_addr_key(group_name, rank) for rank in ranks]

    async def _fetch_all():
        return await asyncio.gather(*(gcs.acall("kv_get", {"key": k}) for k in keys))

    responses = EventLoopThread.get().run(_fetch_all(), timeout=30.0)
    addrs: dict = {}
    for rank, resp in zip(ranks, responses):
        if not resp.get("found"):
            continue
        try:
            addrs[rank] = tuple(json.loads(bytes(resp["value"]).decode()))
        except Exception:
            continue  # malformed row: that rank keeps the mailbox fallback
    return addrs


# ---------------------------------------------------------------------------
# Epochal roster (elastic membership)
# ---------------------------------------------------------------------------

# The roster makes the per-member address rows AUTHORITATIVE: the member set
# of a group at any moment is `collective/<group>/roster/<epoch>` where
# <epoch> is the value of `collective/<group>/repoch`. Members join / leave /
# re-register by publishing the updated set at epoch+1 and bumping the
# counter; every verb snapshots the roster at send time and builds its
# topology over the CURRENT epoch. Mid-operation death is handled by retry
# (survivors keep their payload, rejoiners are re-pushed at their fresh
# address, the dead rank is left out of the next epoch) — NOT by a fence:
# two members racing an epoch bump can disagree for one verb, which then
# fails typed and the caller's next attempt sees the settled roster.

# Bounded back-window for the stale-row sweep: epochs advance one at a time,
# so sweeping this many predecessors on every bump keeps the KV at O(1) rows
# per group without a scan API.
_ROSTER_SWEEP_WINDOW = 16


def roster_epoch_key(group_name: str) -> str:
    return f"collective/{group_name}/repoch"


def roster_key(group_name: str, epoch: int) -> str:
    return f"collective/{group_name}/roster/{epoch}"


@blocking
def fetch_roster_epoch(gcs, group_name: str) -> int:
    """Latest roster epoch; 0 = no roster published (static-world group).
    The counter row is a fast-path HINT, not the truth: epoch rows are
    claimed put-if-absent (publish_roster), so the row sequence is the
    linearization point and a slow winner's counter write can land late
    (lag below a newer claim, whose sweep may already have deleted the
    hinted row). The frontier is found by scanning the live roster rows
    (one kv_keys prefix call — the GCS serves it atomically); the counter
    only covers the no-rows-but-counter-lingers case."""
    try:
        prefix = f"collective/{group_name}/roster/"
        keys = gcs.call("kv_keys", {"prefix": prefix}).get("keys", [])
        epochs = [int(k[len(prefix):]) for k in keys if k[len(prefix):].isdigit()]
        resp = gcs.call("kv_get", {"key": roster_epoch_key(group_name)})
        hinted = int(bytes(resp["value"]).decode()) if resp.get("found") else 0
        return max(epochs + [hinted])
    except Exception:
        return 0


@blocking
def fetch_roster(gcs, group_name: str) -> dict | None:
    """Snapshot the current roster: ``{"epoch", "ranks", "world_size"}``,
    or None when the group never published one (pre-elastic callers).

    A None here must MEAN no roster — a joiner that misreads a live group
    as roster-less derives a singleton member set and breaks the epoch
    chain (every claim must derive from its predecessor row). So a torn
    read — the frontier row swept by a newer claim between the scan and
    the get — is retried against the new frontier, and None is returned
    only when the scan itself shows no live rows."""
    import json

    prefix = f"collective/{group_name}/roster/"
    for attempt in range(4):
        try:
            keys = gcs.call("kv_keys", {"prefix": prefix}).get("keys", [])
        except Exception:
            return None
        epochs = [int(k[len(prefix):]) for k in keys if k[len(prefix):].isdigit()]
        if not epochs:
            # Live rows only — the counter hint is deliberately NOT
            # consulted: a lingering counter (destroy raced a publish)
            # naming no live row must read as "no roster", not wedge
            # every reader on a phantom epoch.
            return None
        epoch = max(epochs)
        try:
            resp = gcs.call("kv_get", {"key": roster_key(group_name, epoch)})
            if not resp.get("found"):
                continue  # swept mid-read: frontier moved, re-scan
            doc = json.loads(bytes(resp["value"]).decode())
            ranks = sorted(int(r) for r in doc.get("ranks", []))
            return {
                "epoch": epoch,
                "ranks": ranks,
                "world_size": int(doc.get("world_size") or ((max(ranks) + 1) if ranks else 0)),
            }
        except Exception:
            return None
    return None


def _record_member_change(group_name: str, reason: str, rank, epoch: int, nranks: int) -> None:
    try:
        from ray_tpu._private import flight_recorder

        flight_recorder.record(
            "coll_member_change",
            f"{group_name}:{reason}:r{'' if rank is None else rank}:e{epoch}:n{nranks}",
        )
    except Exception:
        pass


@blocking
def publish_roster(gcs, group_name: str, ranks, world_size: int | None = None,
                   reason: str = "advance", rank: int | None = None,
                   base_epoch: int | None = None) -> int | None:
    """CLAIM roster epoch ``base_epoch + 1`` with the given member set,
    bump the counter hint, and sweep the stale predecessor rows (satellite
    of the epoch advance: dead-epoch ``roster/<e>`` rows must not pile up
    in the KV). Returns the claimed epoch, or **None when the claim lost**
    the race.

    The row is written put-if-absent and ONLY at base+1, which makes the
    roster a derivation CHAIN: the winner of epoch e+1 provably derived
    its set from row e (it read row e, and nobody else claimed e+1 in
    between). A rank present in row e can therefore only disappear via an
    explicit leave/evict, never a stale-read overwrite — the lost-update
    hole where a gang joiner's stale read used to erase an already
    verified peer. A None return means ``ranks`` was derived from a row
    that is no longer the frontier; the caller must RE-READ and RE-DERIVE
    (roster_join/roster_leave loop exactly that)."""
    import json

    ranks = sorted(set(int(r) for r in ranks))
    world = int(world_size) if world_size else ((max(ranks) + 1) if ranks else 0)
    if base_epoch is None:
        base_epoch = fetch_roster_epoch(gcs, group_name)
    epoch = int(base_epoch) + 1
    doc = json.dumps({"ranks": ranks, "world_size": world}).encode()
    resp = gcs.call(
        "kv_put",
        {"key": roster_key(group_name, epoch), "value": doc, "overwrite": False},
    )
    if not resp.get("added"):
        return None
    # Counter hint: never drag it BACKWARD below a later winner's write
    # (the frontier scan heals any regression that slips through the
    # read-check window).
    try:
        resp = gcs.call("kv_get", {"key": roster_epoch_key(group_name)})
        hinted = int(bytes(resp["value"]).decode()) if resp.get("found") else 0
    except Exception:
        hinted = 0
    if epoch > hinted:
        gcs.call("kv_put", {"key": roster_epoch_key(group_name), "value": str(epoch).encode()})
    # Hygiene sweep, LAGGED by a full window: rows in (epoch-W, epoch) must
    # stay — deleting an immediate predecessor re-opens its key for a
    # put-if-absent claim, letting a stale joiner "win" on a dead fork
    # below the frontier (its membership would never enter the chain). A
    # claimant would have to be W epochs stale within one read-claim
    # round trip to fork past the lag.
    for old in range(max(1, epoch - 2 * _ROSTER_SWEEP_WINDOW),
                     max(1, epoch - _ROSTER_SWEEP_WINDOW + 1)):
        try:
            gcs.call("kv_del", {"key": roster_key(group_name, old)})
        except Exception:
            pass
    COLL.member_changes += 1
    _record_member_change(group_name, reason, rank, epoch, len(ranks))
    return epoch


@blocking
def roster_join(gcs, group_name: str, rank: int, world_size: int | None = None,
                attempts: int = 24) -> int:
    """Add ``rank`` to the roster (join, or RE-REGISTER when the rank is
    already listed — a respawned member at a new address must still bump
    the epoch so every peer's address cache drops). Each attempt reads the
    frontier row, unions itself in, and claims DIRECTLY on top of the row
    it derived from (publish_roster, put-if-absent at base+1) — a won
    claim therefore provably contains this rank AND every rank of the
    predecessor row, so no verify pass is needed and no racing joiner can
    erase an already returned peer. A lost claim means the frontier moved:
    re-read, re-derive, retry — convergent because one claimant wins every
    epoch (worst case: a K-member gang join takes K rounds)."""
    rank = int(rank)
    epoch = 0
    for attempt in range(attempts):
        cur = fetch_roster(gcs, group_name)
        ranks = set(cur["ranks"]) if cur else set()
        rejoin = rank in ranks
        ranks.add(rank)
        world = max(world_size or 0, (cur["world_size"] if cur else 0), rank + 1)
        # base is the epoch this derivation OBSERVED — never a re-probed
        # frontier (a fresh probe can see a row this read never did, and
        # claiming on top of an unread row drops its members from the
        # chain). A None read observed epoch 0: claim row 1 or lose and
        # re-derive.
        base = cur["epoch"] if cur else 0
        epoch = publish_roster(
            gcs, group_name, ranks, world,
            reason="rejoin" if rejoin else "join", rank=rank, base_epoch=base,
        )
        if epoch is not None:
            return epoch
        time.sleep(0.005 * (attempt + 1))
    import logging

    logging.getLogger(__name__).warning(
        "roster join for group %r rank %s lost every claim attempt "
        "(pathological churn); membership not asserted", group_name, rank,
    )
    return fetch_roster_epoch(gcs, group_name)


@blocking
def roster_leave(gcs, group_name: str, rank: int, reason: str = "leave") -> int | None:
    """Drop ``rank`` from the roster (voluntary leave, or a verb evicting a
    member it could not deliver to — ``reason="death"``) and delete its now
    orphaned address row. No-op (None) when the group has no roster or the
    rank is already gone. Claims on top of the row it derived from
    (publish_roster base+1); a lost claim re-reads and re-derives so a
    racing join is never erased."""
    for attempt in range(12):
        cur = fetch_roster(gcs, group_name)
        if cur is None or int(rank) not in cur["ranks"]:
            return None
        ranks = [r for r in cur["ranks"] if r != int(rank)]
        epoch = publish_roster(
            gcs, group_name, ranks, cur["world_size"], reason=reason,
            rank=int(rank), base_epoch=cur["epoch"],
        )
        if epoch is not None:
            unregister_member_addr(gcs, group_name, int(rank))
            return epoch
        time.sleep(0.005 * (attempt + 1))
    return None


@blocking
def sweep_group_kv(gcs, group_name: str, world_size: int = 0) -> int:
    """Teardown sweep: delete EVERY collective KV row of ``group_name`` —
    the roster-epoch counter, the roster back-window, and all member
    address rows — so a destroyed group leaves the KV at baseline. Returns
    the number of delete calls issued (best-effort; a partitioned GCS
    sweeps on the next destroy)."""
    n = 0
    try:
        cur = fetch_roster(gcs, group_name)
        epoch = fetch_roster_epoch(gcs, group_name)
        world = max(
            world_size, cur["world_size"] if cur else 0,
            (max(cur["ranks"]) + 1) if cur and cur["ranks"] else 0,
        )
        keys = [roster_epoch_key(group_name)]
        keys += [roster_key(group_name, e)
                 for e in range(max(1, epoch - 2 * _ROSTER_SWEEP_WINDOW), epoch + 1)]
        keys += [member_addr_key(group_name, r) for r in range(world)]
        for key in keys:
            try:
                gcs.call("kv_del", {"key": key})
                n += 1
            except Exception:
                pass
    except Exception:
        pass
    return n


@blocking
def group_bcast_send(
    cw,
    gcs,
    group_name: str,
    src_rank: int,
    world_size: int,
    tag: str,
    value,
    member_addrs: dict | None = None,
    timeout: float = 30.0,
    mailbox_fallback: bool = True,
    topology: str = "tree",
    roster: dict | None = None,
) -> dict:
    """Fan ``value`` to every OTHER rank of the group as ONE group
    operation: one serialize, each chunk frame ENCODED ONCE
    (``RpcClient.pack_push_frame`` — the rank-free inbox key is what makes
    the bytes identical), every rank confirmed by a ``p2p_ack`` round trip.
    Ranks without a registered address fall back to the GCS-KV mailbox
    under the same logical tag. Never raises for a dead member: the result
    names it so the caller owns the policy —
    ``{"ok_ranks": [...], "fallback_ranks": [...], "failed": {rank: reason},
    "bytes": payload_bytes, "topology": ..., "root_egress_bytes": ...,
    "retried_ranks": [...], "rejoined_ranks": [...], "evicted_ranks": [...],
    "roster_epoch": ...}``.

    ``roster`` is the elastic-membership snapshot (``fetch_roster``): when
    present, the target set is the CURRENT epoch's member ranks (not
    ``range(world_size)``), a rank that fails its first delivery is
    re-fetched from the address registry and retried once at its fresh
    address (it may have RE-REGISTERED mid-operation — survivors +
    rejoiners, not a frozen world), and ranks that still cannot be reached
    are EVICTED: the roster advances one epoch without them, so the next
    verb builds its topology over the survivors instead of failing forever
    against a corpse. When ``roster=None`` and no member_addrs are passed,
    the snapshot is taken here.

    ``topology="tree"`` (default, ≥2 addressed ranks): the root pushes
    chunk frames only to its BINOMIAL-TREE children, each frame carrying
    the child's relay spec; mid-tree members forward every chunk to their
    own children the moment it lands (cut-through — :class:`RelayTable`),
    so root egress is O(log K) streams instead of K. The per-member
    contract is unchanged: the root still acks EVERY rank directly, and
    any rank whose ack fails (a dead relay orphans its whole subtree) is
    retried DIRECTLY with a flat resend — one dead relay costs one named
    failure plus re-delivered orphans, not K/2 failed members. A rank
    still failing after the direct retry is named with its orphaned
    subtree. ``topology="flat"`` is PR 15's fan-out (every rank pushed
    from the root), kept for the bench A/B and as the retry primitive.

    This is the cpu-backend group op behind device_object.broadcast(); on
    TPU hardware the same seam maps to an ICI broadcast (tpu_group.py)."""
    import asyncio

    from ray_tpu._private import serialization
    from ray_tpu._private.rpc import RpcClient

    data = serialization.dumps(value)
    if member_addrs is None:
        if roster is None:
            roster = fetch_roster(gcs, group_name)
        member_addrs = fetch_member_addrs(
            gcs, group_name, world_size,
            ranks=roster["ranks"] if roster else None,
        )
    else:
        member_addrs = dict(member_addrs)
    total = max(1, (len(data) + _DIRECT_CHUNK_BYTES - 1) // _DIRECT_CHUNK_BYTES)
    if roster is not None:
        targets = [r for r in roster["ranks"] if r != src_rank]
    else:
        targets = [r for r in range(world_size) if r != src_rank]
    addressed = [r for r in targets if r in member_addrs]
    use_tree = topology == "tree" and len(addressed) >= 2
    result = {
        "ok_ranks": [], "fallback_ranks": [], "failed": {}, "bytes": len(data),
        "topology": "tree" if use_tree else "flat",
        "root_egress_bytes": 0, "retried_ranks": [], "rejoined_ranks": [],
        "evicted_ranks": [],
        "roster_epoch": roster["epoch"] if roster else 0,
    }
    key = bcast_key(group_name, tag)
    chunks = [
        data[i * _DIRECT_CHUNK_BYTES : (i + 1) * _DIRECT_CHUNK_BYTES]
        for i in range(total)
    ]
    frames = [
        RpcClient.pack_push_frame(
            "p2p_data",
            {"key": key, "idx": i, "total": total, "data": chunks[i]},
        )
        for i in range(total)
    ]

    # Tree positions: [root] + addressed ranks in rank order — every rank
    # appears exactly once, so parent/child is a pure function of the
    # (group, membership) pair. ``subtree`` maps each rank to its
    # descendant ranks for the orphan annotation on failures.
    subtree: dict[int, list[int]] = {}
    root_specs: list[dict] = []
    if use_tree:
        order = [src_rank] + sorted(addressed)

        def _spec(pos: int) -> dict:
            rank = order[pos]
            kids = [_spec(c) for c in _binomial_children(pos, len(order))]
            desc: list[int] = []
            for k in kids:
                desc.append(k["rank"])
                desc.extend(subtree[k["rank"]])
            subtree[rank] = sorted(desc)
            return {"rank": rank, "addr": list(member_addrs[rank]), "children": kids}

        root_specs = [_spec(c) for c in _binomial_children(0, len(order))]
        result["root_children"] = sorted(s["rank"] for s in root_specs)

    # Ack wait scales with the caller's budget (clamped by the server at
    # 30s): a slow-but-healthy member still reassembling a large payload
    # must not be branded a failed rank by a fixed small bound.
    ack_wait = max(_BCAST_ACK_S, min(30.0, timeout))

    async def _push_direct(rank: int):
        client = cw._owner_client(tuple(member_addrs[rank]))
        for i, frame in enumerate(frames):
            await _gate_egress(len(chunks[i]))
            await client.apush_packed("p2p_data", frame)
        result["root_egress_bytes"] += len(data)

    async def _ack(rank: int, wait: float):
        client = cw._owner_client(tuple(member_addrs[rank]))
        resp = await client.acall(
            "p2p_ack", {"key": key, "timeout": wait},
            timeout=wait + 5.0, retries=0,
        )
        if not resp.get("ok"):
            raise RuntimeError("p2p_ack reported the payload never landed")

    async def _deliver(rank: int):
        await _push_direct(rank)
        await _ack(rank, ack_wait)

    async def _deliver_tree_child(spec: dict):
        client = cw._owner_client(tuple(spec["addr"]))
        if spec["children"]:
            relay = {"rank": spec["rank"], "children": spec["children"]}
            # Relay spec rides EVERY chunk frame: whichever lands first
            # opens the session, so loss/reorder of any one frame cannot
            # stall the whole subtree.
            for i in range(total):
                await _gate_egress(len(chunks[i]))
                await client.apush(
                    "p2p_data",
                    {"key": key, "idx": i, "total": total,
                     "data": chunks[i], "relay": relay},
                )
        else:
            for i, frame in enumerate(frames):
                await _gate_egress(len(chunks[i]))
                await client.apush_packed("p2p_data", frame)
        result["root_egress_bytes"] += len(data)
        await _ack(spec["rank"], ack_wait)

    async def _fan_out():
        tasks: dict = {}
        if use_tree:
            for spec in root_specs:
                tasks[spec["rank"]] = asyncio.ensure_future(
                    asyncio.wait_for(_deliver_tree_child(spec), timeout)
                )
            for rank in addressed:
                if rank not in tasks:  # delivered by a relay: ack only
                    tasks[rank] = asyncio.ensure_future(
                        asyncio.wait_for(_ack(rank, ack_wait), timeout)
                    )
        else:
            for rank in addressed:
                tasks[rank] = asyncio.ensure_future(
                    asyncio.wait_for(_deliver(rank), timeout)
                )
        if tasks:
            await asyncio.wait(tasks.values())
        outcomes = {rank: t.exception() for rank, t in tasks.items()}
        if use_tree:
            round1 = [r for r, e in outcomes.items() if e is not None]
            if round1:
                # Orphan recovery: a failed ack means the rank is dead OR a
                # relay above it died — re-deliver DIRECTLY (flat resend;
                # duplicate chunks overwrite partials in the inbox) so one
                # dead relay doesn't fail its whole healthy subtree.
                retry_ack = max(5.0, min(ack_wait, 10.0))

                async def _retry(rank: int):
                    await _push_direct(rank)
                    await _ack(rank, retry_ack)

                rtasks = {
                    r: asyncio.ensure_future(
                        asyncio.wait_for(_retry(r), retry_ack + 10.0)
                    )
                    for r in round1
                }
                await asyncio.wait(rtasks.values())
                for r, t in rtasks.items():
                    if t.exception() is None:
                        outcomes[r] = None
                        result["retried_ranks"].append(r)
                        COLL.bcast_retries += 1
        return outcomes

    # Outer bound is a backstop over the per-member wait_for; each member's
    # delivery is already clamped to ``timeout`` individually (plus the
    # bounded retry round in tree mode).
    outer = timeout + 15.0 + (20.0 if use_tree else 0.0)
    outcomes = cw._io.run(_fan_out(), timeout=outer) if targets else {}

    # Elastic round: a rank that failed delivery may have RE-REGISTERED at
    # a fresh address mid-operation (its replacement actor joined under the
    # same rank). Re-read its address row — bypassing every cache — and
    # retry once directly. This is the "survivors + rejoiners" half of the
    # epochal contract; the eviction below is the other half.
    if roster is not None:
        lost = [r for r in addressed if outcomes.get(r) is not None]
        if lost:
            try:
                fresh = fetch_member_addrs(gcs, group_name, world_size, ranks=lost)
            except Exception:
                fresh = {}
            rejoiners = [
                r for r in lost if fresh.get(r) and fresh[r] != member_addrs.get(r)
            ]
            if rejoiners:
                member_addrs.update({r: fresh[r] for r in rejoiners})
                rejoin_ack = max(5.0, min(ack_wait, 10.0))

                async def _rejoin_round():
                    tasks = {
                        r: asyncio.ensure_future(
                            asyncio.wait_for(_deliver(r), rejoin_ack + 10.0)
                        )
                        for r in rejoiners
                    }
                    await asyncio.wait(tasks.values())
                    return {r: t.exception() for r, t in tasks.items()}

                try:
                    redone = cw._io.run(_rejoin_round(), timeout=rejoin_ack + 20.0)
                except Exception:
                    redone = {}
                for r, exc in redone.items():
                    if exc is None:
                        outcomes[r] = None
                        result["rejoined_ranks"].append(r)

    for rank in targets:
        if rank not in member_addrs:
            # Never registered an address (old-style member): the GCS
            # mailbox is its normal path, not a failure — but ONLY for
            # callers whose receivers actually poll it
            # (bcast_recv_payload). The device-object descriptor path
            # resolves from the direct inbox alone, so there a mailbox
            # drop would be dead weight in the KV and a false "delivered"
            # — it reports the rank failed instead.
            if not mailbox_fallback:
                result["failed"][rank] = "no registered member address"
                COLL.bcast_failed_ranks += 1
                continue
            try:
                mailbox_send(gcs, group_name, src_rank, rank, f"bcast/{tag}", value)
                _schedule_bcast_janitor(cw, gcs, mailbox_key(group_name, src_rank, rank, f"bcast/{tag}"))
                result["fallback_ranks"].append(rank)
                COLL.bcast_fallbacks += 1
            except Exception as e:
                result["failed"][rank] = repr(e)
                COLL.bcast_failed_ranks += 1
            continue
        exc = outcomes.get(rank)
        if exc is None:
            result["ok_ranks"].append(rank)
        else:
            # A REGISTERED member we could not deliver to is dead, severed,
            # or wedged — a GCS mailbox drop would "succeed" against a
            # corpse (the KV is alive either way), so the honest outcome is
            # a named failure the caller can act on.
            reason = repr(exc)
            orphans = subtree.get(rank) or []
            if orphans:
                recovered = sorted(set(orphans) & set(result["retried_ranks"]))
                reason += (
                    f" [tree relay: orphaned subtree ranks {orphans}"
                    + (f"; re-delivered directly: {recovered}" if recovered else "")
                    + "]"
                )
            result["failed"][rank] = reason
            COLL.bcast_failed_ranks += 1
    result["retried_ranks"].sort()
    if roster is not None and result["failed"]:
        # Eviction: advance the epoch without the members this op could not
        # reach — the NEXT verb topologizes over the survivors instead of
        # failing forever against a corpse. A live member evicted by a
        # transient stall is not stranded: its next re-register (or the
        # sync loop's respawn) rejoins at epoch+1. One batch publish, not
        # one bump per corpse.
        dead = sorted(set(result["failed"]) & set(roster["ranks"]))
        if dead:
            try:
                # Claim on top of the row the survivor set derives from;
                # a lost claim (concurrent join/leave moved the frontier)
                # re-reads and re-derives so a racing rejoiner is never
                # erased by this eviction.
                cur = roster
                for attempt in range(6):
                    survivors = [r for r in cur["ranks"] if r not in set(dead)]
                    if set(survivors) == set(cur["ranks"]):
                        break  # every dead rank already evicted elsewhere
                    ep = publish_roster(
                        gcs, group_name, survivors, cur["world_size"],
                        reason="death", rank=dead[0], base_epoch=cur["epoch"],
                    )
                    if ep is not None:
                        break
                    time.sleep(0.005 * (attempt + 1))
                    cur = fetch_roster(gcs, group_name)
                    if cur is None:
                        break
                for r in dead:
                    unregister_member_addr(gcs, group_name, r)
                result["evicted_ranks"] = dead
            except Exception:
                pass  # GCS hiccup: the next verb's snapshot retries
    COLL.bcast_sends += 1
    if use_tree:
        COLL.tree_sends += 1
    COLL.root_egress_bytes += result["root_egress_bytes"]
    COLL.bcast_send_bytes += len(data) * (
        len(result["ok_ranks"]) + len(result["fallback_ranks"])
    )
    return result


async def sweep_stale_group_rows(gcs, group_name: str) -> int:
    """GCS hygiene for one group: delete dead-epoch ``roster/<e>`` and
    coordinator ``coord/<e>`` rows behind the current epochs, plus orphaned
    ``addr/<rank>`` rows of ranks no longer in the roster (a SIGKILLed
    member never unregisters itself). Runs on the IO loop; called on every
    roster advance (inline, via publish_roster's back-window) and from the
    mailbox janitors. Best-effort: a partitioned GCS sweeps next time."""
    import json

    n = 0
    try:
        resp = await gcs.acall("kv_get", {"key": roster_epoch_key(group_name)})
        epoch = int(bytes(resp["value"]).decode()) if resp.get("found") else 0
        # Lagged like publish_roster's inline sweep: rows within a window
        # of the frontier must stay, or their freed keys become claimable
        # forks for a stale put-if-absent join.
        for old in range(max(1, epoch - 2 * _ROSTER_SWEEP_WINDOW),
                         max(1, epoch - _ROSTER_SWEEP_WINDOW + 1)):
            await gcs.acall("kv_del", {"key": roster_key(group_name, old)})
            n += 1
        # tpu_group's jax.distributed rendezvous epochs (a separate counter:
        # one per world re-formation, not per membership change).
        resp = await gcs.acall("kv_get", {"key": f"collective/{group_name}/epoch"})
        cepoch = int(bytes(resp["value"]).decode()) if resp.get("found") else 0
        for old in range(max(1, cepoch - _ROSTER_SWEEP_WINDOW), cepoch):
            await gcs.acall("kv_del", {"key": f"collective/{group_name}/coord/{old}"})
            n += 1
        if epoch:
            resp = await gcs.acall("kv_get", {"key": roster_key(group_name, epoch)})
            if resp.get("found"):
                doc = json.loads(bytes(resp["value"]).decode())
                ranks = set(int(r) for r in doc.get("ranks", []))
                world = int(doc.get("world_size") or 0)
                for r in range(world):
                    if r not in ranks:
                        await gcs.acall(
                            "kv_del", {"key": member_addr_key(group_name, r)}
                        )
                        n += 1
    except Exception:
        pass
    return n


def _schedule_bcast_janitor(cw, gcs, key: str, delay_s: float = 180.0) -> None:
    """A mailbox-fallback payload a dead/slow member never claims must not
    sit in the GCS KV forever (same janitor shape as
    DeviceObjectManager._schedule_mailbox_janitor). The sweep also runs the
    per-group stale-row janitor: a group leaning on the mailbox fallback is
    exactly the kind whose dead-epoch roster/coord/addr rows accumulate."""
    # mailbox_key layout: collective/<group>/p2p/<src>-><dst>/<tag>
    parts = key.split("/")
    group_name = parts[1] if len(parts) > 2 and parts[0] == "collective" else None

    async def _sweep():
        import asyncio

        await asyncio.sleep(delay_s)
        try:
            await gcs.acall("kv_del", {"key": key})
        except Exception:
            pass
        if group_name:
            await sweep_stale_group_rows(gcs, group_name)

    try:
        cw._io.spawn(_sweep())
    except Exception:
        pass


@blocking
def group_bcast_recv(cw, gcs, group_name: str, src_rank: int, my_rank: int, tag: str, timeout: float = 120.0, abort_check=None):
    """Member-side receive of a group broadcast: watch BOTH landing zones —
    the direct mailbox (steady state: the payload is already here, or
    arrives whenever the sender's chunk pushes finish) and the GCS mailbox
    (the sender's fallback for members it could not dial) — until the
    deadline; typed timeout naming group/rank/tag otherwise. Interleaved
    on purpose: a receiver that blocks before the sender starts (normal
    collective ordering) must catch a direct delivery landing at ANY point
    in the window, not just the first second. ``abort_check`` (optional)
    turns a concurrent ``destroy_collective_group`` into an IMMEDIATE typed
    CollectiveError instead of a full-timeout park — a destroyed group's
    payload is never coming."""
    from ray_tpu._private import serialization
    from ray_tpu.exceptions import CollectiveError, CollectiveTimeoutError

    deadline = time.monotonic() + timeout
    key = bcast_key(group_name, tag)
    gcs_key = mailbox_key(group_name, src_rank, my_rank, f"bcast/{tag}")
    while True:
        if abort_check is not None and abort_check():
            raise CollectiveError(
                f"group {group_name!r} was destroyed while rank {my_rank} "
                f"waited for broadcast tag {tag!r} from rank {src_rank}"
            )
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            COLL.timeouts += 1
            raise CollectiveTimeoutError(
                f"group broadcast recv on {group_name!r} tag {tag!r}: nothing "
                f"from rank {src_rank} within {timeout}s (direct mailbox and "
                "GCS fallback both empty)",
                group=group_name, ranks=[src_rank], tag=tag,
            )
        data = direct_recv(cw, key, timeout=min(0.25, remaining))
        if data is not None:
            COLL.bcast_recvs += 1
            return serialization.loads(data)
        try:
            resp = gcs.call("kv_get", {"key": gcs_key})
            if resp.get("found"):
                gcs.call("kv_del", {"key": gcs_key})
                COLL.bcast_recvs += 1
                return serialization.loads(resp["value"])
        except Exception:
            pass  # GCS hiccup: the direct-path wait keeps the clock


@blocking
def direct_recv(cw, key: str, timeout: float, abort_check=None) -> bytes | None:
    """Wait for a direct-mailbox payload under ``key``. Returns the bytes,
    or None when ``timeout`` expires (caller falls back to the pull path)
    or ``abort_check()`` goes true (teardown / poison: caller surfaces its
    own typed error). Steady state returns without sleeping — for channel
    payloads the deposit itself is what woke the reader, so the bytes are
    already here by the time the consumer resolves the slot."""
    inbox = cw.p2p_inbox
    deadline = time.monotonic() + timeout
    ev = inbox._waiter(key)
    try:
        while True:
            data = inbox.take(key)
            if data is not None:
                return data
            if abort_check is not None and abort_check():
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            ev.wait(min(0.05, remaining))
            ev.clear()
    finally:
        inbox._drop_waiter(key)


# ---------------------------------------------------------------------------
# Group reduce / allreduce (chunk-wise combine at every relay hop)
# ---------------------------------------------------------------------------


def reduce_key(group_name: str, tag: str, src_rank: int) -> str:
    """Stream key for ONE member's partial chunks flowing up the reduce
    tree. Rank-scoped (unlike :func:`bcast_key`): a parent combining k
    children must tell their streams apart. The ``collred/`` prefix routes
    these frames into :class:`ChunkStreams` instead of the inbox."""
    return f"collred/{group_name}/{tag}/{src_rank}"


async def _push_reduce_chunk(client, key: str, idx: int, total: int, data: bytes):
    await _gate_egress(len(data))
    await client.apush(
        "p2p_data", {"key": key, "idx": idx, "total": total, "data": data}
    )


@blocking
def group_reduce_send(
    cw,
    gcs,
    group_name: str,
    my_rank: int,
    world_size: int,
    tag: str,
    value,
    op: ReduceOp = ReduceOp.SUM,
    dst_rank: int = 0,
    member_addrs: dict | None = None,
    timeout: float = 60.0,
    roster: dict | None = None,
):
    """One member's share of a TREE reduce toward ``dst_rank``: wait per
    chunk index for each tree child's combined partial, merge it into this
    rank's own slice ELEMENTWISE, and push the result to the parent the
    moment it's ready (cut-through combine — a chunk flows up while later
    chunks are still arriving below). Every rank of the group must call
    this with the same (tag, op, dst_rank); chunks travel as dense
    ``dtype`` bytes (NOT serialized objects) so relay hops can combine
    without a deserialize round trip.

    Returns the reduced ``np.ndarray`` on ``dst_rank``, None elsewhere.
    MEAN sums up the tree and divides ONCE at the root (matching
    ``np.stack(...).mean(axis=0)`` bit-for-bit on exact inputs). Requires
    every member to have a registered address — callers (cpu_group) fall
    back to the GCS ring otherwise. A silent child raises a typed
    CollectiveTimeoutError NAMING it; a shape/dtype disagreement surfaces
    as a CollectiveError naming both ranks.

    ``roster`` (elastic membership): the tree spans the CURRENT epoch's
    member ranks, not ``range(world_size)`` — every participant must
    snapshot the same epoch (they rendezvous through the per-rank stream
    keys, so a disagreement surfaces as the typed child timeout and the
    caller retries against the settled roster; a partial reduce is poison,
    so there is no in-op rejoin round here, unlike broadcast)."""
    import numpy as np

    from ray_tpu.exceptions import CollectiveError, CollectiveTimeoutError

    member_ranks = sorted(roster["ranks"]) if roster else list(range(world_size))
    if roster is not None and (my_rank not in member_ranks or dst_rank not in member_ranks):
        raise CollectiveError(
            f"tree reduce on group {group_name!r}: rank {my_rank} -> "
            f"{dst_rank} not in roster epoch {roster['epoch']} "
            f"(members {member_ranks}) — re-register before reducing"
        )
    if member_addrs is None:
        member_addrs = fetch_member_addrs(gcs, group_name, world_size, ranks=member_ranks)
    missing = [r for r in member_ranks if r != my_rank and r not in member_addrs]
    if missing:
        raise CollectiveError(
            f"tree reduce on group {group_name!r} needs a registered address "
            f"for every member; missing ranks {missing}"
        )
    arr = np.ascontiguousarray(value)
    combine = {
        ReduceOp.SUM: np.add,
        ReduceOp.PRODUCT: np.multiply,
        ReduceOp.MIN: np.minimum,
        ReduceOp.MAX: np.maximum,
        ReduceOp.MEAN: np.add,  # summed at every hop; the root divides once
    }[op]
    # Same deterministic shape as the broadcast tree, rooted at dst_rank —
    # a pure function of the (group, roster-epoch) pair, so every member's
    # snapshot of the same epoch yields the same tree.
    order = [dst_rank] + sorted(r for r in member_ranks if r != dst_rank)
    pos = order.index(my_rank)
    kid_ranks = [order[c] for c in _binomial_children(pos, len(order))]
    parent_client = None
    if pos:
        parent_rank = order[pos - (1 << (pos.bit_length() - 1))]
        parent_client = cw._owner_client(tuple(member_addrs[parent_rank]))
    data = arr.tobytes()
    # Chunk on element boundaries so every chunk is a dense dtype slice.
    itemsize = max(1, arr.dtype.itemsize)
    chunk_bytes = max(itemsize, (_DIRECT_CHUNK_BYTES // itemsize) * itemsize)
    total = max(1, (len(data) + chunk_bytes - 1) // chunk_bytes)
    deadline = time.monotonic() + timeout
    streams = cw.p2p_streams
    up_key = reduce_key(group_name, tag, my_rank)
    out_parts: list = []
    try:
        for idx in range(total):
            own = np.frombuffer(
                data[idx * chunk_bytes : (idx + 1) * chunk_bytes], dtype=arr.dtype
            )
            acc = own
            for kr in kid_ranks:
                chunk = streams.wait_chunk(reduce_key(group_name, tag, kr), idx, deadline)
                if chunk is None:
                    COLL.timeouts += 1
                    raise CollectiveTimeoutError(
                        f"tree reduce on group {group_name!r} tag {tag!r} "
                        f"(rank {my_rank}): no chunk {idx}/{total} from child "
                        f"rank {kr} within {timeout}s",
                        group=group_name, ranks=[kr], tag=tag,
                    )
                if len(chunk) != own.nbytes:
                    raise CollectiveError(
                        f"tree reduce on group {group_name!r} tag {tag!r}: "
                        f"chunk {idx} from rank {kr} is {len(chunk)} bytes, "
                        f"rank {my_rank} expects {own.nbytes} — members "
                        "disagree on shape/dtype"
                    )
                acc = combine(acc, np.frombuffer(chunk, dtype=arr.dtype))
            if parent_client is None:
                out_parts.append(acc)
            else:
                payload = acc.tobytes()
                cw._io.run(
                    _push_reduce_chunk(parent_client, up_key, idx, total, payload),
                    timeout=30.0,
                )
                COLL.reduce_bytes += len(payload)
    finally:
        for kr in kid_ranks:
            streams.purge(reduce_key(group_name, tag, kr))
    COLL.reduce_sends += 1
    if parent_client is not None:
        return None
    out = np.concatenate(out_parts) if len(out_parts) > 1 else out_parts[0]
    out = np.array(out).reshape(arr.shape)
    if op is ReduceOp.MEAN:
        out = out / len(order)
    return out


@blocking
def group_allreduce(
    cw,
    gcs,
    group_name: str,
    my_rank: int,
    world_size: int,
    tag: str,
    value,
    op: ReduceOp = ReduceOp.SUM,
    member_addrs: dict | None = None,
    timeout: float = 60.0,
    finalize=None,
    roster: dict | None = None,
):
    """Tree allreduce: reduce up to the root (lowest roster rank; rank 0 in
    a static world), then tree-broadcast the combined result back down —
    every rank returns the same reduced value after 2·depth hops instead of
    a K-wide ring epoch. ``finalize`` (optional) runs ON THE ROOT before
    the down-broadcast (e.g. a jnp conversion), so output placement is
    decided once and every rank receives the finalized payload — placement
    parity with ``broadcast``. Raises CollectiveBroadcastError if the
    down-broadcast misses a rank (an allreduce is all-or-nothing: a member
    without the result would silently diverge). ``roster`` restricts the
    whole op to the current epoch's member set."""
    from ray_tpu.exceptions import CollectiveBroadcastError

    root = min(roster["ranks"]) if roster and roster["ranks"] else 0
    red = group_reduce_send(
        cw, gcs, group_name, my_rank, world_size, tag, value,
        op=op, dst_rank=root, member_addrs=member_addrs, timeout=timeout,
        roster=roster,
    )
    COLL.allreduces += 1
    down_tag = f"allred/{tag}"
    if my_rank == root:
        out = finalize(red) if finalize is not None else red
        res = group_bcast_send(
            cw, gcs, group_name, root, world_size, down_tag, out,
            member_addrs=member_addrs, timeout=timeout, mailbox_fallback=False,
            roster=roster,
        )
        if res["failed"]:
            raise CollectiveBroadcastError(
                f"allreduce down-broadcast on group {group_name!r} failed for "
                f"ranks {sorted(res['failed'])}",
                group=group_name, failed=res["failed"], info=res,
            )
        return out
    return group_bcast_recv(cw, gcs, group_name, root, my_rank, down_tag, timeout)


def scatter_key(group_name: str, tag: str, dst_rank: str | int) -> str:
    """Inbox key of ONE member's reduce-scatter shard. Rank-scoped like
    :func:`reduce_key` (every member gets a DIFFERENT shard, so there is no
    shared-frame encoding to exploit, unlike broadcast)."""
    return f"collscat/{group_name}/{tag}/{dst_rank}"


@blocking
def group_reducescatter(
    cw,
    gcs,
    group_name: str,
    my_rank: int,
    world_size: int,
    tag: str,
    value,
    op: ReduceOp = ReduceOp.SUM,
    member_addrs: dict | None = None,
    timeout: float = 60.0,
    finalize=None,
    roster: dict | None = None,
):
    """Tree reduce-scatter: combine every member's tensor up the binomial
    tree to the root (lowest roster rank), which slices axis 0 into one
    shard per member and pushes each member ITS shard over the direct
    mailbox — each rank moves the full tensor up at most once and receives
    exactly 1/K of the result, vs the GCS ring where every rank posts the
    full tensor to the KV and downloads K of them. Semantics match the ring
    ``reducescatter``: the leading dimension must equal the member count,
    and the rank at sorted-roster position ``i`` returns reduced slice
    ``i``. ``finalize`` (optional) runs per-shard ON THE ROOT before the
    fan-out, so placement is decided once (allreduce's contract). The shard
    frames are fire-and-forget; a lost one surfaces as a typed
    CollectiveTimeoutError on the receiver NAMING the root. ``roster``
    restricts the op to the current epoch's member set."""
    from ray_tpu._private import serialization
    from ray_tpu.exceptions import CollectiveError, CollectiveTimeoutError

    member_ranks = sorted(roster["ranks"]) if roster else list(range(world_size))
    k = len(member_ranks)
    shape0 = getattr(value, "shape", (None,))[0] if hasattr(value, "shape") else None
    if shape0 != k:
        raise CollectiveError(
            f"reducescatter on group {group_name!r} needs leading dimension "
            f"== member count {k}, got shape {getattr(value, 'shape', '?')}"
        )
    root = member_ranks[0]
    red = group_reduce_send(
        cw, gcs, group_name, my_rank, world_size, tag, value,
        op=op, dst_rank=root, member_addrs=member_addrs, timeout=timeout,
        roster=roster,
    )
    COLL.reducescatters += 1
    if my_rank != root:
        data = direct_recv(cw, scatter_key(group_name, tag, my_rank), timeout=timeout)
        if data is None:
            COLL.timeouts += 1
            raise CollectiveTimeoutError(
                f"reducescatter on group {group_name!r} tag {tag!r}: rank "
                f"{my_rank} received no shard from root rank {root} within "
                f"{timeout}s",
                group=group_name, ranks=[root], tag=tag,
            )
        return serialization.loads(data)
    if member_addrs is None:
        member_addrs = fetch_member_addrs(gcs, group_name, world_size, ranks=member_ranks)
    shards = [red[pos] for pos in range(k)]
    if finalize is not None:
        shards = [finalize(s) for s in shards]
    for pos, rank in enumerate(member_ranks):
        if rank == root:
            continue
        data = serialization.dumps(shards[pos])
        direct_send(cw, tuple(member_addrs[rank]), scatter_key(group_name, tag, rank), data)
        COLL.scatter_bytes += len(data)
    return shards[0]  # root is position 0: the lowest roster rank
