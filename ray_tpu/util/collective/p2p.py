"""Point-to-point transfer plane for collective groups and channel payloads.

Analog of the reference's ``ray.util.collective`` ``send``/``recv``
(python/ray/util/collective/collective.py:531/594): a 2-party transfer
between two ranks of an initialized group, OUT OF BAND with respect to the
shm object store — this is the wire the device-object plane
(experimental/device_object/) rides for actor-to-actor tensor handoff.

Two rendezvous mechanisms share this seam:

- **GCS-KV mailbox** (``mailbox_send``/``mailbox_recv``): the group-rank
  path. The sender posts the serialized value under a single-use tagged key
  in the group's GCS KV (the same control plane the CPU ring collectives
  and the TPU world bootstrap already use); the receiver polls it down and
  deletes it. Needs no peer address — ranks are the only names.
- **Direct mailbox** (``direct_send``/``direct_recv`` + ``P2PInbox``): the
  address-direct path the descriptor channel plane (PR 12,
  experimental/channel/device_envelope.py) streams microbatch payloads
  over. The sender pushes chunked one-way ``p2p_data`` frames straight at
  the consumer core worker's RPC server (no GCS round trips, no polling);
  the receiver waits on its process-local inbox. Keys are caller-scoped
  (``chdev/<cid>/<seq>`` for channel slots), delivery is at-most-once —
  callers fall back to a pull (resolve.py) on a missed grace window.

Device arrays serialize through ``_private/serialization`` so sharding
layout survives either hop and the receiver's ``device_put`` lands shards
back on the matching devices.

On real TPU hardware the collectives INSIDE jitted programs ride ICI; both
host mailboxes are correctness stand-ins until jax exposes a cross-process
device-to-device transfer API in this image (the reference's NCCL p2p
equivalent). The seams are ``TpuCollectiveGroup.send/recv`` and
``direct_send/direct_recv`` — swap in the device path there without
touching any caller.
"""

from __future__ import annotations

import threading
import time

from ray_tpu._private.concurrency import any_thread, blocking

_POLL_S = 0.003
# Direct-mailbox chunk size: one-way frames on the existing worker pipe,
# bounded like the chunked object-push path.
_DIRECT_CHUNK_BYTES = 512 * 1024
# Unclaimed inbox entries (consumer died / tore down between the eager push
# and the read) are swept after this age so a long-lived worker's inbox
# cannot grow without bound on lost readers.
_INBOX_SWEEP_AGE_S = 180.0


def mailbox_key(group_name: str, src_rank: int, dst_rank: int, tag: str) -> str:
    """Public so senders can janitor abandoned transfers (a recv that timed
    out or died never deletes the key; without cleanup the serialized
    payload would sit in the GCS KV forever)."""
    return f"collective/{group_name}/p2p/{src_rank}->{dst_rank}/{tag}"


_key = mailbox_key


@blocking
def mailbox_send(gcs, group_name: str, src_rank: int, dst_rank: int, tag: str, value) -> int:
    """Serialize ``value`` and post it for ``dst_rank``; returns byte size.
    Single-use: the receiver deletes the key after pickup."""
    from ray_tpu._private import serialization

    data = serialization.dumps(value)
    gcs.call(
        "kv_put",
        {"key": _key(group_name, src_rank, dst_rank, tag), "value": data},
    )
    return len(data)


@blocking
def mailbox_recv(gcs, group_name: str, src_rank: int, dst_rank: int, tag: str, timeout: float = 120.0):
    """Block until the tagged value from ``src_rank`` arrives; deserializes
    (device arrays reassemble with their original sharding) and deletes the
    mailbox key."""
    from ray_tpu._private import serialization

    key = _key(group_name, src_rank, dst_rank, tag)
    deadline = time.monotonic() + timeout
    while True:
        resp = gcs.call("kv_get", {"key": key})
        if resp.get("found"):
            gcs.call("kv_del", {"key": key})
            return serialization.loads(resp["value"])
        if time.monotonic() > deadline:
            from ray_tpu.exceptions import CollectiveTimeoutError

            raise CollectiveTimeoutError(
                f"p2p recv on group {group_name!r} tag {tag!r} from rank "
                f"{src_rank} timed out after {timeout}s",
                group=group_name, ranks=[src_rank], tag=tag,
            )
        time.sleep(_POLL_S)


# ---------------------------------------------------------------------------
# Direct mailbox (address-directed, no GCS round trips)
# ---------------------------------------------------------------------------


class P2PInbox:
    """Per-process landing zone for ``p2p_data`` frames (one per core
    worker; the ``rpc_p2p_data`` handler deposits into it). Chunked frames
    reassemble here; a waiter blocks on a per-key event. All state behind
    one lock; methods never block — deposit runs on the IO loop."""

    def __init__(self):
        from ray_tpu._private.ids import BoundedIdSet

        self._lock = threading.Lock()
        self._parts: dict[str, dict] = {}    # key -> {idx: bytes}
        self._parts_ts: dict[str, float] = {}  # key -> first-chunk monotonic ts
        self._done: dict[str, tuple] = {}    # key -> (bytes, monotonic ts)
        self._waiters: dict[str, threading.Event] = {}
        self._deposits = 0
        # Recently-COMPLETED keys: delivery of p2p_data frames is
        # at-least-once under connection blips (and chaos dup injection),
        # and a duplicate chunk arriving AFTER its payload completed used
        # to re-open a partial reassembly that could never complete
        # (leaked until the age sweep) — or, for a single-chunk payload,
        # resurrect a consumed ``_done`` entry, breaking the at-most-once
        # take() contract. Tombstoned keys drop silently.
        self._completed = BoundedIdSet(cap=1024)

    @any_thread
    def deposit(self, key: str, idx: int, total: int, data: bytes) -> bool:
        """Returns True when the payload is COMPLETE (all chunks landed).
        Idempotent under duplicated/reordered chunks: a repeat of a
        still-assembling chunk overwrites in place, and any chunk of an
        already-completed key is dropped."""
        complete = False
        with self._lock:
            if key in self._completed or key in self._done:
                self._deposits += 1
                return False  # duplicate of a completed payload
            parts = self._parts.get(key)
            if parts is None:
                parts = self._parts[key] = {}
                self._parts_ts[key] = time.monotonic()
            parts[idx] = data
            if len(parts) == total:
                self._completed.add(key)
                self._parts.pop(key)
                self._parts_ts.pop(key, None)
                self._done[key] = (
                    data if total == 1 else b"".join(parts[i] for i in range(total)),
                    time.monotonic(),
                )
                waiter = self._waiters.get(key)
                if waiter is not None:
                    waiter.set()
                complete = True
            self._deposits += 1
            sweep = self._deposits & 255 == 0
        if sweep:
            self.sweep()
        return complete

    @any_thread
    def take(self, key: str) -> bytes | None:
        with self._lock:
            entry = self._done.pop(key, None)
            return None if entry is None else entry[0]

    @any_thread
    def _waiter(self, key: str) -> threading.Event:
        with self._lock:
            if key in self._done:
                ev = threading.Event()
                ev.set()
                return ev
            ev = self._waiters.get(key)
            if ev is None:
                ev = self._waiters[key] = threading.Event()
            return ev

    @any_thread
    def _drop_waiter(self, key: str) -> None:
        with self._lock:
            self._waiters.pop(key, None)

    @any_thread
    def completed(self, key: str) -> bool:
        """True once every chunk of ``key`` has landed — stays true after a
        take() (the tombstone remembers), which is exactly the delivery
        acknowledgement ``p2p_ack`` needs: 'the payload reached this
        process', not 'it is still unclaimed'."""
        with self._lock:
            return key in self._completed or key in self._done

    @blocking
    def wait_complete(self, key: str, timeout: float) -> bool:
        """Block (bounded) until ``key``'s payload has fully landed. Used by
        the ``p2p_ack`` RPC: the ack rides the same connection as the data
        frames, but handlers are dispatched as tasks, so a bounded wait
        covers the (rare) reorder instead of trusting scheduling order."""
        deadline = time.monotonic() + timeout
        ev = self._waiter(key)
        try:
            while True:
                if self.completed(key):
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                ev.wait(min(0.05, remaining))
                ev.clear()
        finally:
            self._drop_waiter(key)

    @any_thread
    def purge_prefix(self, prefix: str) -> int:
        """Drop every entry/partial under a key prefix (channel teardown:
        cids are dead, nobody will ever take these payloads)."""
        with self._lock:
            victims = [k for k in self._done if k.startswith(prefix)]
            for k in victims:
                del self._done[k]
            for k in [k for k in self._parts if k.startswith(prefix)]:
                del self._parts[k]
                self._parts_ts.pop(k, None)
                victims.append(k)
            return len(victims)

    @any_thread
    def sweep(self, max_age_s: float = _INBOX_SWEEP_AGE_S) -> int:
        """Age out unclaimed payloads AND stale partial reassemblies (a
        producer that died mid-push leaves chunks that will never
        complete — lost writers must not leak any more than lost
        readers)."""
        cutoff = time.monotonic() - max_age_s
        with self._lock:
            victims = [k for k, (_, ts) in self._done.items() if ts < cutoff]
            for k in victims:
                del self._done[k]
            stale = [k for k, ts in self._parts_ts.items() if ts < cutoff]
            for k in stale:
                self._parts.pop(k, None)
                del self._parts_ts[k]
            return len(victims) + len(stale)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._done),
                "partials": len(self._parts),
                "bytes": sum(len(d) for d, _ in self._done.values()),
            }


@any_thread
def direct_send(cw, addr: tuple, key: str, data: bytes) -> None:
    """Push serialized payload bytes at ``addr``'s inbox under ``key`` as
    chunked ONE-WAY frames on the existing worker pipe (fire-and-forget,
    like the channel doorbell): zero round trips on the hot path. Loss is
    recoverable — the consumer's grace window expires and it falls back to
    the pull path (resolve.py), where the holder still pins the payload."""
    client = cw._owner_client(tuple(addr))
    total = max(1, (len(data) + _DIRECT_CHUNK_BYTES - 1) // _DIRECT_CHUNK_BYTES)

    async def _push_all():
        try:
            for i in range(total):
                await client.apush(
                    "p2p_data",
                    {
                        "key": key,
                        "idx": i,
                        "total": total,
                        "data": data[
                            i * _DIRECT_CHUNK_BYTES : (i + 1) * _DIRECT_CHUNK_BYTES
                        ],
                    },
                )
        except Exception:
            pass  # consumer unreachable: its grace window handles it

    cw._io.spawn(_push_all())


# ---------------------------------------------------------------------------
# Group broadcast (ONE group op fanning a payload to every member)
# ---------------------------------------------------------------------------

# Per-member budget for the delivery acknowledgement round trip. The ack is
# what turns the fire-and-forget chunk frames into a delivery receipt: it
# rides the same FIFO connection as the data, so by the time the member
# answers, its inbox either has the payload or never will.
_BCAST_ACK_S = 10.0


class _CollStats:
    """Plain-int hot-path counters for the group-collective plane, folded
    into ``ray_tpu_collective_*`` instruments by self_metrics at flush time
    (same pattern as DEVOBJ_STATS — no instrument lock on the send path)."""

    __slots__ = (
        "bcast_sends",        # group broadcasts fanned out by this process
        "bcast_send_bytes",   # serialized payload bytes × delivered ranks
        "bcast_recvs",        # descriptor resolves served from a broadcast
        "bcast_fallbacks",    # per-rank deliveries that fell back to the GCS mailbox
        "bcast_failed_ranks", # ranks a broadcast could not deliver to
        "timeouts",           # typed collective timeouts raised here
    )

    def __init__(self):
        for name in self.__slots__:
            setattr(self, name, 0)


COLL = _CollStats()


def bcast_key(group_name: str, tag: str) -> str:
    """Inbox key of a group-broadcast payload. Deterministic from (group,
    tag) and deliberately RANK-FREE: inboxes are per-process, so every
    member gets the same key — which is what lets the fan-out encode each
    chunk frame once and write identical bytes to every connection.
    Device-object broadcasts use the object id as the tag, so one broadcast
    per object id (the inbox tombstones a repeated key as a duplicate)."""
    return f"collbcast/{group_name}/{tag}"


def member_addr_key(group_name: str, rank: int) -> str:
    return f"collective/{group_name}/addr/{rank}"


def register_member_addr(gcs, group_name: str, rank: int, addr) -> None:
    """Publish this member's core-worker RPC address so a group broadcast
    can push payload frames straight at its inbox (no GCS mailbox on the
    fan-out path). Best-effort: a member without a row just gets the
    mailbox fallback."""
    import json

    try:
        gcs.call(
            "kv_put",
            {"key": member_addr_key(group_name, rank), "value": json.dumps(list(addr)).encode()},
        )
    except Exception:
        pass


def unregister_member_addr(gcs, group_name: str, rank: int) -> None:
    try:
        gcs.call("kv_del", {"key": member_addr_key(group_name, rank)})
    except Exception:
        pass


@blocking
def fetch_member_addrs(gcs, group_name: str, world_size: int) -> dict:
    """{rank: (host, port)} for every member that registered an address.
    Callers cache this per group epoch — membership is static."""
    import json

    addrs: dict = {}
    for rank in range(world_size):
        try:
            resp = gcs.call("kv_get", {"key": member_addr_key(group_name, rank)})
            if resp.get("found"):
                addrs[rank] = tuple(json.loads(bytes(resp["value"]).decode()))
        except Exception:
            continue
    return addrs


@blocking
def group_bcast_send(
    cw,
    gcs,
    group_name: str,
    src_rank: int,
    world_size: int,
    tag: str,
    value,
    member_addrs: dict | None = None,
    timeout: float = 30.0,
    mailbox_fallback: bool = True,
) -> dict:
    """Fan ``value`` to every OTHER rank of the group as ONE group
    operation: one serialize, each chunk frame ENCODED ONCE
    (``RpcClient.pack_push_frame`` — the rank-free inbox key is what makes
    the bytes identical) and written down every member connection
    concurrently, each member confirmed by a ``p2p_ack`` round trip (wall
    clock ≈ serialize + encode + max member RTT; CPU ≈ one encode instead
    of K). Ranks without a registered address fall back to the GCS-KV
    mailbox under the same logical tag. Never raises for a dead member:
    the result names it so the caller owns the policy —
    ``{"ok_ranks": [...], "fallback_ranks": [...], "failed": {rank: reason},
    "bytes": payload_bytes}``.

    This is the cpu-backend group op behind device_object.broadcast(); on
    TPU hardware the same seam maps to an ICI broadcast (tpu_group.py)."""
    import asyncio

    from ray_tpu._private import serialization
    from ray_tpu._private.rpc import RpcClient

    data = serialization.dumps(value)
    if member_addrs is None:
        member_addrs = fetch_member_addrs(gcs, group_name, world_size)
    total = max(1, (len(data) + _DIRECT_CHUNK_BYTES - 1) // _DIRECT_CHUNK_BYTES)
    targets = [r for r in range(world_size) if r != src_rank]
    result = {"ok_ranks": [], "fallback_ranks": [], "failed": {}, "bytes": len(data)}
    key = bcast_key(group_name, tag)
    frames = [
        RpcClient.pack_push_frame(
            "p2p_data",
            {
                "key": key,
                "idx": i,
                "total": total,
                "data": data[i * _DIRECT_CHUNK_BYTES : (i + 1) * _DIRECT_CHUNK_BYTES],
            },
        )
        for i in range(total)
    ]

    # Ack wait scales with the caller's budget (clamped by the server at
    # 30s): a slow-but-healthy member still reassembling a large payload
    # must not be branded a failed rank by a fixed small bound.
    ack_wait = max(_BCAST_ACK_S, min(30.0, timeout))

    async def _deliver(rank: int, addr: tuple):
        client = cw._owner_client(tuple(addr))
        for frame in frames:
            await client.apush_packed("p2p_data", frame)
        resp = await client.acall(
            "p2p_ack", {"key": key, "timeout": ack_wait},
            timeout=ack_wait + 5.0, retries=0,
        )
        if not resp.get("ok"):
            raise RuntimeError("p2p_ack reported the payload never landed")

    async def _fan_out():
        tasks = {
            rank: asyncio.ensure_future(
                asyncio.wait_for(_deliver(rank, member_addrs[rank]), timeout)
            )
            for rank in targets
            if rank in member_addrs
        }
        if tasks:
            await asyncio.wait(tasks.values())
        return {rank: t.exception() for rank, t in tasks.items()}

    # Outer bound is a backstop over the per-member wait_for; each member's
    # delivery is already clamped to ``timeout`` individually.
    outcomes = cw._io.run(_fan_out(), timeout=timeout + 15.0) if targets else {}
    for rank in targets:
        if rank not in member_addrs:
            # Never registered an address (old-style member): the GCS
            # mailbox is its normal path, not a failure — but ONLY for
            # callers whose receivers actually poll it
            # (bcast_recv_payload). The device-object descriptor path
            # resolves from the direct inbox alone, so there a mailbox
            # drop would be dead weight in the KV and a false "delivered"
            # — it reports the rank failed instead.
            if not mailbox_fallback:
                result["failed"][rank] = "no registered member address"
                COLL.bcast_failed_ranks += 1
                continue
            try:
                mailbox_send(gcs, group_name, src_rank, rank, f"bcast/{tag}", value)
                _schedule_bcast_janitor(cw, gcs, mailbox_key(group_name, src_rank, rank, f"bcast/{tag}"))
                result["fallback_ranks"].append(rank)
                COLL.bcast_fallbacks += 1
            except Exception as e:
                result["failed"][rank] = repr(e)
                COLL.bcast_failed_ranks += 1
            continue
        exc = outcomes.get(rank)
        if exc is None:
            result["ok_ranks"].append(rank)
        else:
            # A REGISTERED member we could not deliver to is dead, severed,
            # or wedged — a GCS mailbox drop would "succeed" against a
            # corpse (the KV is alive either way), so the honest outcome is
            # a named failure the caller can act on.
            result["failed"][rank] = repr(exc)
            COLL.bcast_failed_ranks += 1
    COLL.bcast_sends += 1
    COLL.bcast_send_bytes += len(data) * (
        len(result["ok_ranks"]) + len(result["fallback_ranks"])
    )
    return result


def _schedule_bcast_janitor(cw, gcs, key: str, delay_s: float = 180.0) -> None:
    """A mailbox-fallback payload a dead/slow member never claims must not
    sit in the GCS KV forever (same janitor shape as
    DeviceObjectManager._schedule_mailbox_janitor)."""
    async def _sweep():
        import asyncio

        await asyncio.sleep(delay_s)
        try:
            await gcs.acall("kv_del", {"key": key})
        except Exception:
            pass

    try:
        cw._io.spawn(_sweep())
    except Exception:
        pass


@blocking
def group_bcast_recv(cw, gcs, group_name: str, src_rank: int, my_rank: int, tag: str, timeout: float = 120.0):
    """Member-side receive of a group broadcast: watch BOTH landing zones —
    the direct mailbox (steady state: the payload is already here, or
    arrives whenever the sender's chunk pushes finish) and the GCS mailbox
    (the sender's fallback for members it could not dial) — until the
    deadline; typed timeout naming group/rank/tag otherwise. Interleaved
    on purpose: a receiver that blocks before the sender starts (normal
    collective ordering) must catch a direct delivery landing at ANY point
    in the window, not just the first second."""
    from ray_tpu._private import serialization
    from ray_tpu.exceptions import CollectiveTimeoutError

    deadline = time.monotonic() + timeout
    key = bcast_key(group_name, tag)
    gcs_key = mailbox_key(group_name, src_rank, my_rank, f"bcast/{tag}")
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            COLL.timeouts += 1
            raise CollectiveTimeoutError(
                f"group broadcast recv on {group_name!r} tag {tag!r}: nothing "
                f"from rank {src_rank} within {timeout}s (direct mailbox and "
                "GCS fallback both empty)",
                group=group_name, ranks=[src_rank], tag=tag,
            )
        data = direct_recv(cw, key, timeout=min(0.25, remaining))
        if data is not None:
            COLL.bcast_recvs += 1
            return serialization.loads(data)
        try:
            resp = gcs.call("kv_get", {"key": gcs_key})
            if resp.get("found"):
                gcs.call("kv_del", {"key": gcs_key})
                COLL.bcast_recvs += 1
                return serialization.loads(resp["value"])
        except Exception:
            pass  # GCS hiccup: the direct-path wait keeps the clock


@blocking
def direct_recv(cw, key: str, timeout: float, abort_check=None) -> bytes | None:
    """Wait for a direct-mailbox payload under ``key``. Returns the bytes,
    or None when ``timeout`` expires (caller falls back to the pull path)
    or ``abort_check()`` goes true (teardown / poison: caller surfaces its
    own typed error). Steady state returns without sleeping — for channel
    payloads the deposit itself is what woke the reader, so the bytes are
    already here by the time the consumer resolves the slot."""
    inbox = cw.p2p_inbox
    deadline = time.monotonic() + timeout
    ev = inbox._waiter(key)
    try:
        while True:
            data = inbox.take(key)
            if data is not None:
                return data
            if abort_check is not None and abort_check():
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            ev.wait(min(0.05, remaining))
            ev.clear()
    finally:
        inbox._drop_waiter(key)
