"""Collective types (analog of python/ray/util/collective/types.py)."""

from __future__ import annotations

import enum


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"


class Backend:
    TPU = "tpu"  # XLA collectives over ICI (replaces the reference's NCCL)
    CPU = "cpu"  # object-store ring (replaces the reference's pygloo/GLOO)

    @staticmethod
    def validate(backend: str) -> str:
        if backend in ("tpu", "xla", "ici"):
            return Backend.TPU
        if backend in ("cpu", "gloo", "object_store"):
            return Backend.CPU
        raise ValueError(f"unknown collective backend {backend!r}; use 'tpu' or 'cpu'")
