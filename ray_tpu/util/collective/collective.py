"""Collective API.

Analog of the reference's ray.util.collective.collective
(python/ray/util/collective/collective.py: init_collective_group:120,
create_collective_group:151, allreduce:258, reduce:311, broadcast:373,
allgather:423, reducescatter:472, send:531, recv:594) with the NCCL backend
replaced by XLA collectives over ICI (tpu_group.py) and the GLOO backend by an
object-store ring (cpu_group.py).

Usage inside member actors (one per TPU host):

    from ray_tpu.util import collective as col

    class TrainWorker:
        def setup(self, world_size, rank):
            col.init_collective_group(world_size, rank, backend="tpu")
        def step(self, grads):
            return col.allreduce(grads)

Driver side: ``create_collective_group(actors, ...)`` declares the group and
invokes ``init`` on every member (gang init, all-or-nothing — an XLA world is
static, SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

import logging
import threading

from ray_tpu.util.collective.types import Backend, ReduceOp

logger = logging.getLogger(__name__)


class GroupManager:
    """Per-process registry (reference: GroupManager collective.py:40)."""

    def __init__(self):
        self._groups: dict = {}
        self._lock = threading.Lock()

    def create(self, group_name: str, world_size: int, rank: int, backend: str, coordinator=None):
        backend = Backend.validate(backend)
        with self._lock:
            if group_name in self._groups:
                raise ValueError(f"collective group {group_name!r} already exists")
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker_if_initialized()
        gcs = cw.gcs if cw is not None else None
        if backend == Backend.TPU:
            from ray_tpu.util.collective.tpu_group import TpuCollectiveGroup

            # This node's GCS-registered address: the coordinator must be
            # dialable from member actors on OTHER hosts, so loopback (the
            # round-1 bug) is structurally wrong on a real cluster.
            node_ip = None
            if cw is not None and rank == 0:
                try:
                    nodes = gcs.call("get_nodes").get("nodes", {})
                    addr = nodes.get(cw.node_id, {}).get("address")
                    if addr:
                        node_ip = addr[0]
                except Exception:
                    logger.warning("could not resolve node IP from GCS; using interface IP")
            group = TpuCollectiveGroup(
                group_name, world_size, rank, coordinator=coordinator, gcs=gcs, node_ip=node_ip
            )
        else:
            from ray_tpu.util.collective.cpu_group import CpuCollectiveGroup

            group = CpuCollectiveGroup(group_name, world_size, rank, gcs=gcs)
        with self._lock:
            self._groups[group_name] = group
        if cw is not None and gcs is not None:
            # Publish this member's core-worker RPC address so a group
            # broadcast (p2p.group_bcast_send) can push payload frames
            # straight at its direct mailbox instead of going through the
            # GCS-KV mailbox per rank. Best-effort: members without a row
            # get the mailbox fallback.
            from ray_tpu.util.collective.p2p import register_member_addr, roster_join

            register_member_addr(gcs, group_name, rank, cw.address)
            # Then JOIN the epochal roster (address row first: a rank the
            # roster lists always has a dialable row). A rank already
            # listed is a RE-REGISTER — a respawned member at a new
            # address — and still bumps the epoch, which is what drops
            # every peer's address cache.
            try:
                roster_join(gcs, group_name, rank, world_size)
            except Exception:
                logger.warning(
                    "roster join failed for group %r rank %s (verbs fall "
                    "back to the static world)", group_name, rank,
                )
        return group

    def get(self, group_name: str):
        group = self._groups.get(group_name)
        if group is None:
            raise ValueError(
                f"no collective group {group_name!r} in this process; "
                "call init_collective_group first"
            )
        return group

    def destroy(self, group_name: str):
        with self._lock:
            group = self._groups.pop(group_name, None)
        if group is not None:
            group.destroy()


_manager = GroupManager()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "tpu",
    group_name: str = "default",
    coordinator: str | None = None,
):
    """Member-side group init (reference: collective.py:120)."""
    return _manager.create(group_name, world_size, rank, backend, coordinator)


def create_collective_group(
    actors: list,
    world_size: int | None = None,
    ranks: list[int] | None = None,
    backend: str = "tpu",
    group_name: str = "default",
):
    """Driver-side gang init (reference: collective.py:151): calls
    ``init_collective_group`` in every member actor concurrently and waits for
    all (the XLA world bootstrap requires all processes to join)."""
    import ray_tpu

    world_size = world_size or len(actors)
    ranks = ranks or list(range(len(actors)))
    # Convention: member actors expose
    # ``init_collective(world_size, rank, backend, group_name)`` which calls
    # init_collective_group (see module docstring).
    refs = [
        actor.init_collective.remote(world_size, r, backend, group_name)
        for actor, r in zip(actors, ranks)
    ]
    return ray_tpu.get(refs, timeout=300)


def get_group(group_name: str = "default"):
    return _manager.get(group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _manager.get(group_name)
        return True
    except ValueError:
        return False


def destroy_collective_group(group_name: str = "default"):
    _manager.destroy(group_name)


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _manager.get(group_name).allreduce(tensor, op)


def allgather(tensor, group_name: str = "default"):
    return _manager.get(group_name).allgather(tensor)


def reducescatter(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _manager.get(group_name).reducescatter(tensor, op)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return _manager.get(group_name).broadcast(tensor, src_rank)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    return _manager.get(group_name).reduce(tensor, dst_rank, op)


def barrier(group_name: str = "default"):
    _manager.get(group_name).barrier()


def send_recv(tensor, perm, group_name: str = "default"):
    """Pairwise exchange (ppermute). The p2p primitive (reference send/recv)."""
    return _manager.get(group_name).send_recv(tensor, perm)


def send(value, dst_rank: int, group_name: str = "default", tag: str = "0"):
    """2-party point-to-point send (reference: collective.py:531): only the
    two endpoints participate. ``tag`` pairs one send with one recv; device
    arrays keep their sharding layout across the hop."""
    return _manager.get(group_name).send(value, dst_rank, tag)


def recv(src_rank: int, group_name: str = "default", tag: str = "0", timeout: float = 120.0):
    """2-party point-to-point recv (reference: collective.py:594)."""
    return _manager.get(group_name).recv(src_rank, tag, timeout)


def roster(group_name: str = "default") -> dict | None:
    """Current epochal-membership snapshot of ``group_name`` from the GCS:
    ``{"epoch", "ranks", "world_size"}``, or None for a group that never
    published one. Works from ANY process with a GCS connection (the
    driver introspecting a group it is not a member of included)."""
    from ray_tpu._private import worker_context
    from ray_tpu.util.collective.p2p import fetch_roster

    cw = worker_context.get_core_worker()
    return fetch_roster(cw.gcs, group_name)


def rejoin_group(group_name: str = "default") -> int | None:
    """Re-assert THIS process's membership in a group it already holds
    locally: re-publish the address row, then re-join the roster. The
    self-healing lever for a LIVE member that a verb EVICTED on a
    transient stall (eviction also deleted its address row) — the epoch
    bump puts it back on every sender's fast path at the next snapshot.
    Returns the new roster epoch, or None when this process never
    initialized the group (a respawned replacement must init, not
    rejoin)."""
    from ray_tpu._private import worker_context
    from ray_tpu.util.collective.p2p import register_member_addr, roster_join

    try:
        group = _manager.get(group_name)
    except ValueError:
        return None
    cw = worker_context.get_core_worker_if_initialized()
    if cw is None:
        return None
    register_member_addr(cw.gcs, group_name, group.rank, cw.address)
    return roster_join(cw.gcs, group_name, group.rank, group.world_size)


def evict_member(group_name: str, rank: int, reason: str = "leave") -> int | None:
    """Driver-side LEAVE on behalf of a member that cannot leave for
    itself (SIGKILLed actor, shrink of a fleet whose workers are killed
    outright): drops ``rank`` from the roster, advances the epoch, and
    deletes its orphaned address row. Returns the new epoch, or None if
    the rank was not listed. The next verb on the group topologizes over
    the survivors."""
    from ray_tpu._private import worker_context
    from ray_tpu.util.collective.p2p import roster_leave

    cw = worker_context.get_core_worker()
    return roster_leave(cw.gcs, group_name, rank, reason=reason)


def local_group_hints() -> list:
    """[(group_name, rank, world_size)] for every collective group THIS
    process has initialized. The device-object plane stamps these into its
    descriptors so a consumer can pick a transfer group it shares with the
    holder without a directory service."""
    with _manager._lock:
        groups = list(_manager._groups.items())
    return [(name, g.rank, g.world_size) for name, g in groups]
