"""CPU collective group over the GCS KV / object store.

Analog of the reference's GLOOGroup
(python/ray/util/collective/collective_group/gloo_collective_group.py): a
pure-Python fallback for host-memory collectives, so collective code runs on
nodes with no accelerator (and in unit tests) without any extra dependency.
Data moves through the GCS KV (small control-plane scale); the TPU group is
the performance path.
"""

from __future__ import annotations

import time

import numpy as np

from ray_tpu.util.collective.types import ReduceOp

_REDUCE = {
    ReduceOp.SUM: lambda stack: stack.sum(axis=0),
    ReduceOp.PRODUCT: lambda stack: stack.prod(axis=0),
    ReduceOp.MIN: lambda stack: stack.min(axis=0),
    ReduceOp.MAX: lambda stack: stack.max(axis=0),
    ReduceOp.MEAN: lambda stack: stack.mean(axis=0),
}


class CpuCollectiveGroup:
    def __init__(self, group_name: str, world_size: int, rank: int, gcs=None):
        from ray_tpu._private import worker_context

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self.gcs = gcs or worker_context.get_core_worker().gcs
        self._epoch = 0

    def _key(self, step: str, rank: int) -> str:
        return f"collective/{self.group_name}/{self._epoch}/{step}/{rank}"

    def _post(self, step: str, arr: np.ndarray):
        from ray_tpu._private import serialization

        self.gcs.call(
            "kv_put", {"key": self._key(step, self.rank), "value": serialization.dumps(arr)}
        )

    def _collect(self, step: str, timeout: float = 120.0) -> list[np.ndarray]:
        from ray_tpu._private import serialization

        out: list = [None] * self.world_size
        deadline = time.monotonic() + timeout
        remaining = set(range(self.world_size))
        while remaining and time.monotonic() < deadline:
            for r in list(remaining):
                resp = self.gcs.call("kv_get", {"key": self._key(step, r)})
                if resp.get("found"):
                    out[r] = np.asarray(serialization.loads(resp["value"]))
                    remaining.discard(r)
            if remaining:
                time.sleep(0.01)
        if remaining:
            raise TimeoutError(f"collective {step} timed out waiting for ranks {remaining}")
        return out

    def _sync(self, step: str, arr) -> list[np.ndarray]:
        arr = np.asarray(arr)
        self._post(step, arr)
        stack = self._collect(step)
        self._epoch += 1
        return stack

    def allreduce(self, x, op: ReduceOp = ReduceOp.SUM):
        stack = self._sync("allreduce", x)
        return _REDUCE[op](np.stack(stack))

    def allgather(self, x):
        return np.stack(self._sync("allgather", x))

    def reducescatter(self, x, op: ReduceOp = ReduceOp.SUM):
        x = np.asarray(x)
        assert x.shape[0] == self.world_size
        stack = self._sync("reducescatter", x)
        return _REDUCE[op](np.stack(stack))[self.rank]

    def broadcast(self, x, src_rank: int = 0):
        stack = self._sync("broadcast", x)
        return stack[src_rank]

    def reduce(self, x, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        out = self.allreduce(x, op)
        return out if self.rank == dst_rank else None

    def barrier(self):
        self._sync("barrier", np.zeros((1,)))

    def send_recv(self, x, perm):
        """Pairwise exchange: returns the tensor sent to this rank (or x)."""
        stack = self._sync("sendrecv", x)
        for src, dst in perm:
            if dst == self.rank:
                return stack[src]
        return np.asarray(x)

    def send(self, value, dst_rank: int, tag: str) -> int:
        """2-party p2p send (reference: collective.py:531). Unlike the ring
        collectives above, only the two endpoints participate; device arrays
        keep their sharding across the hop (p2p.py mailbox)."""
        from ray_tpu.util.collective.p2p import mailbox_send

        return mailbox_send(self.gcs, self.group_name, self.rank, dst_rank, tag, value)

    def recv(self, src_rank: int, tag: str, timeout: float = 120.0):
        """2-party p2p recv (reference: collective.py:594)."""
        from ray_tpu.util.collective.p2p import mailbox_recv

        return mailbox_recv(self.gcs, self.group_name, src_rank, self.rank, tag, timeout)

    def destroy(self):
        pass
