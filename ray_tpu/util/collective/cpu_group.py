"""CPU collective group over the GCS KV / object store.

Analog of the reference's GLOOGroup
(python/ray/util/collective/collective_group/gloo_collective_group.py): a
pure-Python fallback for host-memory collectives, so collective code runs on
nodes with no accelerator (and in unit tests) without any extra dependency.
Data moves through the GCS KV (small control-plane scale); the TPU group is
the performance path.

Payload semantics: values serialize through ``_private/serialization``, so a
``jax.Array`` round-trips bit-exact WITH its sharding layout — ``broadcast``
hands every rank the src rank's value as-is (a sharded weight tensor lands
re-sharded on the receiver's devices), while the reducing ops and
``allgather`` densify to numpy (a stack across ranks has no single sharding
to preserve).
"""

from __future__ import annotations

import time

import numpy as np

from ray_tpu.util.collective.types import ReduceOp

_REDUCE = {
    ReduceOp.SUM: lambda stack: stack.sum(axis=0),
    ReduceOp.PRODUCT: lambda stack: stack.prod(axis=0),
    ReduceOp.MIN: lambda stack: stack.min(axis=0),
    ReduceOp.MAX: lambda stack: stack.max(axis=0),
    ReduceOp.MEAN: lambda stack: stack.mean(axis=0),
}


def _uniform_stack(group_name: str, step: str, values: list) -> np.ndarray:
    """np.stack with a TYPED shape check: ranks contributing mismatched
    shapes/dtypes is a programming error that must name the offenders, not
    surface as a bare numpy ValueError deep in a reduce."""
    from ray_tpu.exceptions import CollectiveError

    arrs = [np.asarray(v) for v in values]
    shapes = {a.shape for a in arrs}
    if len(shapes) > 1:
        per_rank = {r: a.shape for r, a in enumerate(arrs)}
        raise CollectiveError(
            f"collective {step} on group {group_name!r} requires uniform "
            f"shapes across ranks, got {per_rank}"
        )
    return np.stack(arrs)


class CpuCollectiveGroup:
    def __init__(self, group_name: str, world_size: int, rank: int, gcs=None):
        from ray_tpu._private import worker_context

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self.gcs = gcs or worker_context.get_core_worker().gcs
        self._epoch = 0
        # {rank: core-worker addr} lazily fetched from the GCS registry —
        # membership is static per group epoch, so one fetch serves every
        # group broadcast this member fans out.
        self._member_addrs: dict | None = None

    def _key(self, step: str, rank: int) -> str:
        return f"collective/{self.group_name}/{self._epoch}/{step}/{rank}"

    def _post(self, step: str, value):
        from ray_tpu._private import serialization

        self.gcs.call(
            "kv_put", {"key": self._key(step, self.rank), "value": serialization.dumps(value)}
        )

    def _collect(self, step: str, timeout: float = 120.0) -> list:
        from ray_tpu._private import serialization
        from ray_tpu.exceptions import CollectiveTimeoutError

        out: list = [None] * self.world_size
        deadline = time.monotonic() + timeout
        remaining = set(range(self.world_size))
        while remaining and time.monotonic() < deadline:
            for r in list(remaining):
                resp = self.gcs.call("kv_get", {"key": self._key(step, r)})
                if resp.get("found"):
                    out[r] = serialization.loads(resp["value"])
                    remaining.discard(r)
            if remaining:
                time.sleep(0.01)
        if remaining:
            from ray_tpu.util.collective.p2p import COLL

            COLL.timeouts += 1
            raise CollectiveTimeoutError(
                f"collective {step} on group {self.group_name!r} (rank "
                f"{self.rank}) timed out after {timeout}s waiting for ranks "
                f"{sorted(remaining)}",
                group=self.group_name, ranks=remaining,
            )
        return out

    def _sync(self, step: str, value) -> list:
        self._post(step, value)
        stack = self._collect(step)
        self._epoch += 1
        return stack

    def allreduce(self, x, op: ReduceOp = ReduceOp.SUM):
        stack = self._sync("allreduce", np.asarray(x))
        return _REDUCE[op](_uniform_stack(self.group_name, "allreduce", stack))

    def allgather(self, x):
        return _uniform_stack(self.group_name, "allgather", self._sync("allgather", x))

    def reducescatter(self, x, op: ReduceOp = ReduceOp.SUM):
        x = np.asarray(x)
        assert x.shape[0] == self.world_size
        stack = self._sync("reducescatter", x)
        return _REDUCE[op](_uniform_stack(self.group_name, "reducescatter", stack))[self.rank]

    def broadcast(self, x, src_rank: int = 0):
        """Every rank gets the src rank's value AS POSTED: a jax.Array
        round-trips bit-exact with its sharding (the payload-parity
        contract the device-object broadcast path relies on)."""
        stack = self._sync("broadcast", x)
        return stack[src_rank]

    def reduce(self, x, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        out = self.allreduce(x, op)
        return out if self.rank == dst_rank else None

    def barrier(self):
        self._sync("barrier", np.zeros((1,)))

    def send_recv(self, x, perm):
        """Pairwise exchange: returns the tensor sent to this rank (or x)."""
        stack = self._sync("sendrecv", x)
        for src, dst in perm:
            if dst == self.rank:
                return np.asarray(stack[src])
        return np.asarray(x)

    def send(self, value, dst_rank: int, tag: str) -> int:
        """2-party p2p send (reference: collective.py:531). Unlike the ring
        collectives above, only the two endpoints participate; device arrays
        keep their sharding across the hop (p2p.py mailbox)."""
        from ray_tpu.util.collective.p2p import mailbox_send

        return mailbox_send(self.gcs, self.group_name, self.rank, dst_rank, tag, value)

    def recv(self, src_rank: int, tag: str, timeout: float = 120.0):
        """2-party p2p recv (reference: collective.py:594)."""
        from ray_tpu.util.collective.p2p import mailbox_recv

        return mailbox_recv(self.gcs, self.group_name, src_rank, self.rank, tag, timeout)

    # ---- group broadcast (ONE op fanning a payload to every member) ----

    def _addrs(self) -> dict:
        from ray_tpu.util.collective.p2p import fetch_member_addrs

        if self._member_addrs is None:
            self._member_addrs = fetch_member_addrs(self.gcs, self.group_name, self.world_size)
        return self._member_addrs

    def bcast_send_payload(self, value, tag: str, timeout: float = 30.0,
                           mailbox_fallback: bool = True) -> dict:
        """Holder-side group broadcast: one serialize, concurrent acked
        chunk pushes at every member's direct mailbox (p2p.group_bcast_send)
        — the fan-out device_object.broadcast() rides. Returns the per-rank
        delivery map; never raises for a dead member (the caller owns the
        policy). ``mailbox_fallback=False`` when receivers only watch the
        direct inbox (the descriptor-resolution path)."""
        from ray_tpu._private import worker_context
        from ray_tpu.util.collective.p2p import group_bcast_send

        cw = worker_context.get_core_worker()
        return group_bcast_send(
            cw, self.gcs, self.group_name, self.rank, self.world_size, tag,
            value, member_addrs=self._addrs(), timeout=timeout,
            mailbox_fallback=mailbox_fallback,
        )

    def bcast_recv_payload(self, src_rank: int, tag: str, timeout: float = 120.0):
        """Member-side receive of a group broadcast (direct mailbox, GCS
        fallback, typed timeout naming group/rank/tag)."""
        from ray_tpu._private import worker_context
        from ray_tpu.util.collective.p2p import group_bcast_recv

        cw = worker_context.get_core_worker()
        return group_bcast_recv(
            cw, self.gcs, self.group_name, src_rank, self.rank, tag, timeout
        )

    def destroy(self):
        from ray_tpu.util.collective.p2p import unregister_member_addr

        unregister_member_addr(self.gcs, self.group_name, self.rank)
