"""CPU collective group over the GCS KV / object store.

Analog of the reference's GLOOGroup
(python/ray/util/collective/collective_group/gloo_collective_group.py): a
pure-Python fallback for host-memory collectives, so collective code runs on
nodes with no accelerator (and in unit tests) without any extra dependency.
Data moves through the GCS KV (small control-plane scale); the TPU group is
the performance path.

Payload semantics: values serialize through ``_private/serialization``, so a
``jax.Array`` round-trips bit-exact WITH its sharding layout — ``broadcast``
hands every rank the src rank's value as-is (a sharded weight tensor lands
re-sharded on the receiver's devices). The reducing ops
(``allreduce``/``reduce``/``reducescatter``) now ALSO stay in jnp when
every rank's contribution is a ``jax.Array`` (the stack-reduce runs under
jax and the output is a device array — placement parity with
``broadcast``). The densify-to-numpy cases that REMAIN: any round where at
least one rank posts a non-jax value (the whole stack densifies),
``allgather`` (a cross-rank stack has no single sharding to preserve), and
``send_recv``.
"""

from __future__ import annotations

import time

import numpy as np

from ray_tpu.util.collective.types import ReduceOp

_REDUCE = {
    ReduceOp.SUM: lambda stack: stack.sum(axis=0),
    ReduceOp.PRODUCT: lambda stack: stack.prod(axis=0),
    ReduceOp.MIN: lambda stack: stack.min(axis=0),
    ReduceOp.MAX: lambda stack: stack.max(axis=0),
    ReduceOp.MEAN: lambda stack: stack.mean(axis=0),
}


def _is_jax_array(v) -> bool:
    try:
        import jax
    except Exception:
        return False
    return isinstance(v, jax.Array)


def _uniform_stack(group_name: str, step: str, values: list) -> np.ndarray:
    """np.stack with a TYPED shape check: ranks contributing mismatched
    shapes/dtypes is a programming error that must name the offenders, not
    surface as a bare numpy ValueError deep in a reduce."""
    from ray_tpu.exceptions import CollectiveError

    arrs = [np.asarray(v) for v in values]
    shapes = {a.shape for a in arrs}
    if len(shapes) > 1:
        per_rank = {r: a.shape for r, a in enumerate(arrs)}
        raise CollectiveError(
            f"collective {step} on group {group_name!r} requires uniform "
            f"shapes across ranks, got {per_rank}"
        )
    return np.stack(arrs)


def _reduce_stack(group_name: str, step: str, values: list, op: ReduceOp):
    """Stack-and-reduce that keeps the math in jnp when EVERY contribution
    is a jax.Array — the reduce output is then a device array, matching
    broadcast's payload-parity contract. Mixed or plain-numpy rounds take
    the densifying path (with the typed uniform-shape check)."""
    if values and all(_is_jax_array(v) for v in values):
        from ray_tpu.exceptions import CollectiveError

        shapes = {tuple(v.shape) for v in values}
        if len(shapes) > 1:
            per_rank = {r: tuple(v.shape) for r, v in enumerate(values)}
            raise CollectiveError(
                f"collective {step} on group {group_name!r} requires uniform "
                f"shapes across ranks, got {per_rank}"
            )
        import jax.numpy as jnp

        return _REDUCE[op](jnp.stack(values))
    return _REDUCE[op](_uniform_stack(group_name, step, values))


class CpuCollectiveGroup:
    def __init__(self, group_name: str, world_size: int, rank: int, gcs=None):
        from ray_tpu._private import worker_context

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self.gcs = gcs or worker_context.get_core_worker().gcs
        self._epoch = 0
        # (roster_epoch, {rank: core-worker addr}) — the address cache is
        # KEYED ON THE ROSTER EPOCH and dropped on any bump: membership is
        # elastic, and a member that re-registered at the SAME coordinator
        # epoch has a new address under the same rank row (the bug the
        # static "fetch once per group" cache had).
        self._addr_cache: tuple[int, dict] | None = None
        # Set by destroy(): a verb racing a concurrent
        # destroy_collective_group must surface a typed CollectiveError,
        # never park until its timeout.
        self._destroyed = False

    def _key(self, step: str, rank: int) -> str:
        return f"collective/{self.group_name}/{self._epoch}/{step}/{rank}"

    def _post(self, step: str, value):
        from ray_tpu._private import serialization

        self.gcs.call(
            "kv_put", {"key": self._key(step, self.rank), "value": serialization.dumps(value)}
        )

    def _check_destroyed(self, verb: str) -> None:
        if self._destroyed:
            from ray_tpu.exceptions import CollectiveError

            raise CollectiveError(
                f"collective group {self.group_name!r} was destroyed "
                f"(rank {self.rank}, during {verb})"
            )

    def _collect(self, step: str, timeout: float = 120.0) -> list:
        from ray_tpu._private import serialization
        from ray_tpu.exceptions import CollectiveTimeoutError

        out: list = [None] * self.world_size
        deadline = time.monotonic() + timeout
        remaining = set(range(self.world_size))
        while remaining and time.monotonic() < deadline:
            self._check_destroyed(step)
            for r in list(remaining):
                resp = self.gcs.call("kv_get", {"key": self._key(step, r)})
                if resp.get("found"):
                    out[r] = serialization.loads(resp["value"])
                    remaining.discard(r)
            if remaining:
                time.sleep(0.01)
        if remaining:
            from ray_tpu.util.collective.p2p import COLL

            COLL.timeouts += 1
            raise CollectiveTimeoutError(
                f"collective {step} on group {self.group_name!r} (rank "
                f"{self.rank}) timed out after {timeout}s waiting for ranks "
                f"{sorted(remaining)}",
                group=self.group_name, ranks=remaining,
            )
        return out

    def _sync(self, step: str, value) -> list:
        self._post(step, value)
        stack = self._collect(step)
        self._epoch += 1
        return stack

    def allreduce(self, x, op: ReduceOp = ReduceOp.SUM):
        # Post jax values as-is (they serialize with their sharding): if
        # EVERY rank does, the reduce stays in jnp and the output is a
        # device array (placement parity with broadcast).
        stack = self._sync("allreduce", x if _is_jax_array(x) else np.asarray(x))
        return _reduce_stack(self.group_name, "allreduce", stack, op)

    def allgather(self, x):
        return _uniform_stack(self.group_name, "allgather", self._sync("allgather", x))

    def reducescatter(self, x, op: ReduceOp = ReduceOp.SUM):
        post = x if _is_jax_array(x) else np.asarray(x)
        assert post.shape[0] == self.world_size
        stack = self._sync("reducescatter", post)
        return _reduce_stack(self.group_name, "reducescatter", stack, op)[self.rank]

    def broadcast(self, x, src_rank: int = 0):
        """Every rank gets the src rank's value AS POSTED: a jax.Array
        round-trips bit-exact with its sharding (the payload-parity
        contract the device-object broadcast path relies on)."""
        stack = self._sync("broadcast", x)
        return stack[src_rank]

    def reduce(self, x, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        out = self.allreduce(x, op)
        return out if self.rank == dst_rank else None

    def barrier(self):
        self._sync("barrier", np.zeros((1,)))

    def send_recv(self, x, perm):
        """Pairwise exchange: returns the tensor sent to this rank (or x)."""
        stack = self._sync("sendrecv", x)
        for src, dst in perm:
            if dst == self.rank:
                return np.asarray(stack[src])
        return np.asarray(x)

    def send(self, value, dst_rank: int, tag: str) -> int:
        """2-party p2p send (reference: collective.py:531). Unlike the ring
        collectives above, only the two endpoints participate; device arrays
        keep their sharding across the hop (p2p.py mailbox)."""
        from ray_tpu.util.collective.p2p import mailbox_send

        return mailbox_send(self.gcs, self.group_name, self.rank, dst_rank, tag, value)

    def recv(self, src_rank: int, tag: str, timeout: float = 120.0):
        """2-party p2p recv (reference: collective.py:594)."""
        from ray_tpu.util.collective.p2p import mailbox_recv

        return mailbox_recv(self.gcs, self.group_name, src_rank, self.rank, tag, timeout)

    # ---- group broadcast (ONE op fanning a payload to every member) ----

    def _snapshot(self) -> tuple:
        """(roster, {rank: addr}) for the CURRENT roster epoch. One cheap
        epoch read per verb; the address fan-fetch reruns only when the
        epoch moved (join/leave/re-register all bump it). Groups that never
        published a roster (pre-elastic callers) fall back to the static
        ``range(world_size)`` world under cache key epoch 0."""
        from ray_tpu.util.collective.p2p import fetch_member_addrs, fetch_roster

        roster = fetch_roster(self.gcs, self.group_name)
        repoch = roster["epoch"] if roster else 0
        cache = self._addr_cache
        if cache is None or cache[0] != repoch:
            ranks = roster["ranks"] if roster else list(range(self.world_size))
            world = max(self.world_size, roster["world_size"] if roster else 0)
            cache = (repoch, fetch_member_addrs(self.gcs, self.group_name, world, ranks=ranks))
            self._addr_cache = cache
        return roster, cache[1]

    def _addrs(self) -> dict:
        return self._snapshot()[1]

    def bcast_send_payload(self, value, tag: str, timeout: float = 30.0,
                           mailbox_fallback: bool = True,
                           topology: str = "tree") -> dict:
        """Holder-side group broadcast: one serialize, acked chunk pushes
        riding the binomial relay tree by default (p2p.group_bcast_send) —
        the fan-out device_object.broadcast() rides. The target set is the
        ROSTER SNAPSHOT at send time (members that joined since init are
        included, departed ones are not), a mid-op rejoiner is retried at
        its fresh address, and unreachable members are evicted into the
        next epoch. Returns the per-rank delivery map; never raises for a
        dead member (the caller owns the policy). ``mailbox_fallback=False``
        when receivers only watch the direct inbox (the
        descriptor-resolution path); ``topology="flat"`` forces PR 15's
        per-rank fan-out (the bench A/B arm)."""
        from ray_tpu._private import worker_context
        from ray_tpu.util.collective.p2p import group_bcast_send

        self._check_destroyed("bcast_send_payload")
        cw = worker_context.get_core_worker()
        roster, addrs = self._snapshot()
        world = max(self.world_size, roster["world_size"] if roster else 0)
        return group_bcast_send(
            cw, self.gcs, self.group_name, self.rank, world, tag,
            value, member_addrs=addrs, timeout=timeout,
            mailbox_fallback=mailbox_fallback, topology=topology,
            roster=roster,
        )

    def _finalize_like(self, value, out):
        """Payload-parity for the reducing verbs: a jax input produces a
        jax output (the tree combines on the host — np bytes on the wire —
        so the root converts back once before handing out/broadcasting)."""
        if _is_jax_array(value):
            import jax.numpy as jnp

            return jnp.asarray(out)
        return out

    def reduce_send_payload(self, value, tag: str, op: ReduceOp = ReduceOp.SUM,
                            dst_rank: int = 0, timeout: float = 60.0):
        """Tree reduce toward ``dst_rank`` over the direct-mailbox plane
        (p2p.group_reduce_send): partials combine chunk-wise at every relay
        hop, so no single member ever receives K payloads. Returns the
        reduced value on ``dst_rank`` (same placement as ``value``), None
        elsewhere. The tree spans the roster snapshot at call time. Falls
        back to the GCS ring when any member lacks a registered address
        (old-style members) or the group is trivial (world_size < 2)."""
        self._check_destroyed("reduce_send_payload")
        roster, addrs = self._snapshot()
        ranks = roster["ranks"] if roster else list(range(self.world_size))
        missing = [r for r in ranks if r != self.rank and r not in addrs]
        if len(ranks) < 2 or missing:
            return self.reduce(value, dst_rank, op)
        from ray_tpu._private import worker_context
        from ray_tpu.util.collective.p2p import group_reduce_send

        cw = worker_context.get_core_worker()
        out = group_reduce_send(
            cw, self.gcs, self.group_name, self.rank, self.world_size, tag,
            value, op=op, dst_rank=dst_rank, member_addrs=addrs, timeout=timeout,
            roster=roster,
        )
        if out is None:
            return None
        return self._finalize_like(value, out)

    def allreduce_payload(self, value, tag: str, op: ReduceOp = ReduceOp.SUM,
                          timeout: float = 60.0):
        """Tree allreduce (reduce up to rank 0, tree-broadcast back down):
        every rank returns the same reduced value, placed like ``value``
        (the root finalizes ONCE before the down-broadcast). Ring fallback
        under the same conditions as :meth:`reduce_send_payload`."""
        self._check_destroyed("allreduce_payload")
        roster, addrs = self._snapshot()
        ranks = roster["ranks"] if roster else list(range(self.world_size))
        missing = [r for r in ranks if r != self.rank and r not in addrs]
        if len(ranks) < 2 or missing:
            return self.allreduce(value, op)
        from ray_tpu._private import worker_context
        from ray_tpu.util.collective.p2p import group_allreduce

        cw = worker_context.get_core_worker()
        return group_allreduce(
            cw, self.gcs, self.group_name, self.rank, self.world_size, tag,
            value, op=op, member_addrs=addrs, timeout=timeout,
            finalize=lambda reduced: self._finalize_like(value, reduced),
            roster=roster,
        )

    def reducescatter_payload(self, value, tag: str, op: ReduceOp = ReduceOp.SUM,
                              timeout: float = 60.0):
        """Tree reduce-scatter over the direct-mailbox plane
        (p2p.group_reducescatter): partials combine chunk-wise up the tree
        and the root hands each member only ITS reduced slice — O(log K)
        hops and 1/K of the ring's per-member download. Ring contract
        preserved: leading dim == member count; sorted-roster position i
        gets slice i, placed like ``value`` (the root finalizes per shard
        before fanning out). Ring fallback under the same conditions as
        :meth:`reduce_send_payload`."""
        self._check_destroyed("reducescatter_payload")
        roster, addrs = self._snapshot()
        ranks = roster["ranks"] if roster else list(range(self.world_size))
        missing = [r for r in ranks if r != self.rank and r not in addrs]
        if len(ranks) < 2 or missing:
            return self.reducescatter(value, op)
        from ray_tpu._private import worker_context
        from ray_tpu.util.collective.p2p import group_reducescatter

        cw = worker_context.get_core_worker()
        return group_reducescatter(
            cw, self.gcs, self.group_name, self.rank, self.world_size, tag,
            value, op=op, member_addrs=addrs, timeout=timeout,
            finalize=lambda shard: self._finalize_like(value, shard),
            roster=roster,
        )

    def bcast_recv_payload(self, src_rank: int, tag: str, timeout: float = 120.0):
        """Member-side receive of a group broadcast (direct mailbox, GCS
        fallback, typed timeout naming group/rank/tag). A concurrent
        destroy of this group aborts the wait with a typed CollectiveError
        instead of parking until the deadline."""
        from ray_tpu._private import worker_context
        from ray_tpu.util.collective.p2p import group_bcast_recv

        self._check_destroyed("bcast_recv_payload")
        cw = worker_context.get_core_worker()
        return group_bcast_recv(
            cw, self.gcs, self.group_name, src_rank, self.rank, tag, timeout,
            abort_check=lambda: self._destroyed,
        )

    def destroy(self):
        from ray_tpu.util.collective.p2p import (
            roster_leave,
            sweep_group_kv,
            unregister_member_addr,
        )

        self._destroyed = True
        try:
            roster_leave(self.gcs, self.group_name, self.rank)
        except Exception:
            pass
        unregister_member_addr(self.gcs, self.group_name, self.rank)
        if self.rank == 0:
            # Rank 0 (conventionally the driver/learner side, destroyed
            # last in the gang-teardown idiom) sweeps the group's KV back
            # to baseline: repoch + roster back-window + every addr row.
            try:
                sweep_group_kv(self.gcs, self.group_name, self.world_size)
            except Exception:
                pass
