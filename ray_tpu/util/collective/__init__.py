from ray_tpu.util.collective.collective import (
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_group,
    init_collective_group,
    is_group_initialized,
    reduce,
    reducescatter,
    send_recv,
)
from ray_tpu.util.collective.types import Backend, ReduceOp

__all__ = [
    "Backend",
    "ReduceOp",
    "allgather",
    "allreduce",
    "barrier",
    "broadcast",
    "create_collective_group",
    "destroy_collective_group",
    "get_group",
    "init_collective_group",
    "is_group_initialized",
    "reduce",
    "reducescatter",
    "send_recv",
]
