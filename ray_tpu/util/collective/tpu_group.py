"""TPU collective group — XLA collectives over ICI.

TPU-native replacement for the reference's NCCLGroup
(python/ray/util/collective/collective_group/nccl_collective_group.py:127):
instead of NCCL communicators exchanged via ncclUniqueId, a group of member
processes (one actor per TPU host) forms a single XLA "world":

- rendezvous: rank 0 publishes the jax.distributed coordinator address in the
  GCS KV (exactly the reference's Rendezvous-via-named-store pattern,
  nccl_collective_group.py:28) and every member calls
  ``jax.distributed.initialize(coordinator, world_size, rank)``
- the group then materialises a ``jax.sharding.Mesh`` over the global device
  set — (processes × local chips) — and every collective op is a jitted
  ``shard_map`` program whose psum/all_gather/ppermute compile onto ICI
  (cross-slice traffic rides DCN via XLA multi-slice support)
- collectives are SPMD: every member must call the same op in the same order,
  the same contract NCCL imposes.

A world_size=1 group degenerates to the process's local device mesh — the
single-host multi-chip case where ICI collectives still apply but no
inter-process bootstrap is needed.
"""

from __future__ import annotations

import logging
import socket
import time

from ray_tpu.util.collective.types import ReduceOp

logger = logging.getLogger(__name__)

# Highest collective-group epoch this process has participated in, per group
# name: a member re-forming a group after destroy must not accept the dead
# epoch's coordinator from the KV (fresh processes start at 0 and accept the
# current epoch).
_last_epochs: dict = {}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))  # any-interface: the coordinator must be reachable from other hosts
    port = s.getsockname()[1]
    s.close()
    return port


def _routable_ip() -> str:
    """Best-effort primary-interface IP (UDP-connect trick; no packet sent)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
        finally:
            s.close()
    except Exception:
        return "127.0.0.1"


def _shard_map():
    from ray_tpu.util.jax_compat import shard_map

    return shard_map()


class TpuCollectiveGroup:
    """One member's view of an XLA collective world."""

    def __init__(
        self,
        group_name: str,
        world_size: int,
        rank: int,
        coordinator: str | None = None,
        gcs=None,
        node_ip: str | None = None,
    ):
        import jax

        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self.epoch = 0
        self._gcs = gcs
        self._node_ip = node_ip
        self._op_cache: dict = {}

        if world_size > 1:
            coordinator = coordinator or self._rendezvous(gcs)
            # jax.distributed.initialize refuses to run once the XLA backend
            # has been touched (e.g. a previous epoch of this group, or any
            # local jax work). Reset the backends HERE, at re-form time,
            # rather than in destroy(): live jax.Arrays and world_size=1
            # local-mesh groups in this process survive a destroy and only
            # die when a new multi-process world actually has to be built
            # (one process can host at most one such world).
            try:
                from jax._src import xla_bridge

                if xla_bridge.backends_are_initialized():
                    from jax.extend.backend import clear_backends

                    clear_backends()
            except Exception as e:
                logger.debug("backend reset before initialize: %s", e)
            # A SURVIVOR of a killed gang still holds the previous epoch's
            # distributed world (graceful destroy() shuts it down; a peer
            # SIGKILL doesn't). initialize() refuses to run twice per
            # process, so tear the stale world down here — bounded, because
            # shutdown() against a DEAD coordinator can hang in its
            # coordination-service handshake rather than raise. On timeout,
            # fail fast: this process cannot host a new world, and the gang
            # restart path (BackendExecutor) replaces it with a fresh one.
            import threading as _threading

            shut_done = _threading.Event()

            def _shutdown_stale():
                try:
                    jax.distributed.shutdown()
                except Exception as e:
                    logger.debug("stale distributed world shutdown: %s", e)
                finally:
                    shut_done.set()

            _threading.Thread(target=_shutdown_stale, daemon=True).start()
            if not shut_done.wait(15.0):
                raise RuntimeError(
                    "stale multi-process XLA world did not shut down "
                    "(previous epoch's coordinator dead?); this process "
                    "cannot host a new collective world — restart the gang "
                    "with fresh workers"
                )
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world_size,
                process_id=rank,
            )
        import numpy as np
        from jax.sharding import Mesh

        devices = np.array(jax.devices())
        self.local_device_count = len(jax.local_devices())
        self.devices = devices.reshape(world_size, -1)
        self.mesh = Mesh(self.devices, ("proc", "local"))
        logger.info(
            "collective group %s: rank %d/%d, %d global devices",
            group_name,
            rank,
            world_size,
            devices.size,
        )

    # ---- rendezvous via GCS KV (reference: Rendezvous in
    # nccl_collective_group.py:28, unique id in a named store actor) ----

    def _rendezvous(self, gcs) -> str:
        """Rank 0 advertises ``<routable-ip>:<port>`` under an epoch-scoped
        KV key; members poll the epoch counter, then the coordinator for
        that epoch. The epoch bump is what lets a destroyed group re-form
        under the same name (a member of a dead epoch can't accidentally
        dial a stale coordinator: re-init always publishes a fresh epoch,
        so a member that raced a stale read fails its connect, and the
        gang retry reads the new epoch)."""
        from ray_tpu._private.config import get_config

        assert gcs is not None, "GCS client required for multi-process rendezvous"
        epoch_key = f"collective/{self.group_name}/epoch"
        if self.rank == 0:
            resp = gcs.call("kv_get", {"key": epoch_key})
            epoch = int(bytes(resp["value"]).decode()) + 1 if resp.get("found") else 1
            # The node's GCS-registered address, NOT loopback: a rank on
            # another host must be able to dial this (reference advertises
            # ncclUniqueId the same way, nccl_collective_group.py:28).
            ip = self._node_ip or _routable_ip()
            if ip in ("0.0.0.0", ""):
                ip = _routable_ip()
            coordinator = f"{ip}:{_free_port()}"
            gcs.call("kv_put", {"key": f"collective/{self.group_name}/coord/{epoch}", "value": coordinator.encode()})
            gcs.call("kv_put", {"key": epoch_key, "value": str(epoch).encode()})
            self.epoch = epoch
            _last_epochs[self.group_name] = epoch
            return coordinator
        deadline = time.monotonic() + get_config().collective_rendezvous_timeout_s
        last_seen = _last_epochs.get(self.group_name, 0)
        candidate = None  # (epoch, address)
        while time.monotonic() < deadline:
            resp = gcs.call("kv_get", {"key": epoch_key})
            if resp.get("found"):
                epoch = int(bytes(resp["value"]).decode())
                if epoch > (candidate[0] if candidate else last_seen):
                    coord = gcs.call("kv_get", {"key": f"collective/{self.group_name}/coord/{epoch}"})
                    if coord.get("found"):
                        candidate = (epoch, bytes(coord["value"]).decode())
            if candidate is not None:
                # Liveness probe before handing the address to
                # jax.distributed.initialize: a stale key from a crashed
                # rank 0 (whose destroy never ran) would otherwise block the
                # whole init on a dead endpoint. The live rank 0 only starts
                # listening once IT calls initialize, so a refused connect
                # just means "keep polling" — a newer epoch supersedes.
                host, port = candidate[1].rsplit(":", 1)
                try:
                    s = socket.create_connection((host, int(port)), timeout=0.25)
                    s.close()
                    self.epoch = candidate[0]
                    _last_epochs[self.group_name] = candidate[0]
                    return candidate[1]
                except OSError:
                    pass
            time.sleep(0.05)
        raise TimeoutError(f"collective rendezvous for group {self.group_name} timed out")

    # ---- helpers ----

    def _global(self, x, partitioned: bool):
        """Lift this member's local tensor into the global mesh array.

        partitioned=False: x is this rank's full tensor (allreduce-style);
        global shape (world, *x.shape), sharded over 'proc', replicated local.
        """
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        x = jnp.asarray(x)
        if self.world_size == 1:
            return x
        locals_ = [jax.device_put(x[None], d) for d in self.devices[self.rank]]
        global_shape = (self.world_size,) + x.shape
        return jax.make_array_from_single_device_arrays(
            global_shape, NamedSharding(self.mesh, P("proc")), locals_
        )

    def _local(self, out):
        """Extract this rank's addressable result (replicated output)."""
        import numpy as np

        if self.world_size == 1:
            return out
        shards = out.addressable_shards
        return shards[0].data if shards else np.asarray(out)

    def _jit_op(self, key, build):
        fn = self._op_cache.get(key)
        if fn is None:
            fn = build()
            self._op_cache[key] = fn
        return fn

    # ---- collectives (API parity with collective.py:258-594) ----

    def allreduce(self, x, op: ReduceOp = ReduceOp.SUM):
        import jax
        import jax.numpy as jnp
        import jax.lax as lax
        from jax.sharding import PartitionSpec as P

        x = jnp.asarray(x)
        if self.world_size == 1:
            return x

        def build():
            shard_map = _shard_map()

            def body(a):
                # a: (1, *shape) — this proc's copy.
                if op == ReduceOp.SUM:
                    r = lax.psum(a, "proc")
                elif op == ReduceOp.MEAN:
                    r = lax.pmean(a, "proc")
                elif op == ReduceOp.MAX:
                    r = lax.pmax(a, "proc")
                elif op == ReduceOp.MIN:
                    r = lax.pmin(a, "proc")
                elif op == ReduceOp.PRODUCT:
                    r = lax.all_gather(a, "proc").prod(axis=0)
                else:
                    raise ValueError(op)
                return r

            return jax.jit(
                shard_map(
                    body,
                    mesh=self.mesh,
                    in_specs=P("proc"),
                    out_specs=P(),
                    check_vma=False,
                )
            )

        g = self._global(x, partitioned=False)
        out = self._jit_op(("allreduce", x.shape, str(x.dtype), op), build)(g)
        return self._local(out)[0]

    def allgather(self, x):
        """Returns the (world, *shape) stack of every rank's tensor."""
        import jax
        import jax.numpy as jnp
        import jax.lax as lax
        from jax.sharding import PartitionSpec as P

        x = jnp.asarray(x)
        if self.world_size == 1:
            return x[None]

        def build():
            shard_map = _shard_map()

            def body(a):
                return lax.all_gather(a, "proc", axis=0, tiled=True)

            return jax.jit(
                shard_map(
                    body, mesh=self.mesh, in_specs=P("proc"), out_specs=P(), check_vma=False
                )
            )

        g = self._global(x, partitioned=False)
        out = self._jit_op(("allgather", x.shape, str(x.dtype)), build)(g)
        return self._local(out)

    def reducescatter(self, x, op: ReduceOp = ReduceOp.SUM):
        """x: this rank's (world, chunk) stacked input; returns this rank's
        reduced chunk (x[rank] summed over ranks)."""
        import jax
        import jax.numpy as jnp
        import jax.lax as lax
        from jax.sharding import PartitionSpec as P

        x = jnp.asarray(x)
        assert x.shape[0] == self.world_size, "leading dim must equal world size"
        if self.world_size == 1:
            return x[0]

        def build():
            shard_map = _shard_map()

            def body(a):
                # a: (1, world, chunk...) per proc.
                r = lax.psum_scatter(a[0], "proc", scatter_dimension=0, tiled=False)
                return r[None]

            return jax.jit(
                shard_map(
                    body, mesh=self.mesh, in_specs=P("proc"), out_specs=P("proc"), check_vma=False
                )
            )

        g = self._global(x, partitioned=False)
        out = self._jit_op(("reducescatter", x.shape, str(x.dtype), op), build)(g)
        local = self._local(out)
        return local[0]

    def broadcast(self, x, src_rank: int = 0):
        import jax
        import jax.numpy as jnp
        import jax.lax as lax
        from jax.sharding import PartitionSpec as P

        x = jnp.asarray(x)
        if self.world_size == 1:
            return x

        def build():
            shard_map = _shard_map()

            def body(a):
                # Select src's copy on every proc: sum of masked copies.
                idx = lax.axis_index("proc")
                mask = (idx == src_rank).astype(a.dtype)
                return lax.psum(a * mask, "proc")

            return jax.jit(
                shard_map(
                    body, mesh=self.mesh, in_specs=P("proc"), out_specs=P(), check_vma=False
                )
            )

        g = self._global(x, partitioned=False)
        out = self._jit_op(("broadcast", x.shape, str(x.dtype), src_rank), build)(g)
        return self._local(out)[0]

    def reduce(self, x, dst_rank: int = 0, op: ReduceOp = ReduceOp.SUM):
        # XLA worlds have no single-destination reduce; allreduce and let
        # non-destination ranks drop the value (same cost over ICI ring).
        out = self.allreduce(x, op)
        return out if self.rank == dst_rank else None

    def barrier(self):
        import jax.numpy as jnp

        self.allreduce(jnp.zeros((1,), jnp.float32))

    def send_recv(self, x, perm: list[tuple[int, int]]):
        """ppermute: pairwise exchange over the proc axis (the p2p primitive —
        reference collective.py:531/594 send/recv; on TPU this is the ring
        primitive ring-attention builds on)."""
        import jax
        import jax.numpy as jnp
        import jax.lax as lax
        from jax.sharding import PartitionSpec as P

        x = jnp.asarray(x)
        if self.world_size == 1:
            return x

        perm_t = tuple(tuple(p) for p in perm)

        def build():
            shard_map = _shard_map()

            def body(a):
                return lax.ppermute(a, "proc", perm=perm_t)

            return jax.jit(
                shard_map(
                    body, mesh=self.mesh, in_specs=P("proc"), out_specs=P("proc"), check_vma=False
                )
            )

        g = self._global(x, partitioned=False)
        out = self._jit_op(("ppermute", x.shape, str(x.dtype), perm_t), build)(g)
        return self._local(out)[0]

    def send(self, value, dst_rank: int, tag: str) -> int:
        """2-party p2p send (reference: collective.py:531). In-program
        collectives ride ICI (ppermute above); this out-of-band object
        transfer uses the group's KV mailbox, and the receiver's device_put
        re-lands shards on its mesh — swap in a device-direct transfer here
        when jax exposes one (see util/collective/p2p.py)."""
        from ray_tpu.util.collective.p2p import mailbox_send

        return mailbox_send(self._gcs, self.group_name, self.rank, dst_rank, tag, value)

    def recv(self, src_rank: int, tag: str, timeout: float = 120.0):
        """2-party p2p recv (reference: collective.py:594)."""
        from ray_tpu.util.collective.p2p import mailbox_recv

        return mailbox_recv(self._gcs, self.group_name, src_rank, self.rank, tag, timeout)

    # ---- group payload verbs (device_object broadcast/reduce seam) ----
    #
    # IN-PROGRAM collectives already ride ICI (broadcast()/allreduce()/
    # reduce() above compile to psum variants over the mesh). The verbs
    # below move an OUT-OF-BAND payload — a sealed device object fanning
    # holder→members or combining across holders — and, like send/recv, use
    # the host plane until jax exposes a cross-process device-to-device
    # transfer in this image: swap the ICI/DMA group op in HERE (one
    # serialize → one ICI broadcast/allreduce over the group mesh) without
    # touching any caller (DeviceObjectManager.broadcast_via_group /
    # reduce_via_group). This seam now covers EVERY verb: on the tpu
    # backend the reducing payload verbs map straight onto the psum-based
    # collectives (the data is already on the mesh — no host relay tree
    # needed), which is exactly the swap the cpu tree emulates.

    def bcast_send_payload(self, value, tag: str, timeout: float = 30.0,
                           mailbox_fallback: bool = True) -> dict:
        from ray_tpu._private import worker_context
        from ray_tpu.util.collective.p2p import (
            fetch_member_addrs,
            fetch_roster,
            group_bcast_send,
        )

        cw = worker_context.get_core_worker()
        # The address cache is keyed on the ROSTER epoch (not the
        # coordinator epoch): a member that re-registered at the same
        # coordinator epoch — a respawn joining under its old rank — has a
        # new address under the same row, and only a roster bump says so.
        # Same cache shape as CpuCollectiveGroup._snapshot.
        roster = fetch_roster(self._gcs, self.group_name)
        repoch = roster["epoch"] if roster else 0
        cached = getattr(self, "_bcast_addrs", None)
        if cached is None or cached[0] != repoch:
            ranks = roster["ranks"] if roster else None
            world = max(self.world_size, roster["world_size"] if roster else 0)
            cached = self._bcast_addrs = (
                repoch,
                fetch_member_addrs(self._gcs, self.group_name, world, ranks=ranks),
            )
        world = max(self.world_size, roster["world_size"] if roster else 0)
        return group_bcast_send(
            cw, self._gcs, self.group_name, self.rank, world, tag,
            value, member_addrs=cached[1], timeout=timeout,
            mailbox_fallback=mailbox_fallback, roster=roster,
        )

    def bcast_recv_payload(self, src_rank: int, tag: str, timeout: float = 120.0):
        from ray_tpu._private import worker_context
        from ray_tpu.util.collective.p2p import group_bcast_recv

        cw = worker_context.get_core_worker()
        return group_bcast_recv(
            cw, self._gcs, self.group_name, src_rank, self.rank, tag, timeout
        )

    def reduce_send_payload(self, value, tag: str, op: ReduceOp = ReduceOp.SUM,
                            dst_rank: int = 0, timeout: float = 60.0):
        """Out-of-band group reduce on the tpu backend: the members' arrays
        live on the SAME mesh, so the combine IS a psum — no host relay
        tree. ``tag``/``timeout`` are accepted for cpu-seam parity (the
        gang rendezvous is the compiled program itself)."""
        return self.reduce(value, dst_rank, op)

    def allreduce_payload(self, value, tag: str, op: ReduceOp = ReduceOp.SUM,
                          timeout: float = 60.0):
        """Out-of-band group allreduce: psum over ICI (see seam note)."""
        return self.allreduce(value, op)

    def destroy(self):
        """Tear down the XLA world so the group can re-form (gang restart):
        drops the compiled-op cache, shuts down jax.distributed (releasing
        the coordinator connection), and best-effort clears this epoch's
        coordinator key. The next init under the same name bumps the epoch
        (SURVEY.md hard part #1: group epochs + restart-the-group recovery)."""
        import jax

        self._op_cache.clear()
        if self._gcs is not None:
            from ray_tpu.util.collective.p2p import roster_leave, unregister_member_addr

            try:
                roster_leave(self._gcs, self.group_name, self.rank)
            except Exception:
                pass
            unregister_member_addr(self._gcs, self.group_name, self.rank)
        if self.world_size > 1:
            try:
                jax.distributed.shutdown()
            except Exception as e:  # already down / never initialized
                logger.debug("jax.distributed.shutdown: %s", e)
            if self.rank == 0 and self._gcs is not None:
                # Sweep this epoch's coordinator row AND the dead-epoch
                # rows behind it (every re-formation leaked its
                # predecessor's coord/<e> before), plus the roster rows
                # and orphaned addr rows — KV back to baseline.
                from ray_tpu.util.collective.p2p import sweep_group_kv

                for e in range(max(1, self.epoch - 16), self.epoch + 1):
                    try:
                        self._gcs.call("kv_del", {"key": f"collective/{self.group_name}/coord/{e}"})
                    except Exception:
                        pass
                try:
                    sweep_group_kv(self._gcs, self.group_name, self.world_size)
                except Exception:
                    pass
