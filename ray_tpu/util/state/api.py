"""Public cluster-state API.

TPU-native analog of the reference's ``ray.util.state``
(python/ray/util/state/api.py, aggregated by dashboard/state_aggregator.py):
typed listings of nodes, actors, tasks, objects, workers, placement groups and
jobs, plus task summaries. All reads go to the GCS (and live raylets for
object/worker state) — there is no separate aggregator daemon.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from ray_tpu._private.state import GlobalState


@dataclass
class StateApiOptions:
    limit: int = 10_000
    filters: list[tuple[str, str, Any]] = field(default_factory=list)


def _apply_filters(rows: list[dict], filters) -> list[dict]:
    if not filters:
        return rows
    out = []
    for row in rows:
        ok = True
        for key, op, value in filters:
            have = row.get(key)
            if op == "=":
                ok = have == value
            elif op == "!=":
                ok = have != value
            else:
                raise ValueError(f"unsupported filter op {op!r}")
            if not ok:
                break
        if ok:
            out.append(row)
    return out


def _state(address=None) -> GlobalState:
    return GlobalState(gcs_address=address)


def list_nodes(address=None, filters=None, limit: int = 10_000) -> list[dict]:
    state = _state(address)
    try:
        rows = [
            {
                "node_id": n.get("node_id"),
                "state": n.get("state"),
                "address": n.get("address"),
                "resources_total": n.get("resources_total"),
                "resources_available": n.get("resources_available"),
                "labels": n.get("labels", {}),
            }
            for n in state.nodes()
        ]
        return _apply_filters(rows, filters)[:limit]
    finally:
        state.close()


def list_actors(address=None, filters=None, limit: int = 10_000) -> list[dict]:
    state = _state(address)
    try:
        return _apply_filters(state.actors(), filters)[:limit]
    finally:
        state.close()


def list_placement_groups(address=None, filters=None, limit: int = 10_000) -> list[dict]:
    state = _state(address)
    try:
        return _apply_filters(state.placement_groups(), filters)[:limit]
    finally:
        state.close()


def list_jobs(address=None, filters=None, limit: int = 10_000) -> list[dict]:
    state = _state(address)
    try:
        return _apply_filters(state.jobs(), filters)[:limit]
    finally:
        state.close()


def list_tasks(address=None, filters=None, limit: int = 10_000) -> list[dict]:
    """One row per task, reduced from the task-event log (latest state wins)."""
    state = _state(address)
    try:
        by_task: dict[str, dict] = {}
        # Events from different processes arrive at the GCS out of order
        # (driver and worker flush on independent ticks) — reduce by lifecycle
        # rank first so a terminal state always wins, then by timestamp;
        # cross-process clocks are not comparable enough to order states.
        rank = {"PENDING_ARGS_AVAIL": 0, "RUNNING": 1, "FINISHED": 2, "FAILED": 2}
        events = sorted(
            state.task_events(limit=limit * 4),
            key=lambda e: (rank.get(e.get("state"), 0), e.get("ts", 0)),
        )
        for ev in events:
            tid = ev.get("task_id")
            row = by_task.setdefault(
                tid,
                {
                    "task_id": tid,
                    "name": ev.get("name"),
                    "job_id": ev.get("job_id"),
                    "actor_id": ev.get("actor_id") or None,
                    "state": ev.get("state"),
                    "node_id": ev.get("node_id"),
                    "worker_id": ev.get("worker_id"),
                },
            )
            row["state"] = ev.get("state")
            row["node_id"] = ev.get("node_id")
            row["worker_id"] = ev.get("worker_id")
            if "trace_ctx" in ev:
                row["trace_ctx"] = ev["trace_ctx"]
            if "start_ts" in ev:
                row["start_time"] = ev["start_ts"]
            if "end_ts" in ev:
                row["end_time"] = ev["end_ts"]
            if "error_type" in ev:
                row["error_type"] = ev["error_type"]
        return _apply_filters(list(by_task.values()), filters)[:limit]
    finally:
        state.close()


def list_workers(address=None, filters=None, limit: int = 10_000) -> list[dict]:
    state = _state(address)
    try:
        rows = []
        for node in state.nodes():
            if node.get("state") != "ALIVE":
                continue
            try:
                live = state.node_state(node)
            except Exception:
                continue
            for wid, w in (live.get("workers") or {}).items():
                rows.append(
                    {
                        "worker_id": wid,
                        "node_id": node.get("node_id"),
                        "state": w.get("state"),
                        "pid": w.get("pid"),
                        "actor_id": w.get("actor_id"),
                    }
                )
        return _apply_filters(rows, filters)[:limit]
    finally:
        state.close()


def list_objects(address=None, filters=None, limit: int = 10_000) -> list[dict]:
    """Cluster-wide plasma object listing (per-node store contents)."""
    state = _state(address)
    try:
        rows = []
        for node in state.nodes():
            if node.get("state") != "ALIVE":
                continue
            try:
                live = state.node_state(node)
            except Exception:
                continue
            store = live.get("store") or {}
            for oid, meta in (store.get("objects") or {}).items():
                entry = {"object_id": oid, "node_id": node.get("node_id")}
                if isinstance(meta, dict):
                    entry.update(meta)
                rows.append(entry)
        return _apply_filters(rows, filters)[:limit]
    finally:
        state.close()


def list_device_objects(address=None, filters=None, limit: int = 10_000) -> list[dict]:
    """Cluster-wide device-resident objects (experimental/device_object/):
    one row per object the plane keeps on a holder's devices — shape, dtype,
    payload bytes, transport, and the holder's identity."""
    state = _state(address)
    try:
        return _apply_filters(state.device_objects(), filters)[:limit]
    finally:
        state.close()


def summarize_tasks(address=None) -> dict:
    """Counts of tasks per (name, state) — reference's task summary view."""
    rows = list_tasks(address=address)
    summary: dict[str, dict] = {}
    for row in rows:
        entry = summary.setdefault(
            row.get("name") or "?", {"total": 0, "states": {}}
        )
        entry["total"] += 1
        st = row.get("state") or "?"
        entry["states"][st] = entry["states"].get(st, 0) + 1
    return summary
