"""Cluster-state introspection (reference: python/ray/util/state)."""

from ray_tpu.util.state.api import (  # noqa: F401
    StateApiOptions,
    list_actors,
    list_device_objects,
    list_jobs,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_tasks,
    list_workers,
    summarize_tasks,
)

__all__ = [
    "StateApiOptions",
    "list_actors",
    "list_device_objects",
    "list_jobs",
    "list_nodes",
    "list_objects",
    "list_placement_groups",
    "list_tasks",
    "list_workers",
    "summarize_tasks",
]
