"""ActorPool — load-balance tasks over a fixed set of actors.

Analog of the reference's ray.util.ActorPool (python/ray/util/actor_pool.py):
``map``/``map_unordered`` stream values through the pool; ``submit``/
``get_next``/``get_next_unordered`` give manual control; idle actors can be
popped/pushed for elastic pools.
"""

from __future__ import annotations

import ray_tpu


class ActorPool:
    def __init__(self, actors):
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits = []

    def submit(self, fn, value):
        """fn is (actor, value) -> ObjectRef; queues if no actor is free."""
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future)

    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def get_next(self, timeout: float | None = None):
        """Next result in submission order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        future = self._index_to_future[self._next_return_index]
        ready, _ = ray_tpu.wait([future], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        _, actor = self._future_to_actor.pop(future)
        self._return_actor(actor)
        return ray_tpu.get(future)

    def get_next_unordered(self, timeout: float | None = None):
        """Next result in completion order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for result")
        future = ready[0]
        index, actor = self._future_to_actor.pop(future)
        del self._index_to_future[index]
        self._return_actor(actor)
        return ray_tpu.get(future)

    def map(self, fn, values):
        """Ordered streaming map; yields results as they become available."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn, values):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    def pop_idle(self):
        return self._idle.pop() if self.has_free() else None

    def push(self, actor):
        self._return_actor(actor)
