"""Spark-on-ray_tpu shim (analog of reference python/ray/util/spark/ —
RayDP-style cluster startup). PySpark is not in this image; the entry points
raise with install guidance, keeping the reference's API surface."""

from __future__ import annotations


def _gated(name: str):
    def _fn(*args, **kwargs):
        raise ImportError(
            f"{name} requires the 'pyspark' package, which is not installed "
            "in this environment (pip install pyspark). Dataset interop "
            "(ray_tpu.data.from_pandas/from_arrow) works without Spark."
        )

    _fn.__name__ = name
    return _fn


setup_ray_cluster = _gated("setup_ray_cluster")
shutdown_ray_cluster = _gated("shutdown_ray_cluster")
