"""Cluster-wide object distribution utilities.

`broadcast_object` proactively replicates a plasma object to every (or a
chosen set of) alive node(s) over the raylet push plane — the user-facing
entry to the PushManager/binomial-tree path (reference internals:
src/ray/object_manager/push_manager.h:29; the reference exposes no public
API for this, but its 1-GiB-broadcast envelope test exercises the same
machinery via task arguments).

The tree is CUT-THROUGH (ISSUE 10): the relay subtree rides inside each
`push_begin`, and every level starts forwarding chunks downstream as they
arrive rather than after its local copy seals, so end-to-end latency is
O(size + depth × chunk) instead of O(depth × size); chunks ride raw frames
(zero msgpack encode/copies) whenever both ends negotiated them. See
TRANSFER_r10.json for the measured 3.8× aggregate over the r5 plane.

Usage:
    ref = ray_tpu.put(big_array)
    ray_tpu.util.object_transfer.broadcast_object(ref)   # all alive nodes
"""

from __future__ import annotations


def broadcast_object(ref, node_ids: list[str] | None = None, timeout: float = 600.0) -> int:
    """Replicate `ref`'s value into the object store of every target node.

    Returns the number of nodes newly pushed to. Raises ValueError for
    objects that never reached plasma (<= max_direct_call_object_size values
    live in the owner's in-process store; broadcasting those is meaningless).
    """
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    oid = ref.hex() if hasattr(ref, "hex") else str(ref)

    locs = cw.gcs.call("get_object_locations", {"object_id": oid})["locations"]
    have = {loc["node_id"] for loc in locs}
    if not have:
        raise ValueError(
            f"object {oid[:8]} has no plasma copy (small objects live in the "
            "owner's in-process store and are shipped inline; broadcast "
            "applies to ray_tpu.put() objects above the direct-call cutoff)"
        )
    nodes = cw.gcs.call("get_nodes")["nodes"]
    targets = [
        {"node_id": nid, "address": info["address"]}
        for nid, info in nodes.items()
        if info.get("state") == "ALIVE"
        and nid not in have
        and (node_ids is None or nid in node_ids)
    ]
    if not targets:
        return 0
    resp = cw.raylet.call(
        "broadcast_object", {"object_id": oid, "targets": targets, "timeout": timeout},
        timeout=timeout,
    )
    if not resp.get("ok"):
        raise RuntimeError(f"broadcast of {oid[:8]} failed: {resp.get('failed')}")
    return len(targets)
