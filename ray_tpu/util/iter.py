"""ParallelIterator — sharded iterators over actors.

Analog of the reference's ray.util.iter: ``from_items``/``from_range`` shard
a sequence across actor-held iterators; transforms (``for_each``/``filter``/
``batch``/``flatten``) are recorded lazily and applied shard-local on the
actors; ``gather_sync``/``gather_async`` pull results back.
"""

from __future__ import annotations

import ray_tpu


@ray_tpu.remote
class _ShardActor:
    def __init__(self, items: list):
        self._items = list(items)

    def run(self, transforms: list) -> list:
        it = iter(self._items)
        for kind, fn in transforms:
            if kind == "for_each":
                it = map(fn, it)
            elif kind == "filter":
                it = filter(fn, it)
            elif kind == "batch":
                it = _batched(it, fn)
            elif kind == "flatten":
                it = (x for item in it for x in item)
        return list(it)


def _batched(it, n: int):
    batch = []
    for x in it:
        batch.append(x)
        if len(batch) == n:
            yield batch
            batch = []
    if batch:
        yield batch


class ParallelIterator:
    def __init__(self, actors: list, transforms: list | None = None):
        self._actors = actors
        self._transforms = list(transforms or [])

    def num_shards(self) -> int:
        return len(self._actors)

    def _with(self, kind, fn):
        return ParallelIterator(self._actors, self._transforms + [(kind, fn)])

    def for_each(self, fn):
        return self._with("for_each", fn)

    def filter(self, fn):
        return self._with("filter", fn)

    def batch(self, n: int):
        return self._with("batch", n)

    def flatten(self):
        return self._with("flatten", None)

    def gather_sync(self):
        """Round-robin merge across shards, in shard order."""
        shard_results = ray_tpu.get([a.run.remote(self._transforms) for a in self._actors])
        out = []
        idx = [0] * len(shard_results)
        remaining = sum(len(s) for s in shard_results)
        while remaining:
            for i, shard in enumerate(shard_results):
                if idx[i] < len(shard):
                    out.append(shard[idx[i]])
                    idx[i] += 1
                    remaining -= 1
        return iter(out)

    def gather_async(self):
        """Yield per-shard results in completion order."""
        pending = {a.run.remote(self._transforms): a for a in self._actors}
        while pending:
            ready, _ = ray_tpu.wait(list(pending), num_returns=1)
            ref = ready[0]
            del pending[ref]
            yield from ray_tpu.get(ref)

    def take(self, n: int) -> list:
        out = []
        for x in self.gather_sync():
            out.append(x)
            if len(out) >= n:
                break
        return out

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        if self._transforms != other._transforms:
            raise ValueError("union requires identical transform chains")
        return ParallelIterator(self._actors + other._actors, self._transforms)


def from_items(items: list, num_shards: int = 2) -> ParallelIterator:
    shards = [items[i::num_shards] for i in range(num_shards)]
    return ParallelIterator([_ShardActor.remote(s) for s in shards])


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    return from_items(list(range(n)), num_shards)
