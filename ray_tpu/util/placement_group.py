"""Placement groups — atomic gang reservation of resource bundles.

Analog of the reference's placement group API (python/ray/util/placement_group.py:34,139)
backed by the GCS 2PC scheduler (gcs_placement_group_scheduler.h) and raylet
bundle accounting (placement_group_resource_manager.h).

TPU-first semantics: STRICT_PACK maps all bundles onto a single node — for TPU
scheduling that means one ICI domain, so a gang of actors placed in a
STRICT_PACK group can always materialise a `jax.sharding.Mesh` over ICI
without crossing DCN (SURVEY.md §2.3 / §7 guiding delta 2).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ray_tpu._private.ids import PlacementGroupID

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


@dataclass
class PlacementGroup:
    id: PlacementGroupID
    bundles: list
    strategy: str

    def ready(self, timeout: float | None = None):
        """Block until all bundles are reserved (analog of pg.ready())."""
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker()
        deadline = time.monotonic() + (timeout if timeout is not None else 3600.0)
        while time.monotonic() < deadline:
            resp = cw.gcs.call("get_placement_group", {"pg_id": self.id.hex()})
            if resp.get("found") and resp["info"]["state"] == "CREATED":
                return True
            time.sleep(0.05)
        from ray_tpu.exceptions import PlacementGroupUnavailableError

        raise PlacementGroupUnavailableError(f"placement group {self.id.hex()[:8]} not ready")

    def bundle_node(self, bundle_index: int) -> str | None:
        from ray_tpu._private import worker_context

        cw = worker_context.get_core_worker()
        resp = cw.gcs.call("get_placement_group", {"pg_id": self.id.hex()})
        if not resp.get("found"):
            return None
        return resp["info"]["bundle_nodes"][bundle_index]


def placement_group(bundles: list[dict], strategy: str = "PACK", name: str = "") -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be non-empty resource dicts")
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    pg_id = PlacementGroupID.from_random()
    cw.gcs.call(
        "create_placement_group",
        {
            "pg_id": pg_id.hex(),
            "bundles": bundles,
            "strategy": strategy,
            "name": name,
        },
    )
    return PlacementGroup(id=pg_id, bundles=bundles, strategy=strategy)


def remove_placement_group(pg: PlacementGroup):
    from ray_tpu._private import worker_context

    cw = worker_context.get_core_worker()
    cw.gcs.call("remove_placement_group", {"pg_id": pg.id.hex()})


def tpu_slice_placement_group(num_workers: int, chips_per_worker: int = 1) -> PlacementGroup:
    """Gang-reserve a TPU slice: one bundle per worker host, STRICT_PACK so
    the gang lands on one ICI domain (single-host multi-chip) — the schedulable
    unit an XLA collective world needs (SURVEY.md §7 hard part 1)."""
    bundles = [{"TPU": chips_per_worker} for _ in range(num_workers)]
    return placement_group(bundles, strategy="STRICT_PACK")
