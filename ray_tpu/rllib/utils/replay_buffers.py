"""Replay buffers (reference: rllib/utils/replay_buffers/replay_buffer.py and
prioritized_replay_buffer.py)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform FIFO replay buffer over SampleBatch rows."""

    def __init__(self, capacity: int = 100_000, seed: Optional[int] = None):
        self.capacity = capacity
        self._cols: dict = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch):
        n = batch.count
        if not self._cols:
            for k, v in batch.items():
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:], dtype=v.dtype)
        start = self._next
        first = min(n, self.capacity - start)
        for k, v in batch.items():
            self._cols[k][start : start + first] = v[:first]
            if first < n:
                self._cols[k][: n - first] = v[first:]
        self._next = (start + n) % self.capacity
        self._size = min(self.capacity, self._size + n)

    def sample(self, num_items: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, num_items)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference:
    prioritized_replay_buffer.py) with importance-sampling weights."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6, beta: float = 0.4, seed: Optional[int] = None):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros(capacity, dtype=np.float64)
        self._max_priority = 1.0
        self._last_idx: Optional[np.ndarray] = None

    def add(self, batch: SampleBatch):
        n = batch.count
        start = self._next
        super().add(batch)
        first = min(n, self.capacity - start)
        self._priorities[start : start + first] = self._max_priority
        if first < n:
            self._priorities[: n - first] = self._max_priority

    def sample(self, num_items: int) -> SampleBatch:
        prios = self._priorities[: self._size] ** self.alpha
        probs = prios / prios.sum()
        idx = self._rng.choice(self._size, num_items, p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        weights = weights / weights.max()
        self._last_idx = idx
        out = SampleBatch({k: v[idx] for k, v in self._cols.items()})
        out["weights"] = weights.astype(np.float32)
        return out

    def update_priorities(self, td_errors: np.ndarray, eps: float = 1e-6):
        assert self._last_idx is not None
        self.update_priorities_at(self._last_idx, td_errors, eps)

    # Explicit-index variants: distributed consumers (Ape-X replay shards)
    # interleave sampling rounds, so the implicit last-sample protocol above
    # cannot be relied on across calls.
    def sample_with_indices(self, num_items: int):
        out = self.sample(num_items)
        return out, np.asarray(self._last_idx)

    def update_priorities_at(self, idx: np.ndarray, td_errors: np.ndarray, eps: float = 1e-6):
        prios = np.abs(np.asarray(td_errors)) + eps
        self._priorities[np.asarray(idx)] = prios
        self._max_priority = max(self._max_priority, float(prios.max()))


class ColumnReplayBuffer:
    """Flat columnar ring buffer for dict transitions: arrays are allocated
    lazily from the first item's shapes/dtypes, writes wrap around, sampling
    is uniform. Shared by MADDPG and SlateQ (their transitions are nested
    fixed-shape dicts rather than SampleBatch rows)."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._data: dict | None = None
        self._n = 0
        self._pos = 0
        self._rng = np.random.default_rng(seed)

    def add(self, item: dict):
        if self._data is None:
            self._data = {
                k: np.zeros((self.capacity,) + np.asarray(v).shape, np.asarray(v).dtype)
                for k, v in item.items()
            }
        for k, v in item.items():
            self._data[k][self._pos] = v
        self._pos = (self._pos + 1) % self.capacity
        self._n = min(self._n + 1, self.capacity)

    def __len__(self):
        return self._n

    def sample(self, n: int) -> dict:
        if self._n == 0:
            raise ValueError(
                "ColumnReplayBuffer.sample() on an empty buffer; add() at "
                "least one item first (callers usually gate on learning_starts)"
            )
        idx = self._rng.integers(0, self._n, n)
        return {k: v[idx] for k, v in self._data.items()}
