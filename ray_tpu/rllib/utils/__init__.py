from ray_tpu.rllib.utils.replay_buffers import PrioritizedReplayBuffer, ReplayBuffer  # noqa: F401
