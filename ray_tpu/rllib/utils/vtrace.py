"""V-trace targets (Espeholt et al. 2018) — shared by IMPALA and APPO.

Reference: rllib/algorithms/impala/vtrace_torch.py (the reference keeps
per-framework copies; here one jax implementation serves both algorithms):
    rho_t = min(rho_bar, pi(a|s)/mu(a|s));  c_t = min(c_bar, rho_t)
    delta_t = rho_t (r_t + gamma V(s_{t+1}) - V(s_t))
    vs_t = V(s_t) + delta_t + gamma c_t (vs_{t+1} - V(s_{t+1}))
    pg_adv_t = rho_t (r_t + gamma vs_{t+1} - V(s_t))
computed with a reverse lax.scan over a flat batch of concatenated rollout
fragments; episode ends (dones) and fragment cuts reset the recursion, with
bootstrap values riding in the batch (NEXT_VF_PREDS).
"""

from __future__ import annotations


def vtrace(values_sg, next_values, logp, behavior_logp, rewards, nonterminal, cuts,
           gamma: float, rho_bar: float, c_bar: float):
    """Returns (vs, pg_adv, rho); vs carries no gradient into values_sg
    (pass stop_gradient'ed values), pg_adv is stop-gradient'ed."""
    import jax
    import jax.numpy as jnp

    carry_mask = nonterminal * (1.0 - cuts)
    rho = jnp.minimum(rho_bar, jnp.exp(logp - behavior_logp))
    rho = jax.lax.stop_gradient(rho)
    c = jnp.minimum(c_bar, rho)
    deltas = rho * (rewards + gamma * next_values - values_sg)

    def back(carry, inp):
        delta_t, c_t, mask = inp
        acc = delta_t + gamma * c_t * mask * carry
        return acc, acc

    _, vs_minus_v_rev = jax.lax.scan(
        back, jnp.zeros((), values_sg.dtype), (deltas[::-1], c[::-1], carry_mask[::-1])
    )
    vs = values_sg + vs_minus_v_rev[::-1]
    # vs_{t+1}: next row's vs inside a fragment; the bootstrap value at a
    # fragment cut; 0 past a terminal.
    vs_shift = jnp.concatenate([vs[1:], vs[-1:]])
    vs_next = jnp.where(cuts > 0, next_values, vs_shift) * nonterminal
    pg_adv = rho * (rewards + gamma * vs_next - values_sg)
    return vs, jax.lax.stop_gradient(pg_adv), rho
