"""Algorithm callbacks.

Reference: rllib/algorithms/callbacks.py (DefaultCallbacks): user hook
points invoked by the Algorithm at lifecycle milestones. The subset here
covers the hooks the runtime actually fires — init, train-result,
checkpoint save/load, evaluation — each receiving the algorithm so user
code can reach workers/weights/config.
"""

from __future__ import annotations


class DefaultCallbacks:
    """Subclass and override; pass the CLASS via
    ``config.callbacks(MyCallbacks)`` (reference: AlgorithmConfig.callbacks)."""

    def on_algorithm_init(self, *, algorithm) -> None:
        pass

    def on_train_result(self, *, algorithm, result: dict) -> None:
        """Called after every train(); may mutate `result` in place."""

    def on_evaluate_end(self, *, algorithm, evaluation_metrics: dict) -> None:
        pass

    def on_checkpoint_saved(self, *, algorithm, checkpoint) -> None:
        pass

    def on_checkpoint_loaded(self, *, algorithm) -> None:
        pass


def make_callbacks(callbacks_class) -> DefaultCallbacks:
    if callbacks_class is None:
        return DefaultCallbacks()
    cb = callbacks_class() if isinstance(callbacks_class, type) else callbacks_class
    assert isinstance(cb, DefaultCallbacks), (
        "callbacks must subclass ray_tpu.rllib.callbacks.DefaultCallbacks"
    )
    return cb
