"""Meta-RL task environments.

Reference: the MAML/MBMPO envs in rllib/env/apis/task_settable_env.py
(TaskSettableEnv: sample_tasks/set_task/get_task) and the point-navigation
envs the reference's MAML tuned examples use. A task-settable env exposes a
family of MDPs sharing dynamics/observation structure; meta-learners train
for fast adaptation ACROSS the family rather than performance on one member.

PointGoalEnv additionally exposes a pure-JAX ``reward_fn`` and
``transition_fn`` so model-based algorithms (MBMPO) can run imagined
rollouts entirely inside jit — the TPU-native analog of the reference's
model-ensemble rollout workers.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

try:
    import gymnasium as gym
except ImportError:  # pragma: no cover
    gym = None


class TaskSettableEnv(gym.Env if gym else object):
    """Protocol: an env whose MDP is switchable among a task family
    (reference: rllib/env/apis/task_settable_env.py)."""

    def sample_tasks(self, n_tasks: int) -> List:
        raise NotImplementedError

    def set_task(self, task) -> None:
        raise NotImplementedError

    def get_task(self):
        raise NotImplementedError


class PointGoalEnv(TaskSettableEnv):
    """2-D point navigation; the task is the (hidden) goal position.

    The goal is NOT in the observation — a fixed policy cannot know where to
    go, so pre-adaptation return is capped and any post-adaptation gain is
    attributable to adaptation from task rollouts. Episodes run a fixed
    ``horizon`` (no early termination: uniform batch shapes keep the
    meta-update stackable/vmappable over tasks).
    """

    metadata = {"render_modes": []}

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.horizon = int(config.get("horizon", 20))
        self.goal_radius = float(config.get("goal_radius", 1.0))
        self.step_size = float(config.get("step_size", 0.15))
        self._seed = int(config.get("seed", 0))
        self._rng = np.random.default_rng(self._seed)
        self.observation_space = gym.spaces.Box(-np.inf, np.inf, (2,), np.float32)
        self.action_space = gym.spaces.Box(-1.0, 1.0, (2,), np.float32)
        self._goal = np.array([self.goal_radius, 0.0], np.float32)
        self._pos = np.zeros(2, np.float32)
        self._t = 0

    # -- task API ---------------------------------------------------------
    def sample_tasks(self, n_tasks: int) -> List[np.ndarray]:
        angles = self._rng.uniform(0, 2 * np.pi, n_tasks)
        return [
            np.array([np.cos(a), np.sin(a)], np.float32) * self.goal_radius
            for a in angles
        ]

    def set_task(self, task) -> None:
        self._goal = np.asarray(task, np.float32)

    def get_task(self):
        return self._goal

    # -- gym API ----------------------------------------------------------
    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = np.zeros(2, np.float32)
        self._t = 0
        return self._pos.copy(), {}

    def step(self, action):
        a = np.clip(np.asarray(action, np.float32), -1.0, 1.0)
        self._pos = self._pos + self.step_size * a
        self._t += 1
        reward = -float(np.linalg.norm(self._pos - self._goal))
        truncated = self._t >= self.horizon
        return self._pos.copy(), reward, False, truncated, {}

    # -- pure-JAX dynamics (for imagined rollouts under jit) --------------
    @property
    def step_scale(self) -> float:
        return self.step_size

    @staticmethod
    def reward_fn(obs, action, next_obs, task):
        """Per-step reward as a jax-traceable function of the TRANSITION —
        the analog of the reference MBMPO envs' ``reward(obs, act, obs_next)``
        (rllib/algorithms/mbmpo/mbmpo.py requires envs expose it)."""
        import jax.numpy as jnp

        return -jnp.linalg.norm(next_obs - task, axis=-1)

    @staticmethod
    def transition_fn(obs, action, step_size: float = 0.15):
        """True dynamics (used by tests to validate learned models)."""
        import jax.numpy as jnp

        return obs + step_size * jnp.clip(action, -1.0, 1.0)
