"""Two-player zero-sum board-game envs for tree-search self-play.

Reference: the reference's LeelaChessZero (rllib/algorithms/leela_chess_zero/
leela_chess_zero.py) binds AlphaZero-style MCTS self-play to chess via a
MultiAgentEnv wrapper around python-chess. The algorithm only needs a
board protocol: alternating moves, legal-action masks, state clone/restore
for search simulations, terminal outcome from the mover's perspective.
This module defines that protocol plus TicTacToe (the in-tree test board —
chess itself needs an external move-generator the image doesn't carry; any
env implementing BoardGameEnv plugs into the same algorithm).

Protocol:
    obs = env.reset() -> observation from the CURRENT player's perspective
    obs, reward, done = env.step(action)
        reward is from the perspective of the player WHO JUST MOVED
        (+1 win, 0 draw/ongoing); after step, obs flips to the next player.
    env.legal_actions() -> bool mask [n_actions]
    env.get_state() / env.set_state(s) -> search simulation support
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

try:
    import gymnasium as gym
except ImportError:  # pragma: no cover
    gym = None

_WIN_LINES = [
    (0, 1, 2), (3, 4, 5), (6, 7, 8),
    (0, 3, 6), (1, 4, 7), (2, 5, 8),
    (0, 4, 8), (2, 4, 6),
]


class BoardGameEnv:
    """Protocol base; see module docstring."""

    observation_space: "gym.spaces.Box"
    action_space: "gym.spaces.Discrete"

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool]:
        raise NotImplementedError

    def legal_actions(self) -> np.ndarray:
        raise NotImplementedError

    def observe(self) -> np.ndarray:
        """Current position from the current player's perspective (search
        needs to re-observe after set_state)."""
        raise NotImplementedError

    def get_state(self):
        raise NotImplementedError

    def set_state(self, state) -> None:
        raise NotImplementedError

    def close(self):
        pass


class TicTacToeEnv(BoardGameEnv):
    """3x3 tic-tac-toe. Observation: 9 cells from the current player's
    perspective (+1 mine, -1 opponent's, 0 empty)."""

    def __init__(self, config: Optional[dict] = None):
        self.observation_space = gym.spaces.Box(-1.0, 1.0, (9,), np.float32)
        self.action_space = gym.spaces.Discrete(9)
        self._board = np.zeros(9, np.int8)  # +1 = player0, -1 = player1
        self._player = 1  # +1 moves first

    def _obs(self) -> np.ndarray:
        return (self._board * self._player).astype(np.float32)

    def reset(self) -> np.ndarray:
        self._board = np.zeros(9, np.int8)
        self._player = 1
        return self._obs()

    def legal_actions(self) -> np.ndarray:
        return self._board == 0

    def observe(self) -> np.ndarray:
        return self._obs()

    def step(self, action: int):
        assert self._board[action] == 0, f"illegal move {action}"
        self._board[action] = self._player
        mover = self._player
        for a, b, c in _WIN_LINES:
            if self._board[a] == self._board[b] == self._board[c] == mover:
                self._player = -mover
                return self._obs(), 1.0, True
        self._player = -mover
        if not (self._board == 0).any():
            return self._obs(), 0.0, True  # draw
        return self._obs(), 0.0, False

    def get_state(self):
        return (self._board.copy(), self._player)

    def set_state(self, state) -> None:
        board, player = state
        self._board = board.copy()
        self._player = player
