"""ExternalEnv — inverted-control environments.

Reference: rllib/env/external_env.py:23 — the ENVIRONMENT owns the loop
(a game server, robot, or web client decides when steps happen) and the
algorithm is a service it queries: ``start_episode`` / ``get_action`` /
``log_returns`` / ``end_episode``. The user subclasses ``ExternalEnv``
and implements ``run()``, which executes on its own thread for the life
of the algorithm.

Completed episodes accumulate as SampleBatches, the same contract
``PolicyServerInput`` uses (policy_server.py), so any algorithm that can
consume collected batches (DQN-family via replay, MARWIL/BC/CQL readers)
trains directly from an external sim; ``ExternalEnvRunner`` is the small
pump that drives sampling for them.
"""

from __future__ import annotations

import queue
import threading
import uuid
from typing import Callable, Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    EPS_ID,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)


class _EpisodeState:
    def __init__(self, eid: str, idx: int):
        self.eid = eid
        self.idx = idx
        self.obs: list = []
        self.actions: list = []
        self.rewards: list = []
        self.pending_reward = 0.0


class ExternalEnv(threading.Thread):
    """Subclass and implement ``run()`` (reference: external_env.py:23).

    Inside ``run()`` call:
      - ``eid = self.start_episode()``
      - ``action = self.get_action(eid, obs)``   (served by the live policy)
      - ``self.log_returns(eid, reward)``
      - ``self.end_episode(eid, final_obs)``
    """

    def __init__(self, action_space=None, observation_space=None):
        super().__init__(daemon=True, name=type(self).__name__)
        self.action_space = action_space
        self.observation_space = observation_space
        self._policy_fn: Optional[Callable] = None
        self._policy_ready = threading.Event()
        self._episodes: dict[str, _EpisodeState] = {}
        self._eps_counter = 0
        self._completed: queue.Queue = queue.Queue()
        self._lock = threading.Lock()

    # -- wiring (called by the runner/algorithm side) --------------------

    def set_policy_fn(self, fn: Callable):
        """fn(obs: np.ndarray) -> action. Installed by the runner before
        the env thread may request actions."""
        self._policy_fn = fn
        self._policy_ready.set()

    # -- user-facing API (called from run()) -----------------------------

    def run(self):  # pragma: no cover - subclass responsibility
        raise NotImplementedError

    def start_episode(self, episode_id: Optional[str] = None) -> str:
        eid = episode_id or uuid.uuid4().hex
        with self._lock:
            self._episodes[eid] = _EpisodeState(eid, self._eps_counter)
            self._eps_counter += 1
        return eid

    def get_action(self, episode_id: str, observation):
        self._policy_ready.wait()
        ep = self._episodes[episode_id]
        obs = np.asarray(observation, dtype=np.float32)
        action = self._policy_fn(obs)
        with self._lock:
            if ep.obs:
                ep.rewards.append(ep.pending_reward)
            ep.pending_reward = 0.0
            ep.obs.append(obs)
            ep.actions.append(action)
        return action

    def log_action(self, episode_id: str, observation, action):
        """Off-policy logging: the external system chose `action` itself."""
        ep = self._episodes[episode_id]
        with self._lock:
            if ep.obs:
                ep.rewards.append(ep.pending_reward)
            ep.pending_reward = 0.0
            ep.obs.append(np.asarray(observation, dtype=np.float32))
            ep.actions.append(action)

    def log_returns(self, episode_id: str, reward: float):
        ep = self._episodes[episode_id]
        with self._lock:
            ep.pending_reward += float(reward)

    def end_episode(self, episode_id: str, observation=None):
        with self._lock:
            ep = self._episodes.pop(episode_id, None)
        if ep is None or not ep.obs:
            return
        ep.rewards.append(ep.pending_reward)
        obs = np.stack(ep.obs)
        final = (
            np.asarray(observation, dtype=np.float32)[None]
            if observation is not None
            else obs[-1:]
        )
        next_obs = np.concatenate([obs[1:], final])
        n = len(ep.obs)
        dones = np.zeros(n, dtype=np.float32)
        dones[-1] = 1.0
        batch = SampleBatch({
            OBS: obs,
            ACTIONS: np.asarray(ep.actions),
            REWARDS: np.asarray(ep.rewards, dtype=np.float32),
            NEXT_OBS: next_obs,
            DONES: dones,
            EPS_ID: np.full(n, ep.idx, dtype=np.int64),
        })
        self._completed.put(batch)

    # -- consumption (runner side) ---------------------------------------

    def poll_batch(self, timeout: float = 1.0) -> Optional[SampleBatch]:
        try:
            return self._completed.get(timeout=timeout)
        except queue.Empty:
            return None


class ExternalEnvRunner:
    """Pumps an ExternalEnv's completed episodes into an off-policy
    algorithm's replay buffer and serves its live policy for get_action
    (reference: ExternalEnv rollout integration in rollout_worker.py)."""

    def __init__(self, env: ExternalEnv, algorithm):
        self.env = env
        self.algorithm = algorithm
        env.set_policy_fn(lambda obs: algorithm.compute_single_action(obs, explore=True))
        if not env.is_alive():
            env.start()

    def collect(self, min_steps: int, timeout: float = 30.0) -> int:
        """Blocks until ≥min_steps env steps are ingested; returns steps."""
        import time as _time

        steps = 0
        deadline = _time.monotonic() + timeout
        while steps < min_steps and _time.monotonic() < deadline:
            batch = self.env.poll_batch(timeout=0.5)
            if batch is None:
                continue
            # The DQN-family replay stores transition columns only; EPS_ID
            # (kept on poll_batch() for offline-dataset consumers) would
            # diverge from a buffer initialized by internal rollouts.
            replay = SampleBatch({k: v for k, v in batch.items() if k != EPS_ID})
            self.algorithm.buffer.add(replay)
            n = len(batch[REWARDS])
            steps += n
            self.algorithm._timesteps_total += n
            ep_reward = float(np.sum(batch[REWARDS]))
            window = getattr(self.algorithm, "_episode_reward_window", None)
            if window is not None:
                window.append(ep_reward)
                del window[:-100]
        return steps
