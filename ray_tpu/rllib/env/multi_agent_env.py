"""MultiAgentEnv (analog of reference rllib/env/multi_agent_env.py).

Dict-keyed multi-agent episodes with the gymnasium 5-tuple convention:
``step(action_dict) -> (obs, rewards, terminateds, truncateds, infos)``, each
a per-agent dict; ``terminateds["__all__"]`` ends the episode. Training uses
parameter sharing (one policy for every agent — the reference's default
policy mapping): the rollout layer flattens each agent into a vector-env
slot, so GAE, the learners, and the algorithms are agent-count-agnostic.
Fixed agent sets (``possible_agents``) are assumed — the reference's dynamic
agent turnover is out of scope for the shared-policy path.
"""

from __future__ import annotations

from typing import Optional


class MultiAgentEnv:
    """Subclass and define possible_agents, observation_space, action_space
    (shared across agents), reset(), step(action_dict)."""

    possible_agents: list = []

    @property
    def observation_space(self):
        raise NotImplementedError

    @property
    def action_space(self):
        raise NotImplementedError

    def reset(self, *, seed: Optional[int] = None):
        """-> (obs_dict, info_dict)"""
        raise NotImplementedError

    def step(self, action_dict: dict):
        """-> (obs, rewards, terminateds, truncateds, infos) per-agent dicts;
        terminateds/truncateds may carry the "__all__" key."""
        raise NotImplementedError

    def close(self):
        pass


def make_multi_agent(env_spec, num_agents: int = 2):
    """Lift a single-agent gym env into an N-agent MultiAgentEnv of
    independent copies (reference: rllib/env/multi_agent_env.py
    make_multi_agent) — each agent steps its own instance; the episode ends
    when every copy is done."""

    class _IndependentCopies(MultiAgentEnv):
        # Each agent's copy auto-resets on termination, so every agent is
        # live every step — the property the slot-flattening rollout path
        # needs (see MultiAgentVectorEnv).
        agent_auto_reset = True

        def __init__(self, config: Optional[dict] = None):
            config = dict(config or {})
            n = int(config.pop("num_agents", num_agents))
            self.possible_agents = [f"agent_{i}" for i in range(n)]
            self._envs = {}
            for aid in self.possible_agents:
                if callable(env_spec):
                    self._envs[aid] = env_spec(config)
                else:
                    import gymnasium as gym

                    self._envs[aid] = gym.make(env_spec)
            self._done = {aid: False for aid in self.possible_agents}

        @property
        def observation_space(self):
            return next(iter(self._envs.values())).observation_space

        @property
        def action_space(self):
            return next(iter(self._envs.values())).action_space

        def reset(self, *, seed=None):
            obs, infos = {}, {}
            for i, (aid, env) in enumerate(self._envs.items()):
                # Large per-agent stride so (env seed + agent index) never
                # collides with a sibling env's agents.
                o, info = env.reset(seed=None if seed is None else seed + i * 100003)
                obs[aid], infos[aid] = o, info
                self._done[aid] = False
            return obs, infos

        def step(self, action_dict):
            obs, rewards, terms, truncs, infos = {}, {}, {}, {}, {}
            for aid, action in action_dict.items():
                o, r, term, trunc, info = self._envs[aid].step(action)
                info = dict(info)
                if term or trunc:
                    # The terminal observation must survive the auto-reset —
                    # truncated-episode bootstrapping reads it.
                    info["final_observation"] = o
                    o, _ = self._envs[aid].reset()
                obs[aid], rewards[aid] = o, r
                terms[aid], truncs[aid], infos[aid] = term, trunc, info
            terms["__all__"] = False
            truncs["__all__"] = False
            return obs, rewards, terms, truncs, infos

        def close(self):
            for env in self._envs.values():
                try:
                    env.close()
                except Exception:
                    pass

    return _IndependentCopies
