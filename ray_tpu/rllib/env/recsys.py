"""Synthetic slate-recommendation environment (RecSim-style).

Stands in for the reference's RecSim interest-evolution environment
(rllib/env/wrappers/recsim.py + google/recsim): the real RecSim package is
not in this image, so SlateQ trains and tests against this faithful
miniature:

- USER: a unit-norm interest vector over ``num_topics``, evolving toward
  the topics of clicked documents; a session-length budget ends episodes.
- DOCS: each step presents ``num_candidates`` documents with random topic
  feature vectors (unit-norm) and per-doc quality.
- CHOICE: the user clicks at most one slate item via a conditional
  logistic model over interest-document affinity, with a no-click option.
- REWARD: clicked document's engagement (affinity + quality); clicking
  also evolves the interest state — myopic slates (pure quality) differ
  from long-term-optimal ones, which is exactly the structure SlateQ's
  decomposition exploits.

Observation: concatenation of the interest vector and all candidate
feature rows (reference: RecSim observation dict, flattened). Action: a
slate — ``slate_size`` distinct candidate indices.
"""

from __future__ import annotations

import numpy as np


class SlateRecEnv:
    def __init__(self, config: dict | None = None):
        config = dict(config or {})
        self.num_topics = int(config.get("num_topics", 6))
        self.num_candidates = int(config.get("num_candidates", 10))
        self.slate_size = int(config.get("slate_size", 2))
        self.session_budget = int(config.get("session_budget", 40))
        self.no_click_mass = float(config.get("no_click_mass", 1.0))
        self.interest_lr = float(config.get("interest_lr", 0.2))
        self._rng = np.random.default_rng(config.get("seed", 0))
        self.obs_dim = self.num_topics + self.num_candidates * (self.num_topics + 1)

    # gym-ish metadata used by SlateQ's setup
    @property
    def observation_dim(self) -> int:
        return self.obs_dim

    def _sample_docs(self):
        feats = self._rng.normal(size=(self.num_candidates, self.num_topics)).astype(np.float32)
        feats /= np.linalg.norm(feats, axis=1, keepdims=True) + 1e-8
        quality = self._rng.uniform(0.0, 1.0, self.num_candidates).astype(np.float32)
        return feats, quality

    def _obs(self):
        return np.concatenate(
            [self.interest, np.concatenate([self.doc_feats, self.doc_quality[:, None]], 1).ravel()]
        ).astype(np.float32)

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self.interest = self._rng.normal(size=self.num_topics).astype(np.float32)
        self.interest /= np.linalg.norm(self.interest) + 1e-8
        self.budget = self.session_budget
        self.doc_feats, self.doc_quality = self._sample_docs()
        return self._obs(), {}

    def step(self, slate):
        slate = list(dict.fromkeys(int(i) for i in slate))[: self.slate_size]
        affinity = self.doc_feats[slate] @ self.interest  # [k]
        # Conditional logistic choice with a no-click alternative.
        scores = np.exp(np.concatenate([affinity, [np.log(self.no_click_mass + 1e-8)]]))
        probs = scores / scores.sum()
        choice = self._rng.choice(len(slate) + 1, p=probs)
        reward = 0.0
        clicked = -1
        if choice < len(slate):
            doc = slate[choice]
            clicked = doc
            engagement = float(affinity[choice] + self.doc_quality[doc])
            reward = max(engagement, 0.0)
            # Interest evolves TOWARD the clicked topic mix.
            self.interest = (1 - self.interest_lr) * self.interest + self.interest_lr * self.doc_feats[doc]
            self.interest /= np.linalg.norm(self.interest) + 1e-8
        self.budget -= 1
        done = self.budget <= 0
        self.doc_feats, self.doc_quality = self._sample_docs()
        return self._obs(), reward, done, False, {"clicked": clicked}

    def close(self):
        pass
