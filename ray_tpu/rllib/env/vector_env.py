"""Vectorized environment layer.

Reference: rllib/env/vector_env.py (VectorEnv / _VectorizedGymEnv) with the
gymnasium API. Environments step on CPU rollout actors; the learner never
touches them — the same split as the reference (env stepping on CPU actors,
SGD on accelerator learners, §3.6 of the survey).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import numpy as np


class EnvContext(dict):
    """Env config dict + worker/vector indices (reference: env/env_context.py)."""

    def __init__(self, config: dict, worker_index: int = 0, vector_index: int = 0):
        super().__init__(config or {})
        self.worker_index = worker_index
        self.vector_index = vector_index


def _make_env(env_spec, ctx: EnvContext):
    if callable(env_spec):
        return env_spec(ctx)
    if isinstance(env_spec, str):
        import gymnasium as gym

        return gym.make(env_spec)
    raise ValueError(f"cannot build env from {env_spec!r}")


class VectorEnv:
    """N sub-envs stepped as a batch, with auto-reset on termination."""

    def __init__(self, env_spec, num_envs: int, config: Optional[dict] = None, worker_index: int = 0, seed: Optional[int] = None):
        self.envs = [
            _make_env(env_spec, EnvContext(config or {}, worker_index, i))
            for i in range(num_envs)
        ]
        self.num_envs = num_envs
        self._eps_ids = np.arange(num_envs, dtype=np.int64)
        self._next_eps_id = num_envs
        self._episode_rewards = np.zeros(num_envs, dtype=np.float64)
        self._episode_lens = np.zeros(num_envs, dtype=np.int64)
        self.completed_rewards: List[float] = []
        self.completed_lens: List[int] = []
        obs = []
        for i, env in enumerate(self.envs):
            o, _info = env.reset(seed=None if seed is None else seed + i)
            obs.append(o)
        self._obs = np.stack(obs)

    @property
    def observation_space(self):
        return self.envs[0].observation_space

    @property
    def action_space(self):
        return self.envs[0].action_space

    def current_obs(self) -> np.ndarray:
        return self._obs

    def eps_ids(self) -> np.ndarray:
        return self._eps_ids.copy()

    def step(self, actions: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray, list]:
        """Step every sub-env; returns (next_obs, rewards, dones, infos).
        Terminated/truncated envs auto-reset; `dones` marks the boundary."""
        next_obs, rewards, dones, infos = [], [], [], []
        for i, env in enumerate(self.envs):
            o, r, terminated, truncated, info = env.step(np.asarray(actions[i]))
            done = bool(terminated or truncated)
            # Truncation vs termination matters to off-policy bootstrapping
            # (a time-limit cut must still bootstrap V/Q(s')), so the split
            # flags and the pre-reset observation ride in the info dict.
            info = dict(info)
            info["terminated"] = bool(terminated)
            info["truncated"] = bool(truncated)
            self._episode_rewards[i] += float(r)
            self._episode_lens[i] += 1
            if done:
                info["final_observation"] = o
                self.completed_rewards.append(float(self._episode_rewards[i]))
                self.completed_lens.append(int(self._episode_lens[i]))
                self._episode_rewards[i] = 0.0
                self._episode_lens[i] = 0
                self._eps_ids[i] = self._next_eps_id
                self._next_eps_id += 1
                o, _ = env.reset()
            next_obs.append(o)
            rewards.append(float(r))
            dones.append(done)
            infos.append(info)
        self._obs = np.stack(next_obs)
        return self._obs, np.asarray(rewards, np.float32), np.asarray(dones), infos

    def pop_episode_stats(self) -> Tuple[List[float], List[int]]:
        r, l = self.completed_rewards, self.completed_lens
        self.completed_rewards, self.completed_lens = [], []
        return r, l

    def close(self):
        for env in self.envs:
            try:
                env.close()
            except Exception:
                pass


class MultiAgentVectorEnv:
    """Slot-flattened multi-agent stepping: every (env, agent) pair is one
    vector slot, so the single-policy rollout path (GAE over fragments,
    shared parameters — the reference's default policy mapping) works
    unchanged on MultiAgentEnvs.

    Two supported termination shapes (see multi_agent_env.py):
    - ``agent_auto_reset`` envs keep every agent live (independent copies);
    - lockstep envs end all agents together via ``terminateds["__all__"]``.
    Envs where agents die at different times without auto-reset are not
    representable as fixed slots; use lockstep design or the wrapper.
    """

    def __init__(self, env_spec, num_envs: int, config: Optional[dict] = None,
                 worker_index: int = 0, seed: Optional[int] = None):
        self.envs = [
            _make_env(env_spec, EnvContext(config or {}, worker_index, i))
            for i in range(num_envs)
        ]
        self.agents = list(self.envs[0].possible_agents)
        self.n_agents = len(self.agents)
        self.num_envs = num_envs * self.n_agents  # slots
        self._auto = bool(getattr(self.envs[0], "agent_auto_reset", False))
        self._eps_ids = np.arange(self.num_envs, dtype=np.int64)
        self._next_eps_id = self.num_envs
        self._episode_rewards = np.zeros(self.num_envs, dtype=np.float64)
        self._episode_lens = np.zeros(self.num_envs, dtype=np.int64)
        self.completed_rewards: List[float] = []
        self.completed_lens: List[int] = []
        obs = []
        for i, env in enumerate(self.envs):
            # Stride env seeds so per-agent offsets inside one env can't
            # collide with a sibling env's agents.
            od, _ = env.reset(seed=None if seed is None else seed + i * 1000003)
            obs += [od[a] for a in self.agents]
        self._obs = np.stack(obs)

    @property
    def observation_space(self):
        return self.envs[0].observation_space

    @property
    def action_space(self):
        return self.envs[0].action_space

    def current_obs(self) -> np.ndarray:
        return self._obs

    def eps_ids(self) -> np.ndarray:
        return self._eps_ids.copy()

    def _slot(self, env_i: int, agent_i: int) -> int:
        return env_i * self.n_agents + agent_i

    def step(self, actions: np.ndarray):
        next_obs = [None] * self.num_envs
        rewards = np.zeros(self.num_envs, np.float32)
        dones = np.zeros(self.num_envs, bool)
        infos: list = [{} for _ in range(self.num_envs)]
        for e, env in enumerate(self.envs):
            action_dict = {
                a: np.asarray(actions[self._slot(e, i)]) for i, a in enumerate(self.agents)
            }
            od, rd, td, cd, infod = env.step(action_dict)
            all_done = bool(td.get("__all__", False) or cd.get("__all__", False))
            if all_done and not self._auto:
                reset_obs, _ = env.reset()
            for i, a in enumerate(self.agents):
                s = self._slot(e, i)
                r = float(rd.get(a, 0.0))
                done = bool(td.get(a, False) or cd.get(a, False) or all_done)
                rewards[s] = r
                dones[s] = done
                info = dict(infod.get(a, {}))
                info["terminated"] = bool(td.get(a, False) or (all_done and not cd.get(a, False)))
                info["truncated"] = bool(cd.get(a, False))
                self._episode_rewards[s] += r
                self._episode_lens[s] += 1
                if done:
                    # Prefer the env-provided terminal obs (auto-resetting
                    # envs already replaced od[a] with the fresh episode's
                    # first obs).
                    info.setdefault("final_observation", od.get(a, self._obs[s]))
                    self.completed_rewards.append(float(self._episode_rewards[s]))
                    self.completed_lens.append(int(self._episode_lens[s]))
                    self._episode_rewards[s] = 0.0
                    self._episode_lens[s] = 0
                    self._eps_ids[s] = self._next_eps_id
                    self._next_eps_id += 1
                if all_done and not self._auto:
                    next_obs[s] = reset_obs[a]
                else:
                    next_obs[s] = od.get(a, self._obs[s])
                infos[s] = info
        self._obs = np.stack(next_obs)
        return self._obs, rewards, dones, infos

    def pop_episode_stats(self):
        r, l = self.completed_rewards, self.completed_lens
        self.completed_rewards, self.completed_lens = [], []
        return r, l

    def close(self):
        for env in self.envs:
            try:
                env.close()
            except Exception:
                pass


def make_vector_env(env_spec, num_envs: int, config: Optional[dict] = None,
                    worker_index: int = 0, seed: Optional[int] = None):
    """VectorEnv for gym envs, MultiAgentVectorEnv for MultiAgentEnvs
    (probed by building one instance)."""
    from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv

    probe = _make_env(env_spec, EnvContext(config or {}, worker_index, 0))
    is_multi = isinstance(probe, MultiAgentEnv)
    try:
        probe.close()
    except Exception:
        pass
    cls = MultiAgentVectorEnv if is_multi else VectorEnv
    return cls(env_spec, num_envs, config, worker_index, seed=seed)
