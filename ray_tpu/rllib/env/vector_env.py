"""Vectorized environment layer.

Reference: rllib/env/vector_env.py (VectorEnv / _VectorizedGymEnv) with the
gymnasium API. Environments step on CPU rollout actors; the learner never
touches them — the same split as the reference (env stepping on CPU actors,
SGD on accelerator learners, §3.6 of the survey).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import numpy as np


class EnvContext(dict):
    """Env config dict + worker/vector indices (reference: env/env_context.py)."""

    def __init__(self, config: dict, worker_index: int = 0, vector_index: int = 0):
        super().__init__(config or {})
        self.worker_index = worker_index
        self.vector_index = vector_index


def _make_env(env_spec, ctx: EnvContext):
    if callable(env_spec):
        return env_spec(ctx)
    if isinstance(env_spec, str):
        import gymnasium as gym

        return gym.make(env_spec)
    raise ValueError(f"cannot build env from {env_spec!r}")


class VectorEnv:
    """N sub-envs stepped as a batch, with auto-reset on termination."""

    def __init__(self, env_spec, num_envs: int, config: Optional[dict] = None, worker_index: int = 0, seed: Optional[int] = None):
        self.envs = [
            _make_env(env_spec, EnvContext(config or {}, worker_index, i))
            for i in range(num_envs)
        ]
        self.num_envs = num_envs
        self._eps_ids = np.arange(num_envs, dtype=np.int64)
        self._next_eps_id = num_envs
        self._episode_rewards = np.zeros(num_envs, dtype=np.float64)
        self._episode_lens = np.zeros(num_envs, dtype=np.int64)
        self.completed_rewards: List[float] = []
        self.completed_lens: List[int] = []
        obs = []
        for i, env in enumerate(self.envs):
            o, _info = env.reset(seed=None if seed is None else seed + i)
            obs.append(o)
        self._obs = np.stack(obs)

    @property
    def observation_space(self):
        return self.envs[0].observation_space

    @property
    def action_space(self):
        return self.envs[0].action_space

    def current_obs(self) -> np.ndarray:
        return self._obs

    def eps_ids(self) -> np.ndarray:
        return self._eps_ids.copy()

    def step(self, actions: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray, list]:
        """Step every sub-env; returns (next_obs, rewards, dones, infos).
        Terminated/truncated envs auto-reset; `dones` marks the boundary."""
        next_obs, rewards, dones, infos = [], [], [], []
        for i, env in enumerate(self.envs):
            o, r, terminated, truncated, info = env.step(np.asarray(actions[i]))
            done = bool(terminated or truncated)
            # Truncation vs termination matters to off-policy bootstrapping
            # (a time-limit cut must still bootstrap V/Q(s')), so the split
            # flags and the pre-reset observation ride in the info dict.
            info = dict(info)
            info["terminated"] = bool(terminated)
            info["truncated"] = bool(truncated)
            self._episode_rewards[i] += float(r)
            self._episode_lens[i] += 1
            if done:
                info["final_observation"] = o
                self.completed_rewards.append(float(self._episode_rewards[i]))
                self.completed_lens.append(int(self._episode_lens[i]))
                self._episode_rewards[i] = 0.0
                self._episode_lens[i] = 0
                self._eps_ids[i] = self._next_eps_id
                self._next_eps_id += 1
                o, _ = env.reset()
            next_obs.append(o)
            rewards.append(float(r))
            dones.append(done)
            infos.append(info)
        self._obs = np.stack(next_obs)
        return self._obs, np.asarray(rewards, np.float32), np.asarray(dones), infos

    def pop_episode_stats(self) -> Tuple[List[float], List[int]]:
        r, l = self.completed_rewards, self.completed_lens
        self.completed_rewards, self.completed_lens = [], []
        return r, l

    def close(self):
        for env in self.envs:
            try:
                env.close()
            except Exception:
                pass
