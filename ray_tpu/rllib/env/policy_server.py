"""PolicyServerInput + PolicyClient — external simulators over HTTP.

Reference: rllib/env/policy_server_input.py and policy_client.py — an
external sim (game client, robot, browser) owns the env loop and talks to a
policy over HTTP: start_episode / get_action / log_returns / end_episode.
The server answers actions from the live algorithm's policy and accumulates
finished episodes as SampleBatches for offline-style training (BC/MARWIL/CQL
readers accept them directly; on-policy algorithms can train via
``train_on_collected`` callbacks).
"""

from __future__ import annotations

import json
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    EPS_ID,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)


class _Episode:
    def __init__(self, eid: str, idx: int):
        self.eid = eid
        self.idx = idx
        self.obs: list = []
        self.actions: list = []
        self.rewards: list = []


class PolicyServerInput:
    """Serve a policy to external clients; collect their episodes.

    ``compute_action(obs_np, explore) -> action`` is typically an
    ``Algorithm.compute_single_action`` bound method.
    """

    def __init__(self, compute_action: Callable, host: str = "127.0.0.1", port: int = 0):
        self.compute_action = compute_action
        self._episodes: dict[str, _Episode] = {}
        self._next_idx = 0
        self._completed: list[_Episode] = []
        self._lock = threading.Lock()
        # Policy calls get their own lock: compute_action typically mutates
        # algorithm RNG state (not thread-safe), but it must not serialize
        # unrelated episode bookkeeping.
        self._policy_lock = threading.Lock()
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    out = outer._dispatch(self.path, payload)
                    body = json.dumps(out).encode()
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001 — surfaced to the client
                    body = json.dumps({"error": f"{type(e).__name__}: {e}"}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.address = f"http://{host}:{self._server.server_port}"
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def _dispatch(self, path: str, payload: dict) -> dict:
        if path == "/get_action":
            # Policy forward outside the episode lock (it can take
            # milliseconds), but serialized against other policy calls —
            # compute_action mutates shared RNG state.
            obs = np.asarray(payload["observation"], np.float32)
            with self._policy_lock:
                action = self.compute_action(obs, bool(payload.get("explore", True)))
            with self._lock:
                ep = self._episodes.get(payload.get("episode_id", ""))
                if ep is None:
                    raise KeyError(f"unknown episode {payload.get('episode_id')!r}")
                ep.obs.append(obs)
                ep.actions.append(np.asarray(action))
                ep.rewards.append(0.0)  # accumulated by log_returns
            return {"action": np.asarray(action).tolist()}
        with self._lock:
            if path == "/start_episode":
                eid = payload.get("episode_id") or uuid.uuid4().hex[:12]
                self._episodes[eid] = _Episode(eid, self._next_idx)
                self._next_idx += 1
                return {"episode_id": eid}
            ep = self._episodes.get(payload.get("episode_id", ""))
            if ep is None:
                raise KeyError(f"unknown episode {payload.get('episode_id')!r}")
            if path == "/log_action":
                # Client-side action (off-policy logging).
                ep.obs.append(np.asarray(payload["observation"], np.float32))
                ep.actions.append(np.asarray(payload["action"]))
                ep.rewards.append(0.0)
                return {}
            if path == "/log_returns":
                # Rewards ACCUMULATE onto the current step (the reference's
                # PolicyClient semantics — several shaping rewards per action,
                # or none, are both legal).
                if not ep.rewards:
                    raise RuntimeError("log_returns before any get_action/log_action")
                ep.rewards[-1] += float(payload["reward"])
                return {}
            if path == "/end_episode":
                self._episodes.pop(ep.eid)
                n = len(ep.actions)
                if n:
                    self._completed.append(ep)
                return {"rows": n}
            raise ValueError(f"unknown endpoint {path}")

    def num_completed(self) -> int:
        with self._lock:
            return len(self._completed)

    def next_batch(self, min_episodes: int = 1) -> Optional[SampleBatch]:
        """Drain completed episodes into one SampleBatch (rows in time
        order, EPS_ID marking boundaries; NEXT_OBS shifted within episodes)."""
        with self._lock:
            if len(self._completed) < min_episodes:
                return None
            eps, self._completed = self._completed, []
        frags = []
        for ep in eps:
            obs = np.stack(ep.obs)
            next_obs = np.concatenate([obs[1:], obs[-1:]])
            dones = np.zeros(len(obs), np.float32)
            dones[-1] = 1.0
            frags.append(SampleBatch({
                OBS: obs,
                ACTIONS: np.stack(ep.actions),
                REWARDS: np.asarray(ep.rewards, np.float32),
                DONES: dones,
                NEXT_OBS: next_obs,
                EPS_ID: np.full(len(obs), ep.idx, np.int64),
            }))
        return SampleBatch.concat_samples(frags)

    def shutdown(self):
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass


class PolicyClient:
    """Client side for external sims (reference: policy_client.py)."""

    def __init__(self, address: str, timeout: float = 30.0):
        self.address = address.rstrip("/")
        self.timeout = timeout

    def _post(self, path: str, payload: dict) -> dict:
        import urllib.request

        req = urllib.request.Request(
            self.address + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            out = json.loads(resp.read())
        if isinstance(out, dict) and out.get("error"):
            raise RuntimeError(out["error"])
        return out

    def start_episode(self, episode_id: Optional[str] = None) -> str:
        return self._post("/start_episode", {"episode_id": episode_id})["episode_id"]

    def get_action(self, episode_id: str, observation, explore: bool = True):
        out = self._post("/get_action", {
            "episode_id": episode_id,
            "observation": np.asarray(observation).tolist(),
            "explore": explore,
        })
        a = out["action"]
        return a if np.isscalar(a) else np.asarray(a)

    def log_action(self, episode_id: str, observation, action):
        self._post("/log_action", {
            "episode_id": episode_id,
            "observation": np.asarray(observation).tolist(),
            "action": np.asarray(action).tolist(),
        })

    def log_returns(self, episode_id: str, reward: float):
        self._post("/log_returns", {"episode_id": episode_id, "reward": float(reward)})

    def end_episode(self, episode_id: str, observation=None) -> int:
        return self._post("/end_episode", {"episode_id": episode_id}).get("rows", 0)
