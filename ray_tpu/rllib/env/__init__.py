from ray_tpu.rllib.env.vector_env import EnvContext, VectorEnv  # noqa: F401
