from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv, make_multi_agent  # noqa: F401
from ray_tpu.rllib.env.vector_env import (  # noqa: F401
    EnvContext,
    MultiAgentVectorEnv,
    VectorEnv,
    make_vector_env,
)
from ray_tpu.rllib.env.policy_server import PolicyClient, PolicyServerInput  # noqa: F401
