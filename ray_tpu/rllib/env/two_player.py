"""Two-player simultaneous-move environments for self-play training.

Reference: the reference's AlphaStar (rllib/algorithms/alpha_star/) trains
on multi-agent competitive envs through the MultiAgentEnv API; its league
machinery only needs "two policies act simultaneously, zero-sum payoff,
win-rates are measurable". This module provides that minimal protocol plus
a repeated matrix game (rock-paper-scissors by default) — the standard
testbed for league/exploitability dynamics (OpenSpiel uses the same).

Protocol (simpler than MultiAgentEnv on purpose — both sides step in one
call, which is what simultaneous-move matchmaking needs):
    obs_a, obs_b = env.reset()
    obs_a, obs_b, r_a, r_b, done = env.step(act_a, act_b)
r_a == -r_b (zero-sum).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

try:
    import gymnasium as gym
except ImportError:  # pragma: no cover
    gym = None

# Rock-paper-scissors payoff for the row player: entry [i, j] is row's
# reward when row plays i and column plays j.
RPS_PAYOFF = np.array(
    [
        [0.0, -1.0, 1.0],
        [1.0, 0.0, -1.0],
        [-1.0, 1.0, 0.0],
    ],
    np.float32,
)


class TwoPlayerMatrixEnv:
    """Repeated simultaneous matrix game. Observation (per player) is the
    one-hot of [my last action, opponent's last action] (zeros on the first
    round) — enough memory for best-responding against non-uniform
    opponents while keeping the game small."""

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.payoff = np.asarray(config.get("payoff", RPS_PAYOFF), np.float32)
        assert self.payoff.shape[0] == self.payoff.shape[1]
        self.n_actions = self.payoff.shape[0]
        self.rounds = int(config.get("rounds", 32))
        self.observation_space = gym.spaces.Box(0.0, 1.0, (2 * self.n_actions,), np.float32)
        self.action_space = gym.spaces.Discrete(self.n_actions)
        self._t = 0
        self._last = (None, None)

    def _obs(self, mine, theirs) -> np.ndarray:
        o = np.zeros(2 * self.n_actions, np.float32)
        if mine is not None:
            o[mine] = 1.0
        if theirs is not None:
            o[self.n_actions + theirs] = 1.0
        return o

    def reset(self):
        self._t = 0
        self._last = (None, None)
        return self._obs(None, None), self._obs(None, None)

    def step(self, act_a: int, act_b: int):
        r_a = float(self.payoff[act_a, act_b])
        self._t += 1
        self._last = (act_a, act_b)
        done = self._t >= self.rounds
        return (
            self._obs(act_a, act_b),
            self._obs(act_b, act_a),
            r_a,
            -r_a,
            done,
        )

    def close(self):
        pass


def scripted_biased_policy(n_actions: int, favorite: int, p: float = 0.7, seed: int = 0):
    """A fixed stochastic policy playing `favorite` with probability p —
    the exploitable opponent league tests anchor on."""
    rng = np.random.default_rng(seed)

    def act(_obs) -> int:
        if rng.random() < p:
            return favorite
        return int(rng.integers(0, n_actions))

    return act
