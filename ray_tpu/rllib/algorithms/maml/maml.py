"""MAML — model-agnostic meta-learning over task-settable envs.

Reference: rllib/algorithms/maml/maml.py (Finn et al. 2017, RL variant):
each meta-iteration samples a batch of tasks; workers collect pre-adaptation
rollouts with the meta-policy, the policy takes per-task inner policy-
gradient steps, workers collect post-adaptation rollouts with the adapted
policies, and the meta-update differentiates the post-adaptation surrogate
THROUGH the inner gradient steps (maml.py training_step + the
higher-order-grad workers in maml_torch_policy.py).

TPU-native shape: the inner adaptation is a pure function
``adapted(theta) = theta - lr * grad(pg_loss)(theta, D_task)`` — JAX
differentiates through it exactly (true second-order MAML, no manual
Hessian-vector plumbing like the reference's torch policy), and the whole
meta-update is ONE jitted function vmapped over the task axis: task batches
are stacked [n_tasks, rows, ...] (uniform shapes from fixed-horizon
episodes) so the MXU sees one big batched program instead of a Python loop
over tasks. Workers only collect data; gradients never leave the driver.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    ADVANTAGES,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    VALUE_TARGETS,
    VF_PREDS,
    SampleBatch,
    compute_gae,
)


def inner_pg_loss(params, batch, spec):
    """Vanilla policy-gradient loss for the inner adaptation step
    (reference: maml uses plain PG inside, surrogate outside)."""
    import jax.numpy as jnp

    from ray_tpu.rllib.core import rl_module

    logp, _, _ = rl_module.action_logp_and_entropy(params, batch[OBS], batch[ACTIONS], spec)
    adv = batch[ADVANTAGES]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    return -jnp.mean(logp * adv)


def make_inner_adapt(spec, inner_lr: float, inner_steps: int):
    """Returns adapted(theta, task_batch) — differentiable in theta."""
    import jax

    def adapt(params, batch):
        for _ in range(inner_steps):
            grads = jax.grad(inner_pg_loss)(params, batch, spec)
            params = jax.tree_util.tree_map(lambda p, g: p - inner_lr * g, params, grads)
        return params

    return adapt


def outer_surrogate_loss(adapted_params, batch, spec, cfg):
    """PPO-clip surrogate + vf + entropy on the post-adaptation batch,
    evaluated at the adapted parameters (grad flows back into theta)."""
    import jax.numpy as jnp

    from ray_tpu.rllib.core import rl_module

    logp, entropy, value = rl_module.action_logp_and_entropy(
        adapted_params, batch[OBS], batch[ACTIONS], spec
    )
    ratio = jnp.exp(logp - batch[LOGPS])
    adv = batch[ADVANTAGES]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    clip = cfg["clip_param"]
    surrogate = jnp.minimum(ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
    vf_loss = jnp.mean((value - batch[VALUE_TARGETS]) ** 2)
    return (
        -surrogate.mean()
        + cfg["vf_loss_coeff"] * vf_loss
        - cfg["entropy_coeff"] * entropy.mean()
    )


class _MAMLWorker:
    """Task rollout actor: fixed-horizon episodes on a task-settable env.

    Uniform shapes (episodes never terminate early on the meta envs) let
    the driver stack per-task batches into one [n_tasks, rows, ...] array
    for the vmapped meta-update."""

    def __init__(self, env, env_config, spec, worker_index, gamma, lambda_, seed):
        import jax

        jax.config.update("jax_platforms", "cpu")
        import gymnasium as gym

        self.env = (
            gym.make(env) if isinstance(env, str) else env(dict(env_config))
        )
        self.spec = spec
        self.gamma = gamma
        self.lambda_ = lambda_
        self._rng = jax.random.PRNGKey(seed * 7919 + worker_index)
        from ray_tpu.rllib.core import rl_module

        self._sample_fn = jax.jit(
            lambda p, o, r: rl_module.sample_actions(p, o, r, spec, True)
        )

    def set_task(self, task):
        self.env.set_task(task)
        return True

    def sample(self, weights, n_episodes: int):
        """n_episodes fixed-horizon episodes; GAE per episode; returns the
        stacked columns + the mean episode reward."""
        import jax
        import jax.numpy as jnp

        params = jax.tree_util.tree_map(jnp.asarray, weights)
        frags = []
        ep_rewards = []
        for _ in range(n_episodes):
            obs, _ = self.env.reset()
            cols = {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, LOGPS, VF_PREDS)}
            total = 0.0
            while True:
                o = np.asarray(obs, np.float32)
                self._rng, key = jax.random.split(self._rng)
                a, logp, v = self._sample_fn(params, jnp.asarray(o)[None], key)
                a_np = np.asarray(a)[0]
                env_a = np.clip(a_np, self.env.action_space.low, self.env.action_space.high)
                obs, r, terminated, truncated, _ = self.env.step(env_a)
                total += float(r)
                cols[OBS].append(o)
                cols[ACTIONS].append(a_np)
                cols[REWARDS].append(np.float32(r))
                cols[DONES].append(np.float32(terminated))
                cols[LOGPS].append(np.asarray(logp)[0])
                cols[VF_PREDS].append(np.asarray(v)[0])
                if terminated or truncated:
                    break
            frag = SampleBatch({k: np.stack(v) for k, v in cols.items()})
            frag = compute_gae(frag, 0.0, self.gamma, self.lambda_)
            frags.append(frag)
            ep_rewards.append(total)
        batch = SampleBatch.concat_samples(frags)
        return {k: np.asarray(v) for k, v in batch.items()}, float(np.mean(ep_rewards))

    def stop(self):
        try:
            self.env.close()
        except Exception:
            pass
        return True


class MAMLConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MAML)
        self.lr = 1e-3               # outer (meta) learning rate
        self.inner_lr = 0.1          # inner adaptation step size
        self.inner_adaptation_steps = 1
        self.maml_optimizer_steps = 5
        self.meta_batch_size = 10    # tasks per meta-iteration
        self.episodes_per_task = 10
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.num_rollout_workers = 2

    def training(self, *, inner_lr: Optional[float] = None,
                 inner_adaptation_steps: Optional[int] = None,
                 maml_optimizer_steps: Optional[int] = None,
                 meta_batch_size: Optional[int] = None,
                 episodes_per_task: Optional[int] = None,
                 clip_param: Optional[float] = None,
                 vf_loss_coeff: Optional[float] = None,
                 entropy_coeff: Optional[float] = None, **kwargs) -> "MAMLConfig":
        super().training(**kwargs)
        for name, val in (
            ("inner_lr", inner_lr),
            ("inner_adaptation_steps", inner_adaptation_steps),
            ("maml_optimizer_steps", maml_optimizer_steps),
            ("meta_batch_size", meta_batch_size),
            ("episodes_per_task", episodes_per_task),
            ("clip_param", clip_param),
            ("vf_loss_coeff", vf_loss_coeff),
            ("entropy_coeff", entropy_coeff),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class MAML(Algorithm):
    @classmethod
    def get_default_config(cls) -> MAMLConfig:
        return MAMLConfig(cls)

    def setup(self, config: dict) -> None:
        import jax
        import optax

        self.cleanup()
        cfg: MAMLConfig = self._algo_config
        import gymnasium as gym

        self._task_env = (
            gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        )
        assert hasattr(self._task_env, "sample_tasks"), (
            "MAML needs a task-settable env (sample_tasks/set_task)"
        )
        from ray_tpu.rllib.models import ModelCatalog

        self.module_spec = ModelCatalog.get_model_spec(
            self._task_env.observation_space, self._task_env.action_space, cfg.model_config()
        )
        from ray_tpu.rllib.core import rl_module

        self.params = rl_module.init_params(jax.random.PRNGKey(cfg.seed), self.module_spec)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        n = max(cfg.num_rollout_workers, 1)
        worker_cls = ray_tpu.remote(num_cpus=1)(_MAMLWorker)
        self.workers = [
            worker_cls.remote(
                cfg.env, dict(cfg.env_config), self.module_spec, i,
                cfg.gamma, cfg.lambda_, cfg.seed,
            )
            for i in range(n)
        ]
        self._build_meta_update(cfg)
        self._timesteps_total = 0
        self._episode_reward_window: list = []

    def _build_meta_update(self, cfg: MAMLConfig):
        import jax
        import jax.numpy as jnp

        spec = self.module_spec
        adapt = make_inner_adapt(spec, cfg.inner_lr, cfg.inner_adaptation_steps)
        loss_cfg = {
            "clip_param": cfg.clip_param,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
        }
        tx = self.tx

        def per_task_outer(params, pre_batch, post_batch):
            adapted = adapt(params, pre_batch)
            return outer_surrogate_loss(adapted, post_batch, spec, loss_cfg)

        def meta_loss(params, pre_stack, post_stack):
            # vmap over the task axis; theta broadcast (in_axes=None).
            losses = jax.vmap(per_task_outer, in_axes=(None, 0, 0))(
                params, pre_stack, post_stack
            )
            return losses.mean()

        def meta_update(params, opt_state, pre_stack, post_stack):
            loss, grads = jax.value_and_grad(meta_loss)(params, pre_stack, post_stack)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss

        self._meta_update = jax.jit(meta_update)
        self._adapt = jax.jit(adapt)

    def get_policy_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def _collect(self, weights_per_task, tasks):
        """Round-robin the (task, weights) pairs over the worker pool."""
        cfg: MAMLConfig = self._algo_config
        refs = []
        for i, task in enumerate(tasks):
            w = self.workers[i % len(self.workers)]
            # Serialize per-task on the worker: set_task then sample are
            # actor calls, ordered per submitter.
            w.set_task.remote(task)
            refs.append(w.sample.remote(weights_per_task[i], cfg.episodes_per_task))
        out = ray_tpu.get(refs, timeout=600)
        batches = [SampleBatch(cols) for cols, _ in out]
        rewards = [r for _, r in out]
        return batches, rewards

    @staticmethod
    def _stack(batches):
        import jax.numpy as jnp

        keys = batches[0].keys()
        return {k: jnp.asarray(np.stack([b[k] for b in batches])) for k in keys}

    def training_step(self) -> dict:
        import jax

        cfg: MAMLConfig = self._algo_config
        tasks = self._task_env.sample_tasks(cfg.meta_batch_size)
        theta_np = self.get_policy_weights()

        # 1. Pre-adaptation rollouts with the meta-policy on every task.
        pre_batches, pre_rewards = self._collect([theta_np] * len(tasks), tasks)

        # 2. Per-task inner adaptation (same jitted function the meta-update
        # differentiates through — eval here, grad there).
        pre_stack = self._stack(pre_batches)
        adapted_stack = jax.vmap(self._adapt, in_axes=(None, 0))(self.params, pre_stack)
        adapted_np = [
            jax.tree_util.tree_map(lambda x, i=i: np.asarray(x[i]), adapted_stack)
            for i in range(len(tasks))
        ]

        # 3. Post-adaptation rollouts with each task's adapted policy.
        post_batches, post_rewards = self._collect(adapted_np, tasks)
        post_stack = self._stack(post_batches)

        # 4. Meta-update: differentiate the post-adaptation surrogate
        # through the inner steps (second-order, via jax.grad∘vmap).
        loss = None
        for _ in range(cfg.maml_optimizer_steps):
            self.params, self.opt_state, loss = self._meta_update(
                self.params, self.opt_state, pre_stack, post_stack
            )
        n_rows = sum(b.count for b in pre_batches) + sum(b.count for b in post_batches)
        self._timesteps_total += n_rows
        self._episode_reward_window += post_rewards
        self._episode_reward_window = self._episode_reward_window[-100:]
        pre, post = float(np.mean(pre_rewards)), float(np.mean(post_rewards))
        return {
            "meta_loss": float(loss),
            "pre_adaptation_reward_mean": pre,
            "post_adaptation_reward_mean": post,
            # The MAML headline number: what one inner step buys.
            "adaptation_delta": post - pre,
            "num_env_steps_sampled_this_iter": n_rows,
        }

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window))
            if self._episode_reward_window
            else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def adapt_to_task(self, task, n_episodes: Optional[int] = None):
        """Deploy-time adaptation: collect rollouts on `task` with the
        meta-policy and return task-adapted weights (the reference exposes
        this implicitly via its inner loop; here it is a public API)."""
        import jax
        import jax.numpy as jnp

        cfg: MAMLConfig = self._algo_config
        w = self.workers[0]
        ray_tpu.get(w.set_task.remote(task), timeout=60)
        cols, _ = ray_tpu.get(
            w.sample.remote(self.get_policy_weights(), n_episodes or cfg.episodes_per_task),
            timeout=300,
        )
        jb = {k: jnp.asarray(v) for k, v in cols.items()}
        adapted = self._adapt(self.params, jb)
        return jax.tree_util.tree_map(np.asarray, adapted)

    def compute_single_action(self, obs, explore: bool = False):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.core import rl_module

        actions, _, _ = rl_module.sample_actions(
            self.params, jnp.asarray(np.asarray(obs, np.float32))[None],
            jax.random.PRNGKey(0), self.module_spec, explore,
        )
        a = np.asarray(actions)[0]
        return a.item() if self.module_spec.discrete else a

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict(
            {"weights": self.get_policy_weights(), "timesteps": self._timesteps_total}
        )

    def load_checkpoint(self, checkpoint) -> None:
        import jax
        import jax.numpy as jnp

        data = checkpoint.to_dict()
        self.params = jax.tree_util.tree_map(jnp.asarray, data["weights"])
        self._timesteps_total = data.get("timesteps", 0)

    def cleanup(self) -> None:
        for w in getattr(self, "workers", []):
            try:
                ray_tpu.get(w.stop.remote(), timeout=10)
            except Exception:
                pass
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        env = getattr(self, "_task_env", None)
        if env is not None:
            try:
                env.close()
            except Exception:
                pass
            self._task_env = None
        eval_ws = getattr(self, "_eval_workers", None)
        if eval_ws is not None:
            eval_ws.stop()
            self._eval_workers = None
