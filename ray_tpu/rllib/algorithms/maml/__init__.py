from ray_tpu.rllib.algorithms.maml.maml import MAML, MAMLConfig  # noqa: F401
