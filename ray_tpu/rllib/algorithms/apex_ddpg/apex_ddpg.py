"""Ape-X DDPG — distributed prioritized replay for continuous control.

Reference: rllib/algorithms/apex_ddpg/apex_ddpg.py (Horgan et al. 2018
applied to DDPG): the Ape-X architecture of apex_dqn — many exploration
actors on a per-worker noise ladder feeding actor-sharded prioritized
replay, a central learner pushing priorities back and broadcasting weights
periodically — with DDPG's deterministic-policy TD learner instead of the
Q-network. The exploration ladder uses per-worker Gaussian ACTION noise
(sigma_i = 0.4^(1 + 7 i/(N-1)), the continuous analog of the epsilon
ladder apex_dqn.py:48 uses).

The learner is a single jitted step: importance-weighted critic TD loss
(per-sample weights from the prioritized shards), actor update through the
critic, Polyak targets — and it returns the TD errors so the driver can
push fresh priorities back to the owning shard.
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.apex_dqn.apex_dqn import _ReplayShard
from ray_tpu.rllib.algorithms.ddpg.ddpg import DDPGConfig, init_ddpg_params
from ray_tpu.rllib.algorithms.sac.sac import _mlp_apply, _true_transition
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)


class _ApexDDPGWorker:
    """Exploration actor: deterministic policy + fixed per-worker Gaussian
    action noise against the latest broadcast weights."""

    def __init__(self, env, env_config, hiddens, act_scale, act_offset,
                 worker_index, num_workers, num_envs, seed):
        import jax

        jax.config.update("jax_platforms", "cpu")  # rollouts stay off-chip
        from ray_tpu.rllib.env.vector_env import VectorEnv

        self.env = VectorEnv(env, num_envs, env_config, worker_index, seed=seed + worker_index)
        self._policy = jax.jit(lambda p, o: jax.numpy.tanh(_mlp_apply(p["actor"], o)))
        self.params = None
        self._act_scale = np.asarray(act_scale, np.float32)
        self._act_offset = np.asarray(act_offset, np.float32)
        denom = max(num_workers - 1, 1)
        self.sigma = 0.4 ** (1 + 7 * worker_index / denom)
        self._rng = np.random.default_rng(seed * 9973 + worker_index)

    def set_weights(self, weights):
        import jax
        import jax.numpy as jnp

        self.params = jax.tree_util.tree_map(jnp.asarray, weights)
        return True

    def sample(self, n_steps: int):
        import jax.numpy as jnp

        cols = {OBS: [], ACTIONS: [], REWARDS: [], DONES: [], NEXT_OBS: []}
        for _ in range(n_steps):
            obs = self.env.current_obs().astype(np.float32).reshape(self.env.num_envs, -1)
            a = np.asarray(self._policy(self.params, jnp.asarray(obs)))
            a = np.clip(a + self._rng.normal(0, self.sigma, a.shape), -1, 1).astype(np.float32)
            _, rewards, dones, infos = self.env.step(a * self._act_scale + self._act_offset)
            next_obs, terminateds = _true_transition(self.env, dones, infos)
            cols[OBS].append(obs)
            cols[ACTIONS].append(a)
            cols[REWARDS].append(rewards)
            cols[DONES].append(terminateds)
            cols[NEXT_OBS].append(next_obs)
        out = {k: np.concatenate(v) for k, v in cols.items()}
        rews, _ = self.env.pop_episode_stats()
        return out, rews, len(out[OBS])

    def stop(self):
        self.env.close()
        return True


class ApexDDPGConfig(DDPGConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ApexDDPG)
        self.num_rollout_workers = 2
        self.num_replay_shards = 2
        self.rollout_fragment_length = 50
        self.weight_sync_period_updates = 16
        self.train_rounds_per_iter = 8
        self.updates_per_round = 4
        self.learning_starts = 500

    def training(self, *, num_replay_shards=None, rollout_fragment_length=None,
                 weight_sync_period_updates=None, train_rounds_per_iter=None,
                 updates_per_round=None, **kwargs) -> "ApexDDPGConfig":
        super().training(**kwargs)
        for name, val in (
            ("num_replay_shards", num_replay_shards),
            ("rollout_fragment_length", rollout_fragment_length),
            ("weight_sync_period_updates", weight_sync_period_updates),
            ("train_rounds_per_iter", train_rounds_per_iter),
            ("updates_per_round", updates_per_round),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class ApexDDPG(Algorithm):
    @classmethod
    def get_default_config(cls) -> ApexDDPGConfig:
        return ApexDDPGConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax
        import optax

        self.cleanup()
        cfg: ApexDDPGConfig = self._algo_config
        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        assert not isinstance(probe.action_space, gym.spaces.Discrete), "ApexDDPG needs continuous actions"
        self.obs_dim = int(np.prod(probe.observation_space.shape))
        self.action_dim = int(np.prod(probe.action_space.shape))
        low = np.asarray(probe.action_space.low, np.float32)
        high = np.asarray(probe.action_space.high, np.float32)
        self._act_scale = (high - low) / 2.0
        self._act_offset = (high + low) / 2.0
        probe.close()

        self.params = init_ddpg_params(
            jax.random.PRNGKey(cfg.seed), self.obs_dim, self.action_dim,
            cfg.model_hiddens, cfg.twin_q,
        )
        self.target = jax.tree_util.tree_map(lambda x: x, self.params)
        self._critic_keys = tuple(k for k in ("q1", "q2") if k in self.params)
        self.actor_tx = optax.adam(cfg.lr)
        self.critic_tx = optax.adam(cfg.lr)
        self.opt_state = {
            "actor": self.actor_tx.init(self.params["actor"]),
            "critic": self.critic_tx.init({k: self.params[k] for k in self._critic_keys}),
        }
        self._build_train_step(cfg)

        n_workers = max(cfg.num_rollout_workers, 1)
        worker_cls = ray_tpu.remote(num_cpus=1)(_ApexDDPGWorker)
        self.workers = [
            worker_cls.remote(
                cfg.env, dict(cfg.env_config), cfg.model_hiddens,
                self._act_scale, self._act_offset,
                i, n_workers, max(cfg.num_envs_per_worker, 1), cfg.seed,
            )
            for i in range(n_workers)
        ]
        shard_cls = ray_tpu.remote(num_cpus=0.1)(_ReplayShard)
        shard_cap = max(1, cfg.replay_buffer_capacity // max(cfg.num_replay_shards, 1))
        self.shards = [
            shard_cls.remote(shard_cap, cfg.seed + 31 * i) for i in range(cfg.num_replay_shards)
        ]
        self._shard_sizes = {i: 0 for i in range(len(self.shards))}
        ray_tpu.get(
            [w.set_weights.remote(self._np_weights()) for w in self.workers], timeout=300
        )
        self._timesteps_total = 0
        self._updates = 0
        self._last_sync = 0
        self._add_rr = 0
        self._sample_rr = 0
        self._replay_size = 0
        self._episode_reward_window: list = []

    def _np_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def _build_train_step(self, cfg: ApexDDPGConfig):
        import jax
        import jax.numpy as jnp

        gamma, tau = cfg.gamma, cfg.tau
        twin_q = cfg.twin_q
        critic_keys = self._critic_keys
        actor_tx, critic_tx = self.actor_tx, self.critic_tx

        def q_val(q, obs, a):
            return _mlp_apply(q, jnp.concatenate([obs, a], -1))[:, 0]

        def critic_loss_fn(critic, target, batch):
            obs, next_obs = batch[OBS], batch[NEXT_OBS]
            next_a = jnp.tanh(_mlp_apply(target["actor"], next_obs))
            tq = q_val(target["q1"], next_obs, next_a)
            if twin_q:
                tq = jnp.minimum(tq, q_val(target["q2"], next_obs, next_a))
            td_target = jax.lax.stop_gradient(
                batch[REWARDS] + gamma * (1 - batch[DONES]) * tq
            )
            q1 = q_val(critic["q1"], obs, batch[ACTIONS])
            td_error = q1 - td_target
            # Importance weights from the prioritized shards correct the
            # non-uniform sampling distribution (Ape-X keeps PER's IS step).
            loss = jnp.mean(batch["weights"] * td_error**2)
            if twin_q:
                q2 = q_val(critic["q2"], obs, batch[ACTIONS])
                loss = loss + jnp.mean(batch["weights"] * (q2 - td_target) ** 2)
            return loss, td_error

        def actor_loss_fn(actor, critic, batch):
            obs = batch[OBS]
            a_pi = jnp.tanh(_mlp_apply(actor, obs))
            return -jnp.mean(q_val(critic["q1"], obs, a_pi))

        def train_step(params, target, opt_state, batch):
            critic = {k: params[k] for k in critic_keys}
            (closs, td_error), cgrads = jax.value_and_grad(critic_loss_fn, has_aux=True)(
                critic, target, batch
            )
            cupd, c_opt = critic_tx.update(cgrads, opt_state["critic"], critic)
            critic = jax.tree_util.tree_map(lambda p, u: p + u, critic, cupd)
            aloss, agrads = jax.value_and_grad(actor_loss_fn)(params["actor"], critic, batch)
            aupd, a_opt = actor_tx.update(agrads, opt_state["actor"], params["actor"])
            actor = jax.tree_util.tree_map(lambda p, u: p + u, params["actor"], aupd)
            params = {**critic, "actor": actor}
            target = jax.tree_util.tree_map(
                lambda t, p: (1 - tau) * t + tau * p, target, params
            )
            opt_state = {"actor": a_opt, "critic": c_opt}
            metrics = {"critic_loss": closs, "actor_loss": aloss}
            return params, target, opt_state, td_error, metrics

        self._train_step = jax.jit(train_step)
        self._policy = jax.jit(lambda p, o: jnp.tanh(_mlp_apply(p["actor"], o)))

    def training_step(self) -> dict:
        cfg: ApexDDPGConfig = self._algo_config
        metrics: dict = {}
        for _ in range(cfg.train_rounds_per_iter):
            refs = [w.sample.remote(cfg.rollout_fragment_length) for w in self.workers]
            add_refs, add_shards = [], []
            for cols, rews, count in ray_tpu.get(refs, timeout=600):
                shard_i = self._add_rr % len(self.shards)
                self._add_rr += 1
                add_refs.append(self.shards[shard_i].add.remote(cols))
                add_shards.append(shard_i)
                self._timesteps_total += count
                self._episode_reward_window += rews
            for size, shard in zip(ray_tpu.get(add_refs, timeout=300), add_shards):
                self._shard_sizes[shard] = size
            self._replay_size = sum(self._shard_sizes.values())
            self._episode_reward_window = self._episode_reward_window[-100:]
            if self._replay_size < cfg.learning_starts:
                continue
            for _ in range(cfg.updates_per_round):
                metrics = self._train_once() or metrics
            if self._updates - self._last_sync >= cfg.weight_sync_period_updates:
                self._last_sync = self._updates
                ray_tpu.get(
                    [w.set_weights.remote(self._np_weights()) for w in self.workers],
                    timeout=300,
                )
        metrics["replay_size"] = self._replay_size
        return metrics

    def _train_once(self):
        import jax.numpy as jnp

        cfg: ApexDDPGConfig = self._algo_config
        shard = self.shards[self._sample_rr % len(self.shards)]
        self._sample_rr += 1
        res = ray_tpu.get(shard.sample_with_idx.remote(cfg.train_batch_size), timeout=300)
        if res is None:
            return None
        batch, idx = res
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.target, self.opt_state, td_error, metrics = self._train_step(
            self.params, self.target, self.opt_state, jb
        )
        shard.update_priorities.remote(idx, np.asarray(td_error))
        self._updates += 1
        return {k: float(v) for k, v in metrics.items()}

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window))
            if self._episode_reward_window
            else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def compute_single_action(self, obs, explore: bool = False):
        import jax.numpy as jnp

        obs = np.asarray(obs, np.float32).reshape(1, -1)
        a = np.asarray(self._policy(self.params, jnp.asarray(obs)))[0]
        return np.asarray(a) * self._act_scale + self._act_offset

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint
        import jax

        return Checkpoint.from_dict({
            "weights": self._np_weights(),
            "target": jax.tree_util.tree_map(np.asarray, self.target),
            "timesteps": self._timesteps_total,
            "updates": self._updates,
        })

    def load_checkpoint(self, checkpoint) -> None:
        import jax
        import jax.numpy as jnp

        data = checkpoint.to_dict()
        self.params = jax.tree_util.tree_map(jnp.asarray, data["weights"])
        self.target = jax.tree_util.tree_map(jnp.asarray, data["target"])
        self._timesteps_total = data.get("timesteps", 0)
        self._updates = data.get("updates", 0)
        ray_tpu.get(
            [w.set_weights.remote(self._np_weights()) for w in self.workers], timeout=300
        )

    def cleanup(self) -> None:
        for w in getattr(self, "workers", []):
            try:
                ray_tpu.get(w.stop.remote(), timeout=10)
            except Exception:
                pass
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        for s in getattr(self, "shards", []):
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
        self.workers = []
        self.shards = []
        eval_ws = getattr(self, "_eval_workers", None)
        if eval_ws is not None:
            eval_ws.stop()
            self._eval_workers = None
