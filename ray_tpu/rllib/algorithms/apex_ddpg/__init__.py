from ray_tpu.rllib.algorithms.apex_ddpg.apex_ddpg import ApexDDPG, ApexDDPGConfig  # noqa: F401
