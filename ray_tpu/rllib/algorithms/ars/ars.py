"""ARS — Augmented Random Search (Mania et al. 2018).

Reference: rllib/algorithms/ars/ (ars.py, ars_tf_policy.py): like ES, a
black-box method evaluating antithetic parameter perturbations in worker
actors — but with ARS's three augmentations over vanilla random search:

1. TOP-K direction selection: only the ``num_top_directions`` best
   directions (ranked by max(R+, R-)) enter the update;
2. raw-return weighting scaled by the STD of the used returns (no rank
   transform, no Adam — plain scaled SGD ascent);
3. a running observation mean/std filter (ARS-V2, the reference's
   MeanStdFilter): workers normalize observations and ship their
   accumulated statistics back for merging each iteration.

Shares the ES worker/seed machinery (es.py): perturbations travel as
integer seeds, never parameter-sized noise.
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.es.es import (
    ES,
    ESConfig,
    _ESWorker,
    _flatten,
)


class _ARSWorker(_ESWorker):
    """ES worker + observation normalization with stat accumulation."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._obs_mean = None
        self._obs_std = None
        self._acc_count = 0
        self._acc_sum = None
        self._acc_sumsq = None

    def set_obs_stats(self, mean, std):
        self._obs_mean = np.asarray(mean, np.float32) if mean is not None else None
        self._obs_std = np.asarray(std, np.float32) if std is not None else None
        return True

    def _episode_return(self, flat, episode_horizon: int):
        import jax.numpy as jnp

        from ray_tpu.rllib.algorithms.es.es import _unflatten

        params = _unflatten(flat, self.treedef, self.shapes)
        obs, _ = self.env.reset(seed=int(self._np_rng.integers(1 << 31)))
        total, steps = 0.0, 0
        while steps < episode_horizon:
            o = np.asarray(obs, np.float32).reshape(-1)
            # Accumulate BEFORE normalizing (the filter models raw obs).
            if self._acc_sum is None:
                self._acc_sum = np.zeros_like(o)
                self._acc_sumsq = np.zeros_like(o)
            self._acc_count += 1
            self._acc_sum += o
            self._acc_sumsq += o * o
            if self._obs_mean is not None:
                o = (o - self._obs_mean) / (self._obs_std + 1e-8)
            out = np.asarray(self._forward(params, jnp.asarray(o.reshape(1, -1))))[0]
            action = int(out.argmax()) if self.spec.discrete else np.tanh(out)
            obs, r, terminated, truncated, _ = self.env.step(action)
            total += float(r)
            steps += 1
            if terminated or truncated:
                break
        return total, steps

    def drain_obs_stats(self):
        """(count, sum, sumsq) accumulated since the last drain."""
        out = (
            self._acc_count,
            None if self._acc_sum is None else self._acc_sum.copy(),
            None if self._acc_sumsq is None else self._acc_sumsq.copy(),
        )
        self._acc_count = 0
        if self._acc_sum is not None:
            self._acc_sum[:] = 0
            self._acc_sumsq[:] = 0
        return out


class ARSConfig(ESConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ARS)
        self.episodes_per_batch = 32       # directions per iteration
        self.num_top_directions = 16       # top-k by max(R+, R-)
        self.noise_stdev = 0.025
        self.stepsize = 0.02               # SGD ascent rate (no Adam)
        self.observation_filter = True     # ARS-V2 MeanStdFilter

    def training(self, *, num_top_directions=None, observation_filter=None, **kwargs) -> "ARSConfig":
        super().training(**kwargs)
        if num_top_directions is not None:
            self.num_top_directions = num_top_directions
        if observation_filter is not None:
            self.observation_filter = observation_filter
        return self


class ARS(ES, Algorithm):
    _worker_cls = _ARSWorker

    @classmethod
    def get_default_config(cls) -> ARSConfig:
        return ARSConfig(cls)

    def setup(self, config: dict) -> None:
        super().setup(config)
        # Running obs filter state (merged across workers each iteration).
        self._obs_count = 0
        self._obs_sum = None
        self._obs_sumsq = None

    def _merge_obs_stats(self):
        # Fan out the drains, then collect: N sequential round trips would
        # serialize the iteration on worker latency.
        refs = [w.drain_obs_stats.remote() for w in self._workers]
        for ref in refs:
            try:
                count, s, sq = ray_tpu.get(ref, timeout=120)
            except Exception:
                continue
            if count and s is not None:
                if self._obs_sum is None:
                    self._obs_sum = np.zeros_like(s)
                    self._obs_sumsq = np.zeros_like(sq)
                self._obs_count += count
                self._obs_sum += s
                self._obs_sumsq += sq
        if self._obs_count > 1:
            mean = self._obs_sum / self._obs_count
            var = np.maximum(self._obs_sumsq / self._obs_count - mean * mean, 1e-8)
            std = np.sqrt(var)
            self._obs_mean_cur, self._obs_std_cur = mean, std
            for w in self._workers:
                w.set_obs_stats.remote(mean, std)

    def training_step(self) -> dict:
        cfg: ARSConfig = self._algo_config
        n_dirs = cfg.episodes_per_batch
        seeds = self._np_rng.integers(0, 1 << 31, n_dirs)
        per_worker = np.array_split(seeds, len(self._workers))
        refs = [
            w.rollout.remote(self.flat, list(map(int, chunk)), cfg.noise_stdev, cfg.episode_horizon)
            for w, chunk in zip(self._workers, per_worker)
            if len(chunk)
        ]
        pairs: list = []
        used_seeds: list = []
        steps_this_iter = 0
        for ref, chunk in zip(refs, [c for c in per_worker if len(c)]):
            try:
                res = ray_tpu.get(ref, timeout=600)
                pairs += [(rp, rn) for rp, rn, _ in res]
                steps_this_iter += sum(n for _, _, n in res)
                used_seeds += list(chunk)
            except Exception:
                pass  # lost worker: proceed with the survivors' directions
        if cfg.observation_filter:
            self._merge_obs_stats()
        if not pairs:
            return {"ars_update_skipped": 1.0}
        returns = np.asarray(pairs, np.float32)  # [n, 2] = (R+, R-)

        # Augmentation 1: keep only the top-k directions by max(R+, R-).
        k = min(cfg.num_top_directions, len(returns))
        order = np.argsort(-returns.max(axis=1))[:k]
        top = returns[order]
        top_seeds = [used_seeds[i] for i in order]
        # Augmentation 2: raw-return weights scaled by the std of USED returns.
        sigma_r = float(top.std()) or 1.0
        grad = np.zeros_like(self.flat)
        for (r_pos, r_neg), s in zip(top, top_seeds):
            noise = np.random.default_rng(int(s)).standard_normal(len(self.flat)).astype(np.float32)
            grad += (r_pos - r_neg) * noise
        grad /= k * sigma_r
        grad -= cfg.l2_coeff * self.flat  # weight decay (inherited ES knob)
        self.flat = self.flat + cfg.stepsize * grad

        eval_refs = [self._workers[0].evaluate.remote(self.flat, cfg.eval_episodes, cfg.episode_horizon)]
        try:
            evals = ray_tpu.get(eval_refs[0], timeout=600)
        except Exception:
            evals = []
        rewards = [r for r, _ in evals]
        steps_this_iter += sum(n for _, n in evals)
        self._timesteps_total += steps_this_iter
        self._episode_reward_window += rewards
        self._episode_reward_window = self._episode_reward_window[-100:]
        return {
            "episode_reward_mean": float(np.mean(rewards)) if rewards else float("nan"),
            "top_directions_used": float(k),
            "return_std": sigma_r,
        }

    def compute_single_action(self, obs, explore: bool = False):
        mean = getattr(self, "_obs_mean_cur", None)
        if mean is not None:
            obs = (np.asarray(obs, np.float32).reshape(-1) - mean) / (
                self._obs_std_cur + 1e-8
            )
        return super().compute_single_action(obs, explore=explore)

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        ckpt = super().save_checkpoint().to_dict()
        # The observation filter is part of the POLICY: weights are fit to
        # normalized observations, so restoring them without the filter
        # stats feeds raw obs to a normalized-obs policy.
        ckpt["obs_filter"] = {
            "count": self._obs_count,
            "sum": None if self._obs_sum is None else np.asarray(self._obs_sum),
            "sumsq": None if self._obs_sumsq is None else np.asarray(self._obs_sumsq),
            "mean": getattr(self, "_obs_mean_cur", None),
            "std": getattr(self, "_obs_std_cur", None),
        }
        return Checkpoint.from_dict(ckpt)

    def load_checkpoint(self, checkpoint) -> None:
        super().load_checkpoint(checkpoint)
        flt = checkpoint.to_dict().get("obs_filter")
        if flt:
            self._obs_count = flt.get("count", 0)
            self._obs_sum = flt.get("sum")
            self._obs_sumsq = flt.get("sumsq")
            if flt.get("mean") is not None:
                self._obs_mean_cur = np.asarray(flt["mean"], np.float32)
                self._obs_std_cur = np.asarray(flt["std"], np.float32)
                for w in self._workers:
                    w.set_obs_stats.remote(self._obs_mean_cur, self._obs_std_cur)
