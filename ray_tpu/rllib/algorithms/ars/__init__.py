from ray_tpu.rllib.algorithms.ars.ars import ARS, ARSConfig

__all__ = ["ARS", "ARSConfig"]
