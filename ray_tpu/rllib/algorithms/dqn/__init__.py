from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig, dqn_loss  # noqa: F401
