"""DQN — deep Q-learning with target network + prioritized replay.

Reference: rllib/algorithms/dqn/dqn.py (+ dqn_torch_policy loss): epsilon-
greedy rollouts into a replay buffer, double-Q TD targets against a
periodically-synced target network, jitted TD update.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core import rl_module
from ray_tpu.rllib.core.learner import Learner, LearnerGroup
from ray_tpu.rllib.env.vector_env import VectorEnv
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)
from ray_tpu.rllib.utils.replay_buffers import PrioritizedReplayBuffer, ReplayBuffer


def q_forward(params, obs, spec):
    """The pi head doubles as the Q head for DQN (logits == Q-values)."""
    q, _ = rl_module.forward(params, obs, spec)
    return q


def dqn_loss(params, batch, spec, cfg):
    import jax.numpy as jnp

    q = q_forward(params, batch[OBS], spec)
    q_taken = q[jnp.arange(q.shape[0]), batch[ACTIONS].astype(jnp.int32)]
    td_target = batch["td_target"]
    td_error = q_taken - td_target
    weights = batch.get("weights", jnp.ones_like(td_error))
    loss = jnp.mean(weights * jnp.square(td_error) * 0.5)
    return loss, {"td_error_abs": jnp.abs(td_error).mean(), "q_mean": q_taken.mean()}


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.lr = 5e-4
        self.num_rollout_workers = 0  # DQN collects in-process by default
        self.train_batch_size = 32
        self.replay_buffer_capacity = 50_000
        self.learning_starts = 1000
        self.target_network_update_freq = 500
        self.rollout_steps_per_iter = 1000
        self.train_intensity = 4  # updates per env step / batch ratio
        self.epsilon_timesteps = 10_000
        self.initial_epsilon = 1.0
        self.final_epsilon = 0.02
        self.double_q = True
        self.prioritized_replay = True

    def training(self, *, replay_buffer_capacity=None, learning_starts=None,
                 target_network_update_freq=None, epsilon_timesteps=None,
                 final_epsilon=None, double_q=None, prioritized_replay=None,
                 rollout_steps_per_iter=None, train_intensity=None, **kwargs) -> "DQNConfig":
        super().training(**kwargs)
        for name, val in (
            ("replay_buffer_capacity", replay_buffer_capacity),
            ("learning_starts", learning_starts),
            ("target_network_update_freq", target_network_update_freq),
            ("epsilon_timesteps", epsilon_timesteps),
            ("final_epsilon", final_epsilon),
            ("double_q", double_q),
            ("prioritized_replay", prioritized_replay),
            ("rollout_steps_per_iter", rollout_steps_per_iter),
            ("train_intensity", train_intensity),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class DQN(Algorithm):
    @classmethod
    def get_default_config(cls) -> DQNConfig:
        return DQNConfig(cls)

    def setup(self, config: dict) -> None:
        import jax

        cfg: DQNConfig = self._algo_config
        import gymnasium as gym

        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        from ray_tpu.rllib.models import ModelCatalog

        self.module_spec = ModelCatalog.get_model_spec(
            probe.observation_space, probe.action_space, cfg.model_config()
        )
        assert self.module_spec.discrete, "DQN requires a discrete action space"
        probe.close()
        self.env = VectorEnv(cfg.env, max(cfg.num_envs_per_worker, 1), cfg.env_config, 0, seed=cfg.seed)
        self.learner = Learner(self.module_spec, dqn_loss, lr=cfg.lr, grad_clip=cfg.grad_clip, seed=cfg.seed)
        self.target_params = self.learner.get_weights()
        buf_cls = PrioritizedReplayBuffer if cfg.prioritized_replay else ReplayBuffer
        self.buffer = buf_cls(cfg.replay_buffer_capacity, seed=cfg.seed)
        self._timesteps_total = 0
        self._updates = 0
        self._episode_reward_window: list = []
        self._rng = np.random.default_rng(cfg.seed)
        self._q_fn = jax.jit(lambda p, o: q_forward(p, o, self.module_spec))

    def _epsilon(self) -> float:
        cfg = self._algo_config
        frac = min(1.0, self._timesteps_total / max(cfg.epsilon_timesteps, 1))
        return cfg.initial_epsilon + frac * (cfg.final_epsilon - cfg.initial_epsilon)

    def training_step(self) -> dict:
        import jax.numpy as jnp

        cfg: DQNConfig = self._algo_config
        metrics: dict = {}
        for _ in range(cfg.rollout_steps_per_iter):
            obs = self.env.current_obs().astype(np.float32)
            # Live params: intra-iteration learner updates steer exploration.
            q = np.asarray(self._q_fn(self.learner.params, jnp.asarray(obs)))
            actions = q.argmax(axis=-1)
            eps_mask = self._rng.random(len(actions)) < self._epsilon()
            random_actions = self._rng.integers(0, self.module_spec.action_dim, len(actions))
            actions = np.where(eps_mask, random_actions, actions)
            next_obs, rewards, dones, _ = self.env.step(actions)
            self.buffer.add(SampleBatch({
                OBS: obs, ACTIONS: actions, REWARDS: rewards,
                DONES: dones.astype(np.float32), NEXT_OBS: next_obs.astype(np.float32),
            }))
            self._timesteps_total += len(actions)
            if self._timesteps_total >= cfg.learning_starts and self._timesteps_total % max(1, cfg.train_intensity) == 0:
                metrics = self._train_once()
        stats_r, _ = self.env.pop_episode_stats()
        self._episode_reward_window += stats_r
        self._episode_reward_window = self._episode_reward_window[-100:]
        metrics["epsilon"] = self._epsilon()
        return metrics

    def _train_once(self) -> dict:
        import jax.numpy as jnp

        cfg: DQNConfig = self._algo_config
        batch = self.buffer.sample(cfg.train_batch_size)
        next_obs = jnp.asarray(batch[NEXT_OBS])
        q_next_target = np.asarray(self._q_fn(self._as_jax(self.target_params), next_obs))
        if cfg.double_q:
            q_next_online = np.asarray(self._q_fn(self.learner.params, next_obs))
            best = q_next_online.argmax(axis=-1)
            q_next = q_next_target[np.arange(len(best)), best]
        else:
            q_next = q_next_target.max(axis=-1)
        td_target = batch[REWARDS] + cfg.gamma * (1.0 - batch[DONES]) * q_next
        train_batch = SampleBatch({
            OBS: batch[OBS], ACTIONS: batch[ACTIONS], "td_target": td_target.astype(np.float32),
        })
        if "weights" in batch:
            train_batch["weights"] = batch["weights"]
        metrics = self.learner.update(train_batch, {})
        if isinstance(self.buffer, PrioritizedReplayBuffer):
            q = np.asarray(self._q_fn(self.learner.params, jnp.asarray(batch[OBS])))
            td_err = q[np.arange(len(td_target)), batch[ACTIONS].astype(int)] - td_target
            self.buffer.update_priorities(td_err)
        self._updates += 1
        if self._updates % cfg.target_network_update_freq == 0:
            self.target_params = self.learner.get_weights()
        return metrics

    @staticmethod
    def _as_jax(tree):
        import jax
        import jax.numpy as jnp

        return jax.tree_util.tree_map(jnp.asarray, tree)

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window)) if self._episode_reward_window else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "weights": self.learner.get_weights(),
            "target": self.target_params,
            "timesteps": self._timesteps_total,
        })

    def load_checkpoint(self, checkpoint) -> None:
        data = checkpoint.to_dict()
        self.learner.set_weights(data["weights"])
        self.target_params = data["target"]
        self._timesteps_total = data.get("timesteps", 0)

    def cleanup(self) -> None:
        self.env.close()

    def compute_single_action(self, obs, explore: bool = False):
        import jax.numpy as jnp

        if explore and self._rng.random() < self._epsilon():
            # Epsilon-greedy for external/inverted-control callers
            # (ExternalEnv serves actions through this entry point).
            return int(self._rng.integers(0, self.module_spec.action_dim))
        q = np.asarray(self._q_fn(self.learner.params, jnp.asarray(np.asarray(obs, np.float32))[None]))
        return int(q.argmax())
