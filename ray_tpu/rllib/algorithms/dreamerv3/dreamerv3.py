"""DreamerV3 — model-based RL: RSSM world model + actor-critic trained in
imagination (Hafner et al. 2023).

Reference: rllib/algorithms/dreamerv3/ (torch/tf world-model + dreamed
trajectories). This is a JAX re-derivation shaped for XLA: the whole update
— world-model sequence learning (lax.scan over time), H-step imagination
rollout (lax.scan over horizon), lambda-returns (reverse scan), and both
actor/critic losses — is ONE jitted function, so the compiler fuses the
model/actor/critic passes instead of round-tripping Python between them.

Core recipe kept from the paper, sized for small control tasks:
- RSSM with categorical latents (``latent_groups`` x ``latent_classes``),
  straight-through gradients, GRU deterministic path.
- symlog squashing for observation/reward/value regression targets.
- KL balancing (dyn vs rep) with free bits.
- Imagination actor-critic: continuous actors backprop straight through
  the (differentiable) dynamics; discrete actors use straight-through
  one-hot samples. EMA critic provides bootstrap targets; returns are
  scaled by an EMA 5-95 percentile range (the paper's robust normalizer).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.sac.sac import _mlp_apply, _mlp_params


def symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.expm1(jnp.abs(x))


def _gru_params(key, in_dim, hidden):
    import jax

    k1, k2, k3 = jax.random.split(key, 3)
    scale = 1.0 / np.sqrt(in_dim + hidden)
    import jax.numpy as jnp

    def mat(k, shape):
        return jax.random.uniform(k, shape, jnp.float32, -scale, scale)

    return {
        "wx": mat(k1, (in_dim, 3 * hidden)),
        "wh": mat(k2, (hidden, 3 * hidden)),
        "b": jnp.zeros((3 * hidden,), jnp.float32),
    }


def _gru_apply(p, x, h):
    import jax
    import jax.numpy as jnp

    hidden = h.shape[-1]
    hw = h @ p["wh"]
    gates = x @ p["wx"] + hw + p["b"]
    r, u, c = jnp.split(gates, 3, axis=-1)
    r = jax.nn.sigmoid(r)
    u = jax.nn.sigmoid(u)
    # Standard GRU candidate needs the RESET-gated recurrent term: the
    # fused matmul added h·Wc un-gated, so swap it for r·(h·Wc).
    c = jnp.tanh(c + (r - 1.0) * hw[..., 2 * hidden:])
    return u * h + (1.0 - u) * c


class DreamerV3Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DreamerV3)
        self.lr = 4e-4
        self.actor_lr = 1e-4
        self.critic_lr = 1e-4
        self.num_rollout_workers = 0  # driver-local env stepping
        # World model size.
        self.deter_size = 128
        self.latent_groups = 8
        self.latent_classes = 8
        self.model_hiddens = (128,)
        # Sequence replay.
        self.replay_capacity = 100_000
        self.batch_size = 8
        self.batch_length = 16
        self.learning_starts = 500
        self.rollout_steps_per_iter = 250
        self.train_intensity = 8  # env steps per model/actor/critic update
        # Losses.
        self.kl_dyn_scale = 0.5
        self.kl_rep_scale = 0.1
        self.free_bits = 1.0
        # Imagination.
        self.imagine_horizon = 10
        self.lambda_ = 0.95
        self.entropy_coeff = 3e-3
        self.critic_ema_decay = 0.98
        self.return_norm_decay = 0.99

    def training(self, *, actor_lr=None, critic_lr=None, deter_size=None,
                 latent_groups=None, latent_classes=None, replay_capacity=None,
                 batch_size=None, batch_length=None, learning_starts=None,
                 rollout_steps_per_iter=None, train_intensity=None,
                 kl_dyn_scale=None, kl_rep_scale=None, free_bits=None,
                 imagine_horizon=None, entropy_coeff=None,
                 critic_ema_decay=None, **kwargs) -> "DreamerV3Config":
        super().training(**kwargs)
        for name, value in (
            ("actor_lr", actor_lr), ("critic_lr", critic_lr),
            ("deter_size", deter_size), ("latent_groups", latent_groups),
            ("latent_classes", latent_classes), ("replay_capacity", replay_capacity),
            ("batch_size", batch_size), ("batch_length", batch_length),
            ("learning_starts", learning_starts),
            ("rollout_steps_per_iter", rollout_steps_per_iter),
            ("train_intensity", train_intensity),
            ("kl_dyn_scale", kl_dyn_scale), ("kl_rep_scale", kl_rep_scale),
            ("free_bits", free_bits), ("imagine_horizon", imagine_horizon),
            ("entropy_coeff", entropy_coeff), ("critic_ema_decay", critic_ema_decay),
        ):
            if value is not None:
                setattr(self, name, value)
        return self


class _SequenceReplay:
    """Ring buffer of ARRIVAL-convention rows (the paper's replay layout):
    row t holds (obs_t, action that LED to obs_t, reward received on
    arrival, cont_t = 0 iff obs_t is terminal, is_first). Episode starts
    store the reset observation with zero action/reward. Samples [B, L]
    subsequences; crossing episode boundaries is fine — IS_FIRST resets
    the RSSM state inside the scan."""

    def __init__(self, capacity: int, obs_dim: int, act_dim: int, seed: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity, act_dim), np.float32)
        self.rewards = np.zeros((capacity,), np.float32)
        self.cont = np.ones((capacity,), np.float32)  # 1 - terminated
        self.is_first = np.zeros((capacity,), np.float32)
        self._n = 0
        self._idx = 0
        self._rng = np.random.default_rng(seed)

    def add(self, obs, action, reward, terminated, is_first):
        i = self._idx
        self.obs[i] = obs
        self.actions[i] = action
        self.rewards[i] = reward
        self.cont[i] = 0.0 if terminated else 1.0
        self.is_first[i] = 1.0 if is_first else 0.0
        self._idx = (i + 1) % self.capacity
        self._n = min(self._n + 1, self.capacity)

    def __len__(self):
        return self._n

    def sample(self, batch_size: int, length: int) -> dict:
        assert self._n >= length, "not enough steps buffered"
        starts = self._rng.integers(0, self._n - length + 1, batch_size)
        if self._n == self.capacity:
            # Full ring: logical order starts at the write head; mapping
            # through it keeps sampled windows contiguous-in-time even when
            # they cross the physical wrap point.
            starts = (starts + self._idx) % self.capacity
        idx = (starts[:, None] + np.arange(length)[None, :]) % self.capacity  # [B, L]
        out = {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "cont": self.cont[idx],
            "is_first": self.is_first[idx].copy(),
        }
        # The first sampled step has no in-buffer predecessor context; treat
        # it as a sequence start so stale carry never leaks in.
        out["is_first"][:, 0] = 1.0
        return out


class DreamerV3(Algorithm):
    @classmethod
    def get_default_config(cls) -> DreamerV3Config:
        return DreamerV3Config(cls)

    # -- setup -----------------------------------------------------------
    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        cfg: DreamerV3Config = self._algo_config
        self.env = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        obs_space, act_space = self.env.observation_space, self.env.action_space
        self.obs_dim = int(np.prod(obs_space.shape))
        self.discrete = not hasattr(act_space, "low")
        if self.discrete:
            self.act_dim = int(act_space.n)
            self._act_scale = self._act_offset = None
        else:
            self.act_dim = int(np.prod(act_space.shape))
            low = np.asarray(act_space.low, np.float32).ravel()
            high = np.asarray(act_space.high, np.float32).ravel()
            self._act_scale = (high - low) / 2.0
            self._act_offset = (high + low) / 2.0

        G, C, D = cfg.latent_groups, cfg.latent_classes, cfg.deter_size
        self.latent_dim = G * C
        feat_dim = D + self.latent_dim
        H = tuple(cfg.model_hiddens)
        keys = jax.random.split(jax.random.PRNGKey(cfg.seed), 12)
        self.params = {
            "encoder": _mlp_params(keys[0], self.obs_dim, H, H[-1]),
            "gru_in": _mlp_params(keys[1], self.latent_dim + self.act_dim, (), D),
            "gru": _gru_params(keys[2], D, D),
            "prior": _mlp_params(keys[3], D, H, self.latent_dim),
            "post": _mlp_params(keys[4], D + H[-1], H, self.latent_dim),
            "decoder": _mlp_params(keys[5], feat_dim, H, self.obs_dim),
            "reward": _mlp_params(keys[6], feat_dim, H, 1),
            "cont": _mlp_params(keys[7], feat_dim, H, 1),
        }
        self.actor_params = {
            "pi": _mlp_params(keys[8], feat_dim, H, self.act_dim if self.discrete else 2 * self.act_dim),
        }
        self.critic_params = {"v": _mlp_params(keys[9], feat_dim, H, 1)}
        self.critic_ema = jax.tree_util.tree_map(jnp.asarray, self.critic_params)

        self.model_tx = optax.chain(optax.clip_by_global_norm(100.0), optax.adam(cfg.lr))
        self.actor_tx = optax.chain(optax.clip_by_global_norm(100.0), optax.adam(cfg.actor_lr))
        self.critic_tx = optax.chain(optax.clip_by_global_norm(100.0), optax.adam(cfg.critic_lr))
        self.model_opt = self.model_tx.init(self.params)
        self.actor_opt = self.actor_tx.init(self.actor_params)
        self.critic_opt = self.critic_tx.init(self.critic_params)
        # EMA of the 5-95 return percentile range (robust scale).
        self.return_scale = jnp.asarray(1.0)

        self.buffer = _SequenceReplay(cfg.replay_capacity, self.obs_dim, self.act_dim, cfg.seed)
        self._rng_np = np.random.default_rng(cfg.seed)
        self._key = jax.random.PRNGKey(cfg.seed + 1)
        self._timesteps_total = 0
        self._updates = 0
        self._episode_reward_window: list = []
        self._build_fns(cfg)

        # Live env state: obs + RSSM carry for acting.
        obs, _ = self.env.reset(seed=cfg.seed)
        self._obs = np.asarray(obs, np.float32).ravel()
        self._carry = (np.zeros((1, D), np.float32), np.zeros((1, self.latent_dim), np.float32))
        self._ep_reward = 0.0
        self._ep_first = True
        # Arrival-convention row for the reset observation.
        self.buffer.add(self._obs, np.zeros(self.act_dim, np.float32), 0.0, False, True)

    # -- jitted graph ----------------------------------------------------
    def _build_fns(self, cfg: DreamerV3Config):
        import jax
        import jax.numpy as jnp

        G, C = cfg.latent_groups, cfg.latent_classes
        latent_dim = self.latent_dim
        discrete = self.discrete
        act_dim = self.act_dim

        def sample_latent(logits, key):
            """Straight-through categorical sample per group, with the
            paper's 1% uniform mix for non-degenerate KLs."""
            logits = logits.reshape(logits.shape[:-1] + (G, C))
            probs = 0.99 * jax.nn.softmax(logits) + 0.01 / C
            idx = jax.random.categorical(key, jnp.log(probs))
            onehot = jax.nn.one_hot(idx, C)
            st = onehot + probs - jax.lax.stop_gradient(probs)
            return st.reshape(st.shape[:-2] + (latent_dim,)), jnp.log(probs)

        def kl(lp_a, lp_b):
            # KL(a || b) for grouped categoricals given log-probs [., G, C].
            return (jnp.exp(lp_a) * (lp_a - lp_b)).sum(-1).sum(-1)

        def obs_step(params, h, z, a_prev, embed, is_first, key):
            h = jnp.where(is_first[:, None], jnp.zeros_like(h), h)
            z = jnp.where(is_first[:, None], jnp.zeros_like(z), z)
            a_prev = jnp.where(is_first[:, None], jnp.zeros_like(a_prev), a_prev)
            x = jax.nn.silu(_mlp_apply(params["gru_in"], jnp.concatenate([z, a_prev], -1)))
            h = _gru_apply(params["gru"], x, h)
            prior_logits = _mlp_apply(params["prior"], h)
            post_logits = _mlp_apply(params["post"], jnp.concatenate([h, embed], -1))
            z_new, post_lp = sample_latent(post_logits, key)
            _, prior_lp = sample_latent(prior_logits, key)  # logits→logprobs only
            return h, z_new, prior_lp, post_lp

        def actor_dist(actor_params, feat):
            out = _mlp_apply(actor_params["pi"], feat)
            if discrete:
                return out  # logits
            mean, log_std = jnp.split(out, 2, -1)
            return jnp.tanh(mean), jnp.clip(log_std, -4.0, 1.0)

        def actor_sample(actor_params, feat, key):
            """Differentiable action sample + entropy."""
            if discrete:
                logits = actor_dist(actor_params, feat)
                probs = jax.nn.softmax(logits)
                idx = jax.random.categorical(key, logits)
                onehot = jax.nn.one_hot(idx, act_dim)
                a = onehot + probs - jax.lax.stop_gradient(probs)
                ent = -(probs * jax.nn.log_softmax(logits)).sum(-1)
                return a, ent
            mean, log_std = actor_dist(actor_params, feat)
            std = jnp.exp(log_std)
            a = mean + std * jax.random.normal(key, mean.shape)
            ent = (0.5 * jnp.log(2 * jnp.pi * jnp.e) + log_std).sum(-1)
            return jnp.clip(a, -1.0, 1.0), ent

        def world_loss(params, batch, key):
            B, L = batch["obs"].shape[:2]
            obs_sym = symlog(batch["obs"])
            embeds = _mlp_apply(params["encoder"], obs_sym.reshape(B * L, -1))
            embeds = jax.nn.silu(embeds).reshape(B, L, -1)
            # Arrival convention: row t already stores the action that led
            # INTO obs_t, so the reward/cont heads at state s_t regress
            # quantities s_t can actually explain (r received on arrival,
            # terminality of obs_t) — matching how imagination reads them
            # at the NEXT state.
            a_prev = batch["actions"]
            keys = jax.random.split(key, L)

            def step(carry, t):
                h, z = carry
                h, z, prior_lp, post_lp = obs_step(
                    params, h, z, a_prev[:, t], embeds[:, t],
                    batch["is_first"][:, t], keys[t],
                )
                return (h, z), (h, z, prior_lp, post_lp)

            D = params["gru"]["wh"].shape[0]
            init = (jnp.zeros((B, D)), jnp.zeros((B, latent_dim)))
            _, (hs, zs, prior_lps, post_lps) = jax.lax.scan(step, init, jnp.arange(L))
            # [L, B, ...] -> [B, L, ...]
            hs, zs = hs.swapaxes(0, 1), zs.swapaxes(0, 1)
            prior_lps, post_lps = prior_lps.swapaxes(0, 1), post_lps.swapaxes(0, 1)
            feat = jnp.concatenate([hs, zs], -1)

            obs_hat = _mlp_apply(params["decoder"], feat)
            recon = 0.5 * ((obs_hat - obs_sym) ** 2).sum(-1)
            rew_hat = _mlp_apply(params["reward"], feat)[..., 0]
            rew_loss = 0.5 * (rew_hat - symlog(batch["rewards"])) ** 2
            cont_logit = _mlp_apply(params["cont"], feat)[..., 0]
            cont_loss = -(
                batch["cont"] * jax.nn.log_sigmoid(cont_logit)
                + (1 - batch["cont"]) * jax.nn.log_sigmoid(-cont_logit)
            )
            dyn = jnp.maximum(kl(jax.lax.stop_gradient(post_lps), prior_lps), cfg.free_bits)
            rep = jnp.maximum(kl(post_lps, jax.lax.stop_gradient(prior_lps)), cfg.free_bits)
            loss = (
                recon + rew_loss + cont_loss
                + cfg.kl_dyn_scale * dyn + cfg.kl_rep_scale * rep
            ).mean()
            aux = {
                "model_loss": loss, "recon_loss": recon.mean(),
                "reward_loss": rew_loss.mean(), "kl_dyn": dyn.mean(),
                "states": (jax.lax.stop_gradient(hs), jax.lax.stop_gradient(zs)),
            }
            return loss, aux

        def imagine(params, actor_params, h0, z0, key):
            """Roll the PRIOR forward H steps driven by the actor; fully
            differentiable for dynamics-backprop actor gradients."""
            def step(carry, k):
                h, z = carry
                feat = jnp.concatenate([h, z], -1)
                ka, kz = jax.random.split(k)
                a, ent = actor_sample(actor_params, feat, ka)
                x = jax.nn.silu(_mlp_apply(params["gru_in"], jnp.concatenate([z, a], -1)))
                h2 = _gru_apply(params["gru"], x, h)
                prior_logits = _mlp_apply(params["prior"], h2)
                z2, _ = sample_latent(prior_logits, kz)
                return (h2, z2), (h2, z2, ent)

            keys = jax.random.split(key, cfg.imagine_horizon)
            _, (hs, zs, ents) = jax.lax.scan(step, (h0, z0), keys)
            feat = jnp.concatenate([hs, zs], -1)  # [H, N, feat]
            feat0 = jnp.concatenate([h0, z0], -1)[None]
            return jnp.concatenate([feat0, feat], 0), ents  # [H+1, N, feat]

        def lambda_returns(rewards, conts, values):
            """values[t] bootstraps; reverse scan over H steps."""
            def step(carry, t):
                ret = rewards[t] + cfg.gamma * conts[t] * (
                    (1 - cfg.lambda_) * values[t + 1] + cfg.lambda_ * carry
                )
                return ret, ret

            last = values[-1]
            _, rets = jax.lax.scan(step, last, jnp.arange(len(rewards) - 1, -1, -1))
            return rets[::-1]  # [H, N]

        def ac_loss(actor_params, critic_params, params, critic_ema, states, scale, key):
            hs, zs = states
            h0 = hs.reshape(-1, hs.shape[-1])
            z0 = zs.reshape(-1, zs.shape[-1])
            feats, ents = imagine(params, actor_params, h0, z0, key)  # [H+1,N,f]
            rew = symexp(_mlp_apply(params["reward"], feats)[..., 0])[1:]  # [H,N]
            cont = jax.nn.sigmoid(_mlp_apply(params["cont"], feats)[..., 0])[1:]
            v_ema = symexp(_mlp_apply(critic_ema["v"], feats)[..., 0])  # [H+1,N]
            rets = lambda_returns(rew, cont, v_ema)  # [H, N]
            # Discount weights: imagination beyond a predicted episode end
            # shouldn't carry gradient weight.
            weights = jnp.concatenate(
                [jnp.ones_like(cont[:1]), jnp.cumprod(cont[:-1], 0)], 0
            )
            weights = jax.lax.stop_gradient(weights)
            # Actor: maximize normalized return (grads flow through the
            # dynamics into the actions) + entropy bonus.
            norm_rets = rets / jnp.maximum(scale, 1.0)
            actor_loss = -(weights * norm_rets).mean() - cfg.entropy_coeff * (weights * ents).mean()
            # Critic regresses symlog(lambda-return) on sg(features).
            v_pred = _mlp_apply(critic_params["v"], jax.lax.stop_gradient(feats[:-1]))[..., 0]
            critic_loss = (0.5 * weights * (v_pred - jax.lax.stop_gradient(symlog(rets))) ** 2).mean()
            # Robust return scale update (5-95 percentile range EMA).
            flat = rets.reshape(-1)
            rng = jnp.percentile(flat, 95) - jnp.percentile(flat, 5)
            new_scale = cfg.return_norm_decay * scale + (1 - cfg.return_norm_decay) * rng
            aux = {
                "actor_loss": actor_loss, "critic_loss": critic_loss,
                "imagined_return": rets.mean(), "actor_entropy": ents.mean(),
                "return_scale": new_scale,
            }
            return actor_loss + critic_loss, aux

        def update(params, actor_params, critic_params, critic_ema,
                   model_opt, actor_opt, critic_opt, scale, batch, key):
            k1, k2 = jax.random.split(key)
            (m_loss, m_aux), m_grads = jax.value_and_grad(world_loss, has_aux=True)(
                params, batch, k1
            )
            upd, model_opt = self.model_tx.update(m_grads, model_opt, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, upd)

            def split_loss(ap, cp):
                return ac_loss(ap, cp, params, critic_ema, m_aux["states"], scale, k2)

            (_, a_aux), (a_grads, c_grads) = jax.value_and_grad(
                split_loss, argnums=(0, 1), has_aux=True
            )(actor_params, critic_params)
            upd, actor_opt = self.actor_tx.update(a_grads, actor_opt, actor_params)
            actor_params = jax.tree_util.tree_map(lambda p, u: p + u, actor_params, upd)
            upd, critic_opt = self.critic_tx.update(c_grads, critic_opt, critic_params)
            critic_params = jax.tree_util.tree_map(lambda p, u: p + u, critic_params, upd)
            d = cfg.critic_ema_decay
            critic_ema = jax.tree_util.tree_map(
                lambda e, p: d * e + (1 - d) * p, critic_ema, critic_params
            )
            aux = {
                "model_loss": m_aux["model_loss"], "recon_loss": m_aux["recon_loss"],
                "reward_loss": m_aux["reward_loss"], "kl_dyn": m_aux["kl_dyn"],
                "actor_loss": a_aux["actor_loss"], "critic_loss": a_aux["critic_loss"],
                "imagined_return": a_aux["imagined_return"],
                "actor_entropy": a_aux["actor_entropy"],
            }
            return (params, actor_params, critic_params, critic_ema,
                    model_opt, actor_opt, critic_opt, a_aux["return_scale"], aux)

        self._update_fn = jax.jit(update)

        def policy_step(params, actor_params, h, z, a_prev, obs, is_first, key, explore):
            # Separate subkeys: reusing one key would correlate the
            # posterior latent draw with the exploration noise every step.
            k_latent, k_action = jax.random.split(key)
            embed = jax.nn.silu(_mlp_apply(params["encoder"], symlog(obs)))
            h, z, _, _ = obs_step(params, h, z, a_prev, embed, is_first, k_latent)
            feat = jnp.concatenate([h, z], -1)
            if discrete:
                logits = actor_dist(actor_params, feat)
                a_env = jnp.where(
                    explore,
                    jax.random.categorical(k_action, logits),
                    jnp.argmax(logits, -1),
                )
                a_onehot = jax.nn.one_hot(a_env, act_dim)
                return h, z, a_onehot, a_env
            mean, log_std = actor_dist(actor_params, feat)
            noise = jax.random.normal(k_action, mean.shape) * jnp.exp(log_std)
            a = jnp.clip(jnp.where(explore, mean + noise, mean), -1.0, 1.0)
            return h, z, a, a

        self._policy_fn = jax.jit(policy_step, static_argnames=("explore",))

    # -- acting ----------------------------------------------------------
    def _act(self, explore: bool = True):
        import jax
        import jax.numpy as jnp

        self._key, key = jax.random.split(self._key)
        h, z = self._carry
        a_prev = getattr(self, "_a_prev", None)
        if a_prev is None:
            a_prev = np.zeros((1, self.act_dim), np.float32)
        h, z, a_store, a_env = self._policy_fn(
            self.params, self.actor_params, jnp.asarray(h), jnp.asarray(z),
            jnp.asarray(a_prev), jnp.asarray(self._obs[None]),
            jnp.asarray([1.0 if self._ep_first else 0.0]), key, explore,
        )
        self._carry = (np.asarray(h), np.asarray(z))
        a_store = np.asarray(a_store)[0]
        self._a_prev = a_store[None]
        if self.discrete:
            return a_store, int(np.asarray(a_env)[0])
        env_a = a_store * self._act_scale + self._act_offset
        return a_store, env_a.reshape(self.env.action_space.shape)

    # -- Trainable protocol ---------------------------------------------
    def training_step(self) -> dict:
        import jax

        cfg: DreamerV3Config = self._algo_config
        metrics: dict = {}
        for _ in range(cfg.rollout_steps_per_iter):
            a_store, a_env = self._act(explore=True)
            obs2, r, term, trunc, _ = self.env.step(a_env)
            # Arrival row: the observation we LANDED in, the action that
            # took us there, the reward received, and its terminality —
            # this keeps the reward/cont heads predictable from the state
            # that contains the causing action (paper's replay layout).
            self.buffer.add(
                np.asarray(obs2, np.float32).ravel(), a_store, float(r), term, False
            )
            self._ep_first = False
            self._ep_reward += float(r)
            self._timesteps_total += 1
            if term or trunc:
                self._episode_reward_window.append(self._ep_reward)
                self._episode_reward_window = self._episode_reward_window[-100:]
                self._ep_reward = 0.0
                obs2, _ = self.env.reset()
                self._carry = (
                    np.zeros_like(self._carry[0]), np.zeros_like(self._carry[1])
                )
                self._a_prev = np.zeros((1, self.act_dim), np.float32)
                self._ep_first = True
                self.buffer.add(
                    np.asarray(obs2, np.float32).ravel(),
                    np.zeros(self.act_dim, np.float32), 0.0, False, True,
                )
            self._obs = np.asarray(obs2, np.float32).ravel()
            if (
                len(self.buffer) >= max(cfg.learning_starts, cfg.batch_length + 1)
                and self._timesteps_total % max(1, cfg.train_intensity) == 0
            ):
                metrics = self._train_once()
        return metrics

    def _train_once(self) -> dict:
        import jax
        import jax.numpy as jnp

        cfg: DreamerV3Config = self._algo_config
        batch = self.buffer.sample(cfg.batch_size, cfg.batch_length)
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        self._key, key = jax.random.split(self._key)
        (self.params, self.actor_params, self.critic_params, self.critic_ema,
         self.model_opt, self.actor_opt, self.critic_opt, self.return_scale,
         aux) = self._update_fn(
            self.params, self.actor_params, self.critic_params, self.critic_ema,
            self.model_opt, self.actor_opt, self.critic_opt, self.return_scale,
            batch, key,
        )
        self._updates += 1
        return {k: float(v) for k, v in aux.items()}

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window))
            if self._episode_reward_window
            else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def compute_single_action(self, obs, explore: bool = False):
        """Greedy action through a TRANSIENT RSSM carry (does not disturb
        the training rollout's live carry)."""
        saved = (self._carry, self._obs, self._ep_first, getattr(self, "_a_prev", None))
        try:
            self._obs = np.asarray(obs, np.float32).ravel()
            self._ep_first = True  # no history for a one-shot query
            self._carry = (
                np.zeros_like(self._carry[0]), np.zeros_like(self._carry[1])
            )
            self._a_prev = np.zeros((1, self.act_dim), np.float32)
            _, a_env = self._act(explore=explore)
            return a_env
        finally:
            self._carry, self._obs, self._ep_first, self._a_prev = saved

    def _evaluate_local(self, duration: int, by_episodes: bool):
        """Greedy episodes with a PERSISTENT RSSM carry across each episode
        (the base loop's stateless compute_single_action would wipe the
        world-model memory every step)."""
        env = self._make_eval_env()
        saved = (self._carry, self._obs, self._ep_first, getattr(self, "_a_prev", None))
        rewards, lens, steps = [], [], 0
        try:
            for _ in range(duration if by_episodes else 64):
                obs, _ = env.reset()
                self._obs = np.asarray(obs, np.float32).ravel()
                self._ep_first = True
                self._carry = (
                    np.zeros_like(self._carry[0]), np.zeros_like(self._carry[1])
                )
                self._a_prev = np.zeros((1, self.act_dim), np.float32)
                total, length = 0.0, 0
                for _ in range(10_000):
                    _, a_env = self._act(explore=False)
                    self._ep_first = False
                    obs, r, terminated, truncated, _ = env.step(a_env)
                    self._obs = np.asarray(obs, np.float32).ravel()
                    total += float(r)
                    length += 1
                    steps += 1
                    if terminated or truncated:
                        break
                    if not by_episodes and steps >= duration:
                        break
                rewards.append(total)
                lens.append(length)
                if not by_episodes and steps >= duration:
                    break
        finally:
            self._carry, self._obs, self._ep_first, self._a_prev = saved
            try:
                env.close()
            except Exception:
                pass
        return rewards, lens

    def save_checkpoint(self):
        import jax

        from ray_tpu.air.checkpoint import Checkpoint

        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa: E731
        return Checkpoint.from_dict({
            "params": to_np(self.params),
            "actor": to_np(self.actor_params),
            "critic": to_np(self.critic_params),
            "critic_ema": to_np(self.critic_ema),
            "return_scale": float(self.return_scale),
            "timesteps": self._timesteps_total,
            "updates": self._updates,
        })

    def load_checkpoint(self, checkpoint) -> None:
        import jax
        import jax.numpy as jnp

        data = checkpoint.to_dict()
        to_jax = lambda t: jax.tree_util.tree_map(jnp.asarray, t)  # noqa: E731
        self.params = to_jax(data["params"])
        self.actor_params = to_jax(data["actor"])
        self.critic_params = to_jax(data["critic"])
        self.critic_ema = to_jax(data["critic_ema"])
        self.return_scale = jnp.asarray(data["return_scale"])
        self._timesteps_total = data.get("timesteps", 0)
        self._updates = data.get("updates", 0)

    def cleanup(self) -> None:
        if getattr(self, "env", None) is not None:
            try:
                self.env.close()
            except Exception:
                pass

    def get_policy_weights(self):
        return {"actor": self.actor_params, "model": self.params}
