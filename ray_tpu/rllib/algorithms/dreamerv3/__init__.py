from ray_tpu.rllib.algorithms.dreamerv3.dreamerv3 import (  # noqa: F401
    DreamerV3,
    DreamerV3Config,
)
