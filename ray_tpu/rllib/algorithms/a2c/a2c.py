"""A2C — synchronous advantage actor-critic.

Reference: rllib/algorithms/a2c/a2c.py (A2C = A3C made synchronous: one
gradient step per synchronous sample round, no surrogate clipping). The loss
is a single jitted policy-gradient step on GAE advantages — the degenerate
case of PPO with one epoch and no ratio clip, which is exactly how the
reference implements it on top of the shared policy-gradient machinery.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    ADVANTAGES,
    OBS,
    VALUE_TARGETS,
    SampleBatch,
)


def a2c_loss(params, batch, spec, cfg):
    import jax.numpy as jnp

    from ray_tpu.rllib.core import rl_module

    logp, entropy, value = rl_module.action_logp_and_entropy(
        params, batch[OBS], batch[ACTIONS], spec
    )
    adv = batch[ADVANTAGES]
    policy_loss = -jnp.mean(logp * adv)
    vf_loss = 0.5 * jnp.mean((value - batch[VALUE_TARGETS]) ** 2)
    entropy_mean = entropy.mean()
    total = policy_loss + cfg["vf_loss_coeff"] * vf_loss - cfg["entropy_coeff"] * entropy_mean
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy_mean,
    }


class A2CConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A2C)
        self.lr = 1e-3
        self.train_batch_size = 500
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0
        self.microbatch_size: Optional[int] = None

    def training(self, *, vf_loss_coeff: Optional[float] = None,
                 entropy_coeff: Optional[float] = None,
                 microbatch_size: Optional[int] = None, **kwargs) -> "A2CConfig":
        super().training(**kwargs)
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        if microbatch_size is not None:
            self.microbatch_size = microbatch_size
        return self


class A2C(Algorithm):
    @classmethod
    def get_default_config(cls) -> A2CConfig:
        return A2CConfig(cls)

    def _build_learner_group(self, cfg: A2CConfig) -> LearnerGroup:
        return LearnerGroup(
            self.module_spec,
            a2c_loss,
            lr=cfg.lr,
            grad_clip=cfg.grad_clip,
            seed=cfg.seed,
            num_learners=cfg.num_learners,
            num_tpus_per_learner=cfg.num_tpus_per_learner,
        )

    def training_step(self) -> dict:
        cfg: A2CConfig = self._algo_config
        per_worker = max(
            1, cfg.train_batch_size // max(self.workers.num_workers, 1) // cfg.num_envs_per_worker
        )
        batches = self.workers.sample(per_worker)
        batch = SampleBatch.concat_samples(batches)
        self._timesteps_total += batch.count
        loss_cfg = {"vf_loss_coeff": cfg.vf_loss_coeff, "entropy_coeff": cfg.entropy_coeff}
        # Default: one gradient step on the whole round (reference: a2c.py
        # training_step). microbatch_size instead takes one optimizer step
        # PER microbatch (sequential SGD over the round) — it bounds learner
        # memory but is not gradient-accumulation-equivalent to the full step.
        if cfg.microbatch_size:
            metrics = {}
            for start in range(0, batch.count, cfg.microbatch_size):
                metrics = self.learner_group.update(
                    batch.slice(start, min(start + cfg.microbatch_size, batch.count)), loss_cfg
                )
        else:
            metrics = self.learner_group.update(batch, loss_cfg)
        self.workers.sync_weights(self.learner_group.get_weights())
        metrics["num_env_steps_sampled_this_iter"] = batch.count
        return dict(metrics)
