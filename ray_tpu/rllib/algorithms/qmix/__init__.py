from ray_tpu.rllib.algorithms.qmix.qmix import QMIX, QMIXConfig  # noqa: F401
