"""AlphaZero — single-player MCTS with learned priors and values.

Reference: rllib/algorithms/alpha_zero/ (alpha_zero.py, mcts.py,
ranked_rewards.py): the reference's "contributed" single-player AlphaZero
— a PUCT Monte-Carlo tree search over a STATE-CLONEABLE environment
(``get_state``/``set_state``), with child priors from the policy network,
leaf evaluation by the value network (no rollouts), Dirichlet noise at the
root, and self-play targets: the policy regresses onto MCTS visit
distributions, the value onto the episode's ranked reward. Single-player
returns are unbounded, so the RANKED-REWARDS (R2) transform binarizes
each return against a percentile of recent self-play returns — the
two-player win/loss signal AlphaZero's value head expects.

The network is the shared RLModule MLP (policy + value heads); its update
is one jitted CE+MSE step. The search itself is numpy on CPU — it is
env-bound (each expansion steps the real cloned env), exactly like the
reference's numpy MCTS.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.off_policy import OffPolicyTraining


class StateCloneWrapper:
    """Make a gymnasium env MCTS-plannable: snapshot/restore its state.

    Works for envs whose full dynamics state lives in ``unwrapped.state``
    plus step counters (CartPole & friends). Other envs can subclass and
    override get_state/set_state (reference: envs used with AlphaZero must
    provide exactly these two methods)."""

    def __init__(self, env, horizon: int = 200):
        # Strip gym wrappers (TimeLimit above all): their hidden counters
        # are NOT part of get_state, so search simulations would silently
        # consume the real episode's budget. The horizon here replaces
        # TimeLimit and travels with the cloned state.
        self.env = getattr(env, "unwrapped", env)
        self.horizon = horizon
        self._t = 0

    @property
    def action_space(self):
        return self.env.action_space

    @property
    def observation_space(self):
        return self.env.observation_space

    def reset(self, *, seed=None):
        obs, info = self.env.reset(seed=seed)
        self._t = 0
        return np.asarray(obs, np.float32), info

    def step(self, action):
        obs, reward, term, trunc, info = self.env.step(int(action))
        self._t += 1
        if self._t >= self.horizon:
            trunc = True
        return np.asarray(obs, np.float32), float(reward), term, trunc, info

    def get_state(self):
        import copy

        u = self.env.unwrapped
        # steps_beyond_terminated MUST travel with the state: a terminal
        # step inside one search simulation otherwise poisons the shared
        # env for every later clone (gymnasium latches the flag).
        return (
            copy.deepcopy(u.state),
            getattr(u, "steps_beyond_terminated", None),
            self._t,
        )

    def set_state(self, state):
        import copy

        u = self.env.unwrapped
        u.state = copy.deepcopy(state[0])
        if hasattr(u, "steps_beyond_terminated"):
            u.steps_beyond_terminated = state[1]
        self._t = state[2]
        return np.asarray(u.state, np.float32)

    def close(self):
        self.env.close()


class _Node:
    __slots__ = (
        "parent", "action", "state", "obs", "reward", "done",
        "expanded", "children", "priors", "child_q_sum", "child_visits",
    )

    def __init__(self, parent, action, state, obs, reward, done, n_actions):
        self.parent = parent
        self.action = action
        self.state = state
        self.obs = obs
        self.reward = reward
        self.done = done
        self.expanded = False
        self.children: dict = {}
        self.priors = np.zeros(n_actions, np.float32)
        self.child_q_sum = np.zeros(n_actions, np.float32)
        self.child_visits = np.zeros(n_actions, np.float32)

    def visits(self):
        return self.parent.child_visits[self.action] if self.parent else 0.0


class MCTS:
    """PUCT search (reference: mcts.py, after brilee/python_uct)."""

    def __init__(self, env, predict, n_actions, *, num_sims=25, c_puct=1.4,
                 gamma=0.997, dirichlet_alpha=0.3, dirichlet_eps=0.25, rng=None):
        self.env = env
        self.predict = predict  # obs -> (prior probs, value)
        self.n_actions = n_actions
        self.num_sims = num_sims
        self.c_puct = c_puct
        self.gamma = gamma
        self.alpha = dirichlet_alpha
        self.eps = dirichlet_eps
        self.rng = rng or np.random.default_rng(0)

    def _select_action(self, node: _Node) -> int:
        q = node.child_q_sum / (1.0 + node.child_visits)
        # Min-max-normalize Q into [0,1] over the values seen THIS search
        # (MuZero's MinMaxStats): PUCT's prior term assumes bounded values,
        # and dense per-step rewards otherwise dwarf it — the search then
        # commits to whichever child it expanded first. With no spread yet
        # (min == max), Q carries NO ranking information, so it contributes
        # zero and the prior/visit term alone drives selection.
        if self._q_max > self._q_min:
            q = np.where(
                node.child_visits > 0,
                (q - self._q_min) / (self._q_max - self._q_min),
                0.0,
            )
        else:
            q = np.zeros_like(q)
        total = max(1.0, node.child_visits.sum())
        u = self.c_puct * math.sqrt(total) * node.priors / (1.0 + node.child_visits)
        return int(np.argmax(q + u))

    def search(self, root_obs, root_state, temperature: float = 1.0):
        self._q_min, self._q_max = float("inf"), float("-inf")
        root = _Node(None, 0, root_state, root_obs, 0.0, False, self.n_actions)
        priors, _ = self.predict(root_obs)
        noise = self.rng.dirichlet([self.alpha] * self.n_actions)
        root.priors = ((1 - self.eps) * priors + self.eps * noise).astype(np.float32)
        root.expanded = True

        for _ in range(self.num_sims):
            node = root
            # SELECT down to a leaf.
            while node.expanded and not node.done:
                a = self._select_action(node)
                child = node.children.get(a)
                if child is None:
                    # EXPAND: step the real env from the parent's state.
                    self.env.set_state(node.state)
                    obs, reward, term, trunc, _ = self.env.step(a)
                    child = _Node(
                        node, a, self.env.get_state(), obs, reward,
                        term or trunc, self.n_actions,
                    )
                    node.children[a] = child
                    node = child
                    break
                node = child
            # EVALUATE the leaf with the value net (no rollouts).
            if node.done:
                value = 0.0
            else:
                priors, value = self.predict(node.obs)
                node.priors = priors.astype(np.float32)
                node.expanded = True
            # BACKUP discounted value + path rewards.
            while node.parent is not None:
                value = node.reward + self.gamma * value
                node.parent.child_q_sum[node.action] += value
                node.parent.child_visits[node.action] += 1.0
                mean_q = (
                    node.parent.child_q_sum[node.action]
                    / (1.0 + node.parent.child_visits[node.action])
                )
                self._q_min = min(self._q_min, mean_q)
                self._q_max = max(self._q_max, mean_q)
                node = node.parent

        visits = root.child_visits
        if temperature <= 1e-6:
            probs = np.zeros_like(visits)
            probs[int(np.argmax(visits))] = 1.0
        else:
            scaled = np.power(visits, 1.0 / temperature)
            probs = scaled / max(scaled.sum(), 1e-8)
        return probs


class RankedRewardsBuffer:
    """R2 transform (reference: ranked_rewards.py): binarize a return
    against a percentile of recent self-play returns."""

    def __init__(self, max_length: int = 100, percentile: float = 75.0, rng=None):
        self.max_length = max_length
        self.percentile = percentile
        self.values: list = []
        self.rng = rng or np.random.default_rng(0)

    def add(self, value: float):
        self.values.append(float(value))
        self.values = self.values[-self.max_length :]

    def normalize(self, value: float) -> float:
        if not self.values:
            return 0.0
        threshold = np.percentile(self.values, self.percentile)
        if value > threshold:
            return 1.0
        if value < threshold:
            return -1.0
        # Tie-break with the ALGORITHM's seeded stream (reproducibility).
        return 1.0 if self.rng.random() < 0.5 else -1.0


class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or AlphaZero)
        self.lr = 5e-3
        self.num_rollout_workers = 0
        self.train_batch_size = 128
        self.num_sims = 25
        self.c_puct = 1.4
        self.dirichlet_alpha = 0.3
        self.dirichlet_epsilon = 0.25
        self.temperature_timesteps = 2000  # anneal tau 1.0 -> 0.1
        self.episodes_per_iter = 3
        self.updates_per_iter = 20
        self.horizon = 200
        self.replay_capacity = 20_000
        self.ranked_rewards = True
        self.r2_percentile = 75.0
        self.r2_buffer_length = 100
        # Value-head target: "return" regresses each state's DISCOUNTED
        # return-to-go (matches the search's backup semantics — the right
        # choice for dense-reward envs, where an untrained value net gives
        # the search a depth bias until real values fill in); "r2" is the
        # reference's ranked-reward final-outcome target for sparse
        # outcome-style tasks.
        self.value_target = "return"

    def training(self, *, num_sims=None, c_puct=None, dirichlet_alpha=None,
                 dirichlet_epsilon=None, temperature_timesteps=None,
                 episodes_per_iter=None, updates_per_iter=None, horizon=None,
                 replay_capacity=None, ranked_rewards=None, r2_percentile=None,
                 r2_buffer_length=None, value_target=None, **kwargs) -> "AlphaZeroConfig":
        super().training(**kwargs)
        for name, val in (
            ("num_sims", num_sims), ("c_puct", c_puct),
            ("dirichlet_alpha", dirichlet_alpha),
            ("dirichlet_epsilon", dirichlet_epsilon),
            ("temperature_timesteps", temperature_timesteps),
            ("episodes_per_iter", episodes_per_iter),
            ("updates_per_iter", updates_per_iter), ("horizon", horizon),
            ("replay_capacity", replay_capacity),
            ("ranked_rewards", ranked_rewards),
            ("r2_percentile", r2_percentile),
            ("r2_buffer_length", r2_buffer_length),
            ("value_target", value_target),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class AlphaZero(OffPolicyTraining, Algorithm):
    @classmethod
    def get_default_config(cls) -> AlphaZeroConfig:
        return AlphaZeroConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.core import rl_module
        from ray_tpu.rllib.models import ModelCatalog

        cfg: AlphaZeroConfig = self._algo_config
        base = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        assert hasattr(base.action_space, "n"), "AlphaZero needs discrete actions"
        self.env = (
            base if hasattr(base, "get_state") else StateCloneWrapper(base, cfg.horizon)
        )
        self.n_actions = int(base.action_space.n)
        self.spec = ModelCatalog.get_model_spec(
            base.observation_space, base.action_space, cfg.model_config()
        )
        self.params = rl_module.init_params(jax.random.PRNGKey(cfg.seed), self.spec)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self._rng = np.random.default_rng(cfg.seed)
        self._timesteps_total = 0
        self._episode_reward_window: list = []
        self.r2 = RankedRewardsBuffer(cfg.r2_buffer_length, cfg.r2_percentile, rng=self._rng)
        self._replay: list = []  # (obs, visit_probs, z)

        spec = self.spec
        fwd = jax.jit(lambda p, o: rl_module.forward(p, o, spec))

        def predict(obs):
            logits, value = fwd(self.params, np.asarray(obs, np.float32)[None])
            probs = np.asarray(jax.nn.softmax(logits[0]))
            return probs, float(value[0])

        self._predict = predict

        def update(params, opt_state, obs, target_pi, target_v):
            def loss_fn(p):
                logits, value = rl_module.forward(p, obs, spec)
                logp = jax.nn.log_softmax(logits)
                pi_loss = -jnp.mean(jnp.sum(target_pi * logp, axis=-1))
                v_loss = jnp.mean(jnp.square(value - target_v))
                return pi_loss + v_loss, {"pi_loss": pi_loss, "v_loss": v_loss}

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux = dict(aux)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._update = jax.jit(update)

    def _temperature(self) -> float:
        cfg = self._algo_config
        frac = min(1.0, self._timesteps_total / max(cfg.temperature_timesteps, 1))
        return 1.0 + frac * (0.1 - 1.0)

    def _self_play_episode(self) -> float:
        cfg: AlphaZeroConfig = self._algo_config
        mcts = MCTS(
            self.env, self._predict, self.n_actions,
            num_sims=cfg.num_sims, c_puct=cfg.c_puct, gamma=cfg.gamma,
            dirichlet_alpha=cfg.dirichlet_alpha, dirichlet_eps=cfg.dirichlet_epsilon,
            rng=self._rng,
        )
        obs, _ = self.env.reset(seed=int(self._rng.integers(1 << 31)))
        episode: list = []
        rewards: list = []
        total = 0.0
        done = False
        while not done:
            state = self.env.get_state()
            probs = mcts.search(obs, state, temperature=self._temperature())
            action = int(self._rng.choice(self.n_actions, p=probs))
            episode.append((obs, probs))
            # The search left the env in an arbitrary cloned state.
            self.env.set_state(state)
            obs, reward, term, trunc, _ = self.env.step(action)
            rewards.append(reward)
            total += reward
            done = term or trunc
            self._timesteps_total += 1
        if cfg.value_target == "return":
            # Discounted return-to-go per state: the scale the search's
            # backup mixes with real path rewards.
            g = 0.0
            targets = []
            for r in reversed(rewards):
                g = r + cfg.gamma * g
                targets.append(g)
            targets.reverse()
        else:
            z = total
            if cfg.ranked_rewards:
                self.r2.add(total)
                z = self.r2.normalize(total)
            targets = [z] * len(episode)
        for (o, p), z_t in zip(episode, targets):
            self._replay.append((o, p, z_t))
        self._replay = self._replay[-cfg.replay_capacity :]
        return total

    def training_step(self) -> dict:
        import jax.numpy as jnp

        cfg: AlphaZeroConfig = self._algo_config
        returns = [self._self_play_episode() for _ in range(cfg.episodes_per_iter)]
        self._episode_reward_window += returns
        self._episode_reward_window = self._episode_reward_window[-100:]
        aux: dict = {}
        if self._replay:
            for _ in range(cfg.updates_per_iter):
                idx = self._rng.integers(0, len(self._replay), cfg.train_batch_size)
                obs = jnp.asarray(np.stack([self._replay[i][0] for i in idx]))
                pi = jnp.asarray(np.stack([self._replay[i][1] for i in idx]))
                z = jnp.asarray(np.asarray([self._replay[i][2] for i in idx], np.float32))
                self.params, self.opt_state, aux = self._update(
                    self.params, self.opt_state, obs, pi, z
                )
            aux = {k: float(v) for k, v in aux.items()}
        aux["replay_size"] = float(len(self._replay))
        return aux

    def compute_single_action(self, obs, explore: bool = False, use_mcts: bool = False):
        if use_mcts:
            cfg = self._algo_config
            mcts = MCTS(
                self.env, self._predict, self.n_actions,
                num_sims=cfg.num_sims, c_puct=cfg.c_puct, gamma=cfg.gamma,
                dirichlet_eps=0.0, rng=self._rng,
            )
            state = self.env.get_state()
            probs = mcts.search(np.asarray(obs, np.float32), state, temperature=0.0)
            # The search stepped the env through cloned states: put it back
            # before the caller takes the real step.
            self.env.set_state(state)
            return int(np.argmax(probs))
        probs, _ = self._predict(np.asarray(obs, np.float32))
        return int(np.argmax(probs))

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "params": self.params,
            "opt_state": self.opt_state,
            "timesteps": self._timesteps_total,
            "r2_values": list(self.r2.values),
            "np_rng_state": self._rng.bit_generator.state,
        })

    def load_checkpoint(self, checkpoint) -> None:
        data = checkpoint.to_dict()
        self.params = data["params"]
        self.opt_state = data["opt_state"]
        self._timesteps_total = data.get("timesteps", 0)
        self.r2.values = list(data.get("r2_values", []))
        if "np_rng_state" in data:
            self._rng.bit_generator.state = data["np_rng_state"]

    def cleanup(self) -> None:
        if getattr(self, "env", None) is not None:
            self.env.close()
