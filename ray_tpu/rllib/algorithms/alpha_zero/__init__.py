from ray_tpu.rllib.algorithms.alpha_zero.alpha_zero import (
    AlphaZero,
    AlphaZeroConfig,
    StateCloneWrapper,
)

__all__ = ["AlphaZero", "AlphaZeroConfig", "StateCloneWrapper"]
