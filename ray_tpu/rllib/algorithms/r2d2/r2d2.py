"""R2D2 — Recurrent Replay Distributed DQN.

Reference: rllib/algorithms/r2d2/r2d2.py (+ r2d2_torch_policy.py): a
recurrent Q-network trained from a replay buffer of fixed-length
SEQUENCES, each stored with the hidden state the network had when the
sequence began. Training replays a burn-in prefix to refresh the hidden
state (stored states go stale as parameters move), computes double-Q TD
targets only on the post-burn-in steps, and uses the invertible value
rescaling h(x) from the R2D2 paper for reward-scale robustness.

TPU-native shape: the recurrent core is a GRU unrolled with ``lax.scan``
(static sequence length -> one compiled XLA while-loop on the MXU-friendly
batched matmuls), and the whole TD update — burn-in, double-Q argmax,
rescaled targets, masked Huber loss — is a single jitted function over a
[B, T, ...] batch. No per-step Python in the hot path.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env.vector_env import VectorEnv
from ray_tpu.rllib.policy.sample_batch import SampleBatch


# ---------------------------------------------------------------------------
# Recurrent Q-network: encoder MLP -> GRU -> dueling Q head
# ---------------------------------------------------------------------------


def _dense(key, n_in, n_out):
    import jax

    scale = np.sqrt(2.0 / (n_in + n_out))
    return {
        "w": jax.random.normal(key, (n_in, n_out)) * scale,
        "b": np.zeros((n_out,), np.float32),
    }


def init_params(rng, obs_dim: int, action_dim: int, hidden: int):
    import jax

    k = jax.random.split(rng, 6)
    return {
        "enc": _dense(k[0], obs_dim, hidden),
        # GRU: update/reset/candidate gates over [x, h]
        "gru_z": _dense(k[1], hidden * 2, hidden),
        "gru_r": _dense(k[2], hidden * 2, hidden),
        "gru_h": _dense(k[3], hidden * 2, hidden),
        "val": _dense(k[4], hidden, 1),
        "adv": _dense(k[5], hidden, action_dim),
    }


def _apply(layer, x):
    return x @ layer["w"] + layer["b"]


def gru_cell(params, h, x):
    import jax
    import jax.numpy as jnp

    hx = jnp.concatenate([x, h], axis=-1)
    z = jax.nn.sigmoid(_apply(params["gru_z"], hx))
    r = jax.nn.sigmoid(_apply(params["gru_r"], hx))
    cand = jnp.tanh(_apply(params["gru_h"], jnp.concatenate([x, r * h], axis=-1)))
    return (1.0 - z) * h + z * cand


def q_scan(params, obs_seq, h0):
    """obs_seq [B, T, obs] + h0 [B, H] -> q [B, T, A], h_T [B, H]."""
    import jax
    import jax.numpy as jnp

    x = jnp.tanh(_apply(params["enc"], obs_seq))  # [B, T, H]

    def step(h, xt):
        h = gru_cell(params, h, xt)
        return h, h

    h_last, hs = jax.lax.scan(step, h0, jnp.swapaxes(x, 0, 1))
    hs = jnp.swapaxes(hs, 0, 1)  # [B, T, H]
    val = _apply(params["val"], hs)  # [B, T, 1]
    adv = _apply(params["adv"], hs)  # [B, T, A]
    q = val + adv - adv.mean(axis=-1, keepdims=True)  # dueling combine
    return q, h_last


def h_rescale(x, eps=1e-3):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def h_inverse(x, eps=1e-3):
    import jax.numpy as jnp

    s = jnp.sign(x)
    a = jnp.abs(x)
    return s * (jnp.square((jnp.sqrt(1.0 + 4.0 * eps * (a + 1.0 + eps)) - 1.0) / (2.0 * eps)) - 1.0)


# ---------------------------------------------------------------------------
# Sequence replay buffer (reference: replay stores fixed-length sequences
# with the recurrent state at sequence start)
# ---------------------------------------------------------------------------


class SequenceReplayBuffer:
    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._items: list = []
        self._pos = 0
        self._rng = np.random.default_rng(seed)

    def add(self, seq: dict):
        if len(self._items) < self.capacity:
            self._items.append(seq)
        else:
            self._items[self._pos] = seq
            self._pos = (self._pos + 1) % self.capacity

    def __len__(self):
        return len(self._items)

    def sample(self, n: int) -> dict:
        idx = self._rng.integers(0, len(self._items), n)
        seqs = [self._items[i] for i in idx]
        return {k: np.stack([s[k] for s in seqs]) for k in seqs[0]}


# ---------------------------------------------------------------------------
# Config / algorithm
# ---------------------------------------------------------------------------


class R2D2Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or R2D2)
        self.lr = 1e-3
        self.num_rollout_workers = 0
        self.train_batch_size = 32          # sequences per update
        self.replay_buffer_capacity = 4000  # sequences
        self.learning_starts = 500          # env STEPS buffered before training
        self.target_network_update_freq = 200
        self.rollout_steps_per_iter = 1000
        self.train_intensity = 40           # env steps per update
        self.burn_in = 4
        self.seq_len = 20                   # training steps after burn-in
        self.hidden_size = 64
        self.epsilon_timesteps = 10_000
        self.initial_epsilon = 1.0
        self.final_epsilon = 0.02
        self.use_h_rescale = True

    def training(self, *, replay_buffer_capacity=None, learning_starts=None,
                 target_network_update_freq=None, rollout_steps_per_iter=None,
                 train_intensity=None, burn_in=None, seq_len=None,
                 hidden_size=None, epsilon_timesteps=None, final_epsilon=None,
                 use_h_rescale=None, **kwargs) -> "R2D2Config":
        super().training(**kwargs)
        for name, val in (
            ("replay_buffer_capacity", replay_buffer_capacity),
            ("learning_starts", learning_starts),
            ("target_network_update_freq", target_network_update_freq),
            ("rollout_steps_per_iter", rollout_steps_per_iter),
            ("train_intensity", train_intensity),
            ("burn_in", burn_in),
            ("seq_len", seq_len),
            ("hidden_size", hidden_size),
            ("epsilon_timesteps", epsilon_timesteps),
            ("final_epsilon", final_epsilon),
            ("use_h_rescale", use_h_rescale),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class R2D2(Algorithm):
    @classmethod
    def get_default_config(cls) -> R2D2Config:
        return R2D2Config(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax
        import optax

        cfg: R2D2Config = self._algo_config
        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        assert hasattr(probe.action_space, "n"), "R2D2 requires a discrete action space"
        self.obs_dim = int(np.prod(probe.observation_space.shape))
        self.action_dim = int(probe.action_space.n)
        probe.close()

        self.env = VectorEnv(cfg.env, max(cfg.num_envs_per_worker, 1), cfg.env_config, 0, seed=cfg.seed)
        self.n_envs = max(cfg.num_envs_per_worker, 1)
        self.params = init_params(
            jax.random.PRNGKey(cfg.seed), self.obs_dim, self.action_dim, cfg.hidden_size
        )
        self.target_params = jax.tree_util.tree_map(np.asarray, self.params)
        self.tx = optax.chain(optax.clip_by_global_norm(10.0), optax.adam(cfg.lr))
        self.opt_state = self.tx.init(self.params)
        self.buffer = SequenceReplayBuffer(cfg.replay_buffer_capacity, seed=cfg.seed)
        self._timesteps_total = 0
        self._updates = 0
        self._episode_reward_window: list = []
        self._rng = np.random.default_rng(cfg.seed)

        # Per-env recurrent state + open sequence builders.
        self._hidden = np.zeros((self.n_envs, cfg.hidden_size), np.float32)
        self._seq_open = [self._new_seq(self._hidden[i]) for i in range(self.n_envs)]

        T = cfg.burn_in + cfg.seq_len

        def act_fn(params, obs, h):
            q, h2 = q_scan(params, obs[:, None, :], h)
            return q[:, 0, :], h2

        self._act = jax.jit(act_fn)

        def update_fn(params, target_params, opt_state, batch):
            import jax.numpy as jnp

            def loss_fn(p):
                q_all, _ = q_scan(p, batch["obs"], batch["h0"])          # [B,T,A]
                qt_all, _ = q_scan(target_params, batch["obs"], batch["h0"])
                acts = batch["actions"].astype(jnp.int32)                 # [B,T]
                q_taken = jnp.take_along_axis(q_all, acts[..., None], -1)[..., 0]
                # Double-Q over the NEXT in-sequence step.
                best_next = jnp.argmax(q_all[:, 1:, :], axis=-1)          # [B,T-1]
                q_next = jnp.take_along_axis(qt_all[:, 1:, :], best_next[..., None], -1)[..., 0]
                if cfg.use_h_rescale:
                    q_next = h_inverse(q_next)
                target = batch["rewards"][:, :-1] + cfg.gamma * (
                    1.0 - batch["dones"][:, :-1]
                ) * q_next
                if cfg.use_h_rescale:
                    target = h_rescale(target)
                td = q_taken[:, :-1] - jax.lax.stop_gradient(target)
                # Mask: valid steps only, and burn-in excluded from loss
                # (the prefix exists to refresh the hidden state).
                mask = batch["mask"][:, :-1]
                mask = mask.at[:, : cfg.burn_in].set(0.0)
                huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td * td, jnp.abs(td) - 0.5)
                loss = jnp.sum(huber * mask) / jnp.maximum(jnp.sum(mask), 1.0)
                return loss, {"td_abs": jnp.sum(jnp.abs(td) * mask) / jnp.maximum(jnp.sum(mask), 1.0)}

            import jax

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux = dict(aux)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._update_fn = jax.jit(update_fn)
        self._T = T

    def _new_seq(self, h0):
        return {"h0": np.array(h0), "obs": [], "actions": [], "rewards": [], "dones": []}

    def _epsilon(self) -> float:
        cfg = self._algo_config
        frac = min(1.0, self._timesteps_total / max(cfg.epsilon_timesteps, 1))
        return cfg.initial_epsilon + frac * (cfg.final_epsilon - cfg.initial_epsilon)

    def _finish_seq(self, i: int):
        """Pad the open sequence to T and push it to replay."""
        cfg = self._algo_config
        seq = self._seq_open[i]
        n = len(seq["obs"])
        if n == 0:
            return
        T = self._T
        pad = T - n
        obs = np.asarray(seq["obs"], np.float32)
        if pad:
            obs = np.concatenate([obs, np.zeros((pad, self.obs_dim), np.float32)])
        item = {
            "h0": seq["h0"],
            "obs": obs,
            "actions": np.pad(np.asarray(seq["actions"], np.int32), (0, pad)),
            "rewards": np.pad(np.asarray(seq["rewards"], np.float32), (0, pad)),
            "dones": np.pad(np.asarray(seq["dones"], np.float32), (0, pad), constant_values=1.0),
            "mask": np.pad(np.ones(n, np.float32), (0, pad)),
        }
        self.buffer.add(item)
        self._seq_open[i] = self._new_seq(self._hidden[i])

    def training_step(self) -> dict:
        import jax.numpy as jnp

        cfg: R2D2Config = self._algo_config
        metrics: dict = {}
        for _ in range(cfg.rollout_steps_per_iter // self.n_envs):
            obs = self.env.current_obs().astype(np.float32)
            q, h_next = self._act(self.params, jnp.asarray(obs), jnp.asarray(self._hidden))
            q = np.asarray(q)
            actions = q.argmax(axis=-1)
            eps_mask = self._rng.random(len(actions)) < self._epsilon()
            actions = np.where(
                eps_mask, self._rng.integers(0, self.action_dim, len(actions)), actions
            )
            next_obs, rewards, dones, _ = self.env.step(actions)
            h_next = np.array(h_next)  # mutable copy (jax arrays are read-only)
            for i in range(self.n_envs):
                seq = self._seq_open[i]
                seq["obs"].append(obs[i])
                seq["actions"].append(actions[i])
                seq["rewards"].append(rewards[i])
                seq["dones"].append(float(dones[i]))
                if dones[i]:
                    h_next[i] = 0.0  # recurrent state resets with the episode
                    self._hidden[i] = 0.0
                    self._finish_seq(i)
                elif len(seq["obs"]) >= self._T:
                    self._hidden[i] = h_next[i]
                    self._finish_seq(i)
            self._hidden = h_next
            self._timesteps_total += self.n_envs
            if (
                # learning_starts counts ENV STEPS (reference semantics);
                # the buffer stores sequences of up to T steps each.
                len(self.buffer) * self._T >= max(self._T, cfg.learning_starts)
                and self._timesteps_total % max(1, cfg.train_intensity) < self.n_envs
            ):
                metrics = self._train_once()
        stats_r, _ = self.env.pop_episode_stats()
        self._episode_reward_window += stats_r
        self._episode_reward_window = self._episode_reward_window[-100:]
        metrics["epsilon"] = self._epsilon()
        metrics["replay_sequences"] = len(self.buffer)
        return metrics

    def _train_once(self) -> dict:
        import jax

        cfg = self._algo_config
        batch = self.buffer.sample(cfg.train_batch_size)
        self.params, self.opt_state, aux = self._update_fn(
            self.params, self._as_jax(self.target_params), self.opt_state, batch
        )
        self._updates += 1
        if self._updates % cfg.target_network_update_freq == 0:
            self.target_params = jax.tree_util.tree_map(np.asarray, self.params)
        return {k: float(v) for k, v in aux.items()}

    @staticmethod
    def _as_jax(tree):
        import jax
        import jax.numpy as jnp

        return jax.tree_util.tree_map(jnp.asarray, tree)

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window)) if self._episode_reward_window else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def _evaluate_local(self, duration: int, by_episodes: bool):
        """Recurrent eval must THREAD the GRU state across steps — the base
        loop's stateless compute_single_action would wipe the memory the
        policy was trained to use, scoring a memoryless policy instead."""
        env = self._make_eval_env()
        rewards, lens, steps = [], [], 0
        hidden_size = self._algo_config.hidden_size
        try:
            for _ in range(duration if by_episodes else 64):
                obs, _ = env.reset()
                state = np.zeros((1, hidden_size), np.float32)
                total, length = 0.0, 0
                for _ in range(10_000):
                    action, state = self.compute_single_action(
                        obs, explore=False, state=state
                    )
                    obs, r, terminated, truncated, _ = env.step(action)
                    total += float(r)
                    length += 1
                    steps += 1
                    if terminated or truncated:
                        break
                    if not by_episodes and steps >= duration:
                        break
                rewards.append(total)
                lens.append(length)
                if not by_episodes and steps >= duration:
                    break
        finally:
            try:
                env.close()
            except Exception:
                pass
        return rewards, lens

    def compute_single_action(self, obs, explore: bool = False, state=None):
        import jax.numpy as jnp

        h = state if state is not None else np.zeros((1, self._algo_config.hidden_size), np.float32)
        q, h2 = self._act(self.params, jnp.asarray(np.asarray(obs, np.float32))[None], jnp.asarray(h))
        action = int(np.asarray(q)[0].argmax())
        if state is not None:
            return action, np.asarray(h2)
        return action

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "params": self.params,
            "target": self.target_params,
            "opt_state": self.opt_state,
            "timesteps": self._timesteps_total,
            "updates": self._updates,
        })

    def load_checkpoint(self, checkpoint) -> None:
        data = checkpoint.to_dict()
        self.params = data["params"]
        self.target_params = data["target"]
        self.opt_state = data["opt_state"]
        self._timesteps_total = data.get("timesteps", 0)
        self._updates = data.get("updates", 0)

    def cleanup(self) -> None:
        self.env.close()
