from ray_tpu.rllib.algorithms.r2d2.r2d2 import R2D2, R2D2Config

__all__ = ["R2D2", "R2D2Config"]
