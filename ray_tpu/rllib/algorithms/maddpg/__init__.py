from ray_tpu.rllib.algorithms.maddpg.maddpg import MADDPG, MADDPGConfig

__all__ = ["MADDPG", "MADDPGConfig"]
