"""MADDPG — Multi-Agent DDPG with centralized critics.

Reference: rllib/algorithms/maddpg/maddpg.py (Lowe et al. 2017):
decentralized actors ``a_i = mu_i(o_i)`` with CENTRALIZED critics
``Q_i(o_1..o_N, a_1..a_N)`` — each agent's critic sees every agent's
observation and action during training, which removes the non-stationarity
that breaks independent DDPG in multi-agent settings. Execution stays
decentralized (actors only need their own observation).

TPU-native shape: all agents share one architecture, so per-agent
parameters are STACKED along a leading axis and every forward/backward is
``jax.vmap`` over that axis — one jitted update trains all N agents'
actors and critics as a single XLA program (batched matmuls on the MXU),
instead of the reference's N separate torch modules.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.sac.sac import _mlp_apply, _mlp_params
from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv
from ray_tpu.rllib.utils.replay_buffers import ColumnReplayBuffer


class MADDPGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MADDPG)
        self.lr = 1e-3
        self.critic_lr = 1e-3
        self.num_rollout_workers = 0
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.learning_starts = 1000
        self.tau = 1e-2
        self.rollout_steps_per_iter = 500
        self.train_intensity = 4      # env steps per gradient update
        self.exploration_noise = 0.2  # gaussian, in [-1,1] action units
        self.model_hiddens = (64, 64)

    def training(self, *, critic_lr=None, replay_buffer_capacity=None,
                 learning_starts=None, tau=None, rollout_steps_per_iter=None,
                 train_intensity=None, exploration_noise=None,
                 model_hiddens=None, **kwargs) -> "MADDPGConfig":
        super().training(**kwargs)
        for name, val in (
            ("critic_lr", critic_lr),
            ("replay_buffer_capacity", replay_buffer_capacity),
            ("learning_starts", learning_starts),
            ("tau", tau),
            ("rollout_steps_per_iter", rollout_steps_per_iter),
            ("train_intensity", train_intensity),
            ("exploration_noise", exploration_noise),
            ("model_hiddens", model_hiddens),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class MADDPG(Algorithm):
    @classmethod
    def get_default_config(cls) -> MADDPGConfig:
        return MADDPGConfig(cls)

    def setup(self, config: dict) -> None:
        import jax
        import optax

        cfg: MADDPGConfig = self._algo_config
        env = cfg.env(dict(cfg.env_config)) if callable(cfg.env) else cfg.env
        assert isinstance(env, MultiAgentEnv), "MADDPG requires a MultiAgentEnv"
        self.env = env
        self.agents = list(env.possible_agents)
        self.n_agents = len(self.agents)
        self.obs_dim = int(np.prod(env.observation_space.shape))
        space = env.action_space
        assert hasattr(space, "shape") and space.shape, "MADDPG needs continuous actions"
        self.act_dim = int(np.prod(space.shape))
        low = np.asarray(space.low, np.float32)
        high = np.asarray(space.high, np.float32)
        self._act_scale = (high - low) / 2.0
        self._act_offset = (high + low) / 2.0

        N, H = self.n_agents, cfg.model_hiddens
        global_dim = N * (self.obs_dim + self.act_dim)
        keys = jax.random.split(jax.random.PRNGKey(cfg.seed), 2 * N)
        # Stacked per-agent params: tree leaves have leading axis N.
        actor = [ _mlp_params(keys[i], self.obs_dim, H, self.act_dim) for i in range(N)]
        critic = [_mlp_params(keys[N + i], global_dim, H, 1) for i in range(N)]
        stack = lambda trees: jax.tree_util.tree_map(lambda *xs: np.stack(xs), *trees)  # noqa: E731
        self.params = {"actor": stack(actor), "critic": stack(critic)}
        self.target_params = jax.tree_util.tree_map(np.copy, self.params)
        # Split learning rates (standard MADDPG: critics usually train
        # faster than actors) via per-subtree transforms.
        self.tx = optax.multi_transform(
            {
                "actor": optax.chain(optax.clip_by_global_norm(0.5), optax.adam(cfg.lr)),
                "critic": optax.chain(
                    optax.clip_by_global_norm(0.5), optax.adam(cfg.critic_lr)
                ),
            },
            param_labels={"actor": "actor", "critic": "critic"},
        )
        self.opt_state = self.tx.init(self.params)
        self.buffer = ColumnReplayBuffer(cfg.replay_buffer_capacity, cfg.seed)
        self._timesteps_total = 0
        self._updates = 0
        self._episode_reward_window: list = []
        self._rng = np.random.default_rng(cfg.seed)
        self._obs = self._obs_dict_to_array(self.env.reset(seed=cfg.seed)[0])
        self._ep_reward = 0.0

        def actor_fwd(aparams, obs):  # single agent
            return jax.numpy.tanh(_mlp_apply(aparams, obs))

        self._actors_fwd = jax.jit(
            lambda p, obs: jax.vmap(actor_fwd)(p["actor"], obs)  # [N,obs]->[N,act]
        )

        gamma = cfg.gamma
        tau = cfg.tau

        def update_fn(params, target_params, opt_state, batch):
            import jax.numpy as jnp

            B = batch["obs"].shape[0]
            obs = batch["obs"]            # [B,N,obs]
            acts = batch["actions"]       # [B,N,act]
            rews = batch["rewards"]       # [B,N]
            dones = batch["dones"]        # [B]
            next_obs = batch["next_obs"]  # [B,N,obs]

            # Target joint action: each agent's target actor on ITS obs.
            next_a = jax.vmap(
                lambda ap, o: jnp.tanh(_mlp_apply(ap, o)),
                in_axes=(0, 1), out_axes=1,
            )(target_params["actor"], next_obs)  # [B,N,act]
            next_global = jnp.concatenate(
                [next_obs.reshape(B, -1), next_a.reshape(B, -1)], axis=-1
            )
            q_next = jax.vmap(
                lambda cp: _mlp_apply(cp, next_global)[..., 0], in_axes=0, out_axes=1
            )(target_params["critic"])  # [B,N]
            y = rews + gamma * (1.0 - dones[:, None]) * q_next
            y = jax.lax.stop_gradient(y)

            def loss_fn(p):
                global_in = jnp.concatenate(
                    [obs.reshape(B, -1), acts.reshape(B, -1)], axis=-1
                )
                q = jax.vmap(
                    lambda cp: _mlp_apply(cp, global_in)[..., 0], in_axes=0, out_axes=1
                )(p["critic"])  # [B,N]
                critic_loss = jnp.mean(jnp.square(q - y))

                # Actor i maximizes Q_i with ITS action replaced by mu_i(o_i)
                # and the other agents' actions from the batch (stop-grad
                # through them is implicit: they are data).
                mu = jax.vmap(
                    lambda ap, o: jnp.tanh(_mlp_apply(ap, o)), in_axes=(0, 1), out_axes=1
                )(p["actor"], obs)  # [B,N,act]
                eye = jnp.eye(self.n_agents)[None, :, :, None]  # [1,N,N,1]
                # joint_i: batch actions with column i swapped for mu_i.
                joint = acts[:, None, :, :] * (1.0 - eye) + mu[:, :, None, :].transpose(0, 2, 1, 3) * eye
                # joint[b, i, j, :] = action of agent j in agent i's critic input
                global_a = jnp.concatenate(
                    [
                        jnp.broadcast_to(obs.reshape(B, 1, -1), (B, self.n_agents, self.n_agents * self.obs_dim)),
                        joint.reshape(B, self.n_agents, -1),
                    ],
                    axis=-1,
                )  # [B,N,global]
                # Critic params are FROZEN in the actor term — without the
                # stop_gradient the actor objective would "improve" by
                # inflating the critic's Q estimates instead of the policy.
                q_pi = jax.vmap(
                    lambda cp, gi: _mlp_apply(cp, gi)[..., 0],
                    in_axes=(0, 1), out_axes=1,
                )(jax.lax.stop_gradient(p["critic"]), global_a)  # [B,N]
                actor_loss = -jnp.mean(q_pi)
                return critic_loss + actor_loss, {
                    "critic_loss": critic_loss,
                    "actor_loss": actor_loss,
                    "q_mean": q.mean(),
                }

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target_params = jax.tree_util.tree_map(
                lambda t, o: (1.0 - tau) * t + tau * o, target_params, params
            )
            aux = dict(aux)
            aux["total_loss"] = loss
            return params, target_params, opt_state, aux

        self._update_fn = jax.jit(update_fn)

    # -- helpers ---------------------------------------------------------

    def _obs_dict_to_array(self, obs_dict: dict) -> np.ndarray:
        return np.stack(
            [np.asarray(obs_dict[a], np.float32).reshape(-1) for a in self.agents]
        )

    def _scale(self, a: np.ndarray) -> np.ndarray:
        return a * self._act_scale + self._act_offset

    # -- training --------------------------------------------------------

    def training_step(self) -> dict:
        import jax.numpy as jnp

        cfg: MADDPGConfig = self._algo_config
        metrics: dict = {}
        for _ in range(cfg.rollout_steps_per_iter):
            a = np.array(self._actors_fwd(self._as_jax(self.params), jnp.asarray(self._obs)))
            a = np.clip(a + self._rng.normal(0, cfg.exploration_noise, a.shape), -1, 1)
            action_dict = {ag: self._scale(a[i]) for i, ag in enumerate(self.agents)}
            obs_d, rew_d, term_d, trunc_d, _ = self.env.step(action_dict)
            done = bool(term_d.get("__all__")) or bool(trunc_d.get("__all__"))
            rewards = np.asarray([rew_d.get(ag, 0.0) for ag in self.agents], np.float32)
            next_obs = (
                self._obs_dict_to_array(obs_d)
                if obs_d
                else np.zeros_like(self._obs)
            )
            self.buffer.add({
                "obs": self._obs, "actions": a.astype(np.float32),
                "rewards": rewards, "dones": np.float32(done),
                "next_obs": next_obs,
            })
            self._ep_reward += float(rewards.sum())
            self._timesteps_total += 1
            if done:
                self._episode_reward_window.append(self._ep_reward)
                self._episode_reward_window = self._episode_reward_window[-100:]
                self._ep_reward = 0.0
                self._obs = self._obs_dict_to_array(self.env.reset()[0])
            else:
                self._obs = next_obs
            if (
                len(self.buffer) >= cfg.learning_starts
                and self._timesteps_total % max(1, cfg.train_intensity) == 0
            ):
                metrics = self._train_once()
        return metrics

    def _train_once(self) -> dict:
        batch = self.buffer.sample(self._algo_config.train_batch_size)
        self.params, self.target_params, self.opt_state, aux = self._update_fn(
            self.params, self.target_params, self.opt_state, batch
        )
        self._updates += 1
        return {k: float(v) for k, v in aux.items()}

    @staticmethod
    def _as_jax(tree):
        import jax
        import jax.numpy as jnp

        return jax.tree_util.tree_map(jnp.asarray, tree)

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window))
            if self._episode_reward_window
            else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def compute_actions(self, obs_dict: dict) -> dict:
        """Decentralized execution: each agent acts from its own obs."""
        import jax.numpy as jnp

        obs = self._obs_dict_to_array(obs_dict)
        a = np.array(self._actors_fwd(self._as_jax(self.params), jnp.asarray(obs)))
        return {ag: self._scale(a[i]) for i, ag in enumerate(self.agents)}

    def _evaluate_local(self, duration: int, by_episodes: bool):
        """Greedy (noise-free) multi-agent episodes; team reward per episode.
        Overrides the base single-agent eval loop — MADDPG envs take action
        DICTS and report per-agent rewards."""
        cfg = self._algo_config
        shared = not callable(cfg.env)
        # Fresh env per round (closed below); instance-config borrows the
        # training env since a second instance can't be constructed.
        env = self.env if shared else cfg.env(dict(cfg.env_config))
        rewards, lens, steps = [], [], 0
        try:
            for _ in range(duration if by_episodes else 64):
                obs_d, _ = env.reset()
                total, length = 0.0, 0
                for _ in range(10_000):
                    obs_d, rew_d, term_d, trunc_d, _ = env.step(self.compute_actions(obs_d))
                    total += float(sum(rew_d.get(ag, 0.0) for ag in self.agents))
                    length += 1
                    steps += 1
                    done = bool(term_d.get("__all__")) or bool(trunc_d.get("__all__"))
                    if done or (not by_episodes and steps >= duration):
                        break
                rewards.append(total)
                lens.append(length)
                if not by_episodes and steps >= duration:
                    break
        finally:
            if shared:
                # Re-seat the training rollout on a fresh episode: eval
                # stepped the shared env, so the cached obs is stale.
                self._obs = self._obs_dict_to_array(env.reset()[0])
                self._ep_reward = 0.0
            else:
                try:
                    env.close()
                except Exception:
                    pass
        return rewards, lens

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "params": self.params,
            "target": self.target_params,
            "opt_state": self.opt_state,
            "timesteps": self._timesteps_total,
        })

    def load_checkpoint(self, checkpoint) -> None:
        data = checkpoint.to_dict()
        self.params = data["params"]
        self.target_params = data["target"]
        self.opt_state = data["opt_state"]
        self._timesteps_total = data.get("timesteps", 0)

    def cleanup(self) -> None:
        if getattr(self, "env", None) is not None:
            self.env.close()
