"""DDPPO — decentralized distributed PPO.

Reference: rllib/algorithms/ddppo/ddppo.py (Wijmans et al. 2019): sampling
AND SGD both happen inside the rollout workers; gradients are averaged
worker-to-worker with an allreduce (torch DDP over gloo/nccl in the
reference) and each worker applies them locally, so parameters never
transit the driver — it only coordinates rounds and aggregates metrics
(`ddppo.py:90`: "gradients are computed on the workers ... all-reduce").

TPU-native shape: the allreduce rides ray_tpu's collective plane
(util/collective — XLA collectives over ICI when the group backend is
"tpu", the CPU ring otherwise), the same plane the LearnerGroup uses. Every
worker seeds the same params + optax state, and identical averaged
gradients keep them bit-identical thereafter — asserted cheaply via a
weight-digest check each round.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.ppo.ppo import PPOConfig, ppo_loss
from ray_tpu.rllib.evaluation.rollout_worker import RolloutWorker


class _DDPPOWorker(RolloutWorker):
    """Rollout worker that also runs the PPO SGD locally, allreducing
    gradients with its peers each minibatch."""

    def __init__(self, *args, lr=3e-4, grad_clip=0.5, opt_seed=0, **kwargs):
        super().__init__(*args, **kwargs)
        import jax
        import optax

        from ray_tpu.rllib.core import rl_module

        chain = []
        if grad_clip:
            chain.append(optax.clip_by_global_norm(grad_clip))
        chain.append(optax.adam(lr))
        self._tx = optax.chain(*chain)
        # Same opt_seed everywhere -> identical initial params on every
        # worker; identical averaged grads keep them in lockstep.
        self._params = rl_module.init_params(jax.random.PRNGKey(opt_seed), self.spec)
        self._opt_state = self._tx.init(self._params)
        self._world = 1
        self._group = None
        spec = self.spec

        def grads_fn(params, batch, cfg):
            (loss, metrics), grads = jax.value_and_grad(
                lambda p: ppo_loss(p, batch, spec, cfg), has_aux=True
            )(params)
            metrics = dict(metrics)
            metrics["total_loss"] = loss
            return grads, metrics

        self._grads_fn = jax.jit(grads_fn)

        def apply_fn(params, opt_state, grads):
            updates, opt_state = self._tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            return params, opt_state

        self._apply_fn = jax.jit(apply_fn)

    def init_collective(self, world_size: int, rank: int, backend: str, group_name: str):
        from ray_tpu.util import collective

        self._world = world_size
        self._group = group_name
        if world_size > 1:
            collective.init_collective_group(
                world_size=world_size, rank=rank, backend=backend, group_name=group_name
            )
        return True

    def train_round(self, fragment_len: int, minibatch_size: int, num_sgd_iter: int,
                    loss_cfg: dict, seed: int):
        """One DDPPO round: sample locally, SGD locally, allreduce grads.

        Every peer calls allreduce the same number of times per round
        (identical fragment/minibatch geometry), which the collective plane
        requires — minibatches() pads/trims identically on every worker.
        """
        import jax
        import jax.numpy as jnp

        batch = self.sample(fragment_len, explore=True)
        metrics: dict = {}
        for epoch in range(num_sgd_iter):
            for mb in batch.minibatches(min(minibatch_size, batch.count), seed=seed + epoch):
                jb = {k: jnp.asarray(v) for k, v in mb.items()}
                grads, metrics = self._grads_fn(self._params, jb, loss_cfg)
                if self._world > 1:
                    from ray_tpu.util import collective

                    flat, treedef = jax.tree_util.tree_flatten(grads)
                    reduced = [
                        collective.allreduce(
                            np.asarray(g) / self._world, group_name=self._group
                        )
                        for g in flat
                    ]
                    grads = jax.tree_util.tree_unflatten(
                        treedef, [jnp.asarray(g) for g in reduced]
                    )
                self._params, self._opt_state = self._apply_fn(
                    self._params, self._opt_state, grads
                )
        rewards, lens = self.env.pop_episode_stats()
        digest = float(
            sum(np.abs(np.asarray(x)).sum() for x in jax.tree_util.tree_leaves(self._params))
        )
        return (
            {k: float(v) for k, v in metrics.items()},
            batch.count,
            rewards,
            digest,
        )

    def get_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self._params)


class DDPPOConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPPO)
        self.num_rollout_workers = 2
        # Per-worker fragment per round (reference: rollout_fragment_length
        # drives the per-worker batch; there is no global train_batch_size).
        self.rollout_fragment_length = 100
        self.sgd_minibatch_size = 64
        self.num_sgd_iter = 4
        self.collective_backend = "cpu"

    def training(self, *, collective_backend: Optional[str] = None, **kwargs) -> "DDPPOConfig":
        super().training(**kwargs)
        if collective_backend is not None:
            self.collective_backend = collective_backend
        return self


class DDPPO(Algorithm):
    @classmethod
    def get_default_config(cls) -> DDPPOConfig:
        return DDPPOConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym

        self.cleanup()
        cfg: DDPPOConfig = self._algo_config
        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        from ray_tpu.rllib.models import ModelCatalog

        self.module_spec = ModelCatalog.get_model_spec(
            probe.observation_space, probe.action_space, cfg.model_config()
        )
        probe.close()
        n = max(cfg.num_rollout_workers, 1)
        worker_cls = ray_tpu.remote(num_cpus=1)(_DDPPOWorker)
        self.workers = [
            worker_cls.remote(
                cfg.env, self.module_spec, i, max(cfg.num_envs_per_worker, 1),
                dict(cfg.env_config), cfg.gamma, cfg.lambda_, cfg.seed,
                cfg.observation_filter,
                lr=cfg.lr, grad_clip=cfg.grad_clip, opt_seed=cfg.seed,
            )
            for i in range(n)
        ]
        group = f"ddppo_{id(self)}"
        ray_tpu.get(
            [
                w.init_collective.remote(n, rank, cfg.collective_backend, group)
                for rank, w in enumerate(self.workers)
            ],
            timeout=300,
        )
        self._timesteps_total = 0
        self._round = 0
        self._episode_reward_window: list = []

    def training_step(self) -> dict:
        cfg: DDPPOConfig = self._algo_config
        loss_cfg = {
            "clip_param": cfg.clip_param,
            "vf_clip_param": cfg.vf_clip_param,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
        }
        self._round += 1
        refs = [
            w.train_round.remote(
                cfg.rollout_fragment_length, cfg.sgd_minibatch_size,
                cfg.num_sgd_iter, loss_cfg, self._round * 10_000,
            )
            for w in self.workers
        ]
        results = ray_tpu.get(refs, timeout=600)
        digests = [r[3] for r in results]
        # Lockstep invariant: decentralized updates must agree bit-for-bit
        # (they started identical and applied identical averaged grads).
        if max(digests) - min(digests) > 1e-4 * max(1.0, abs(digests[0])):
            raise RuntimeError(f"DDPPO workers diverged: digests={digests}")
        metrics: dict = {}
        for m, count, rewards, _ in results:
            metrics = m
            self._timesteps_total += count
            self._episode_reward_window += rewards
        self._episode_reward_window = self._episode_reward_window[-100:]
        metrics["num_env_steps_sampled_this_iter"] = sum(r[1] for r in results)
        return metrics

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window))
            if self._episode_reward_window
            else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def get_policy_weights(self):
        return ray_tpu.get(self.workers[0].get_weights.remote(), timeout=60)

    def compute_single_action(self, obs, explore: bool = False):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.core import rl_module

        params = jax.tree_util.tree_map(jnp.asarray, self.get_policy_weights())
        actions, _, _ = rl_module.sample_actions(
            params, jnp.asarray(np.asarray(obs, np.float32))[None],
            jax.random.PRNGKey(0), self.module_spec, explore,
        )
        a = np.asarray(actions)[0]
        return a.item() if self.module_spec.discrete else a

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict(
            {"weights": self.get_policy_weights(), "timesteps": self._timesteps_total}
        )

    def load_checkpoint(self, checkpoint) -> None:
        data = checkpoint.to_dict()
        ray_tpu.get(
            [w.set_weights.remote(data["weights"]) for w in self.workers], timeout=300
        )
        self._timesteps_total = data.get("timesteps", 0)

    def cleanup(self) -> None:
        for w in getattr(self, "workers", []):
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        eval_ws = getattr(self, "_eval_workers", None)
        if eval_ws is not None:
            eval_ws.stop()
            self._eval_workers = None
