from ray_tpu.rllib.algorithms.ddppo.ddppo import DDPPO, DDPPOConfig  # noqa: F401
