from ray_tpu.rllib.algorithms.bandits.bandits import (  # noqa: F401
    BanditConfig,
    BanditLinTS,
    BanditLinUCB,
)
