"""Contextual bandits — LinUCB and LinTS.

Reference: rllib/algorithms/bandit/ (bandit.py, policy/online linear
regression): one linear model per arm over the observation context, updated
in closed form (Sherman-Morrison), with UCB or Thompson-sampling
exploration. Environments are ordinary gym envs whose episodes are one step
long (the reference's bandit envs behave the same way); rollouts happen
in-process — there is nothing to parallelize in a closed-form update.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env.vector_env import EnvContext, _make_env


class _LinearArm:
    """Online ridge regression for one arm: A = I*lambda + sum x x^T,
    b = sum r x; theta = A^-1 b. A^-1 maintained by Sherman-Morrison."""

    def __init__(self, dim: int, lam: float = 1.0):
        self.A_inv = np.eye(dim) / lam
        self.b = np.zeros(dim)
        self.theta = np.zeros(dim)
        self.n = 0

    def update(self, x: np.ndarray, reward: float):
        Ax = self.A_inv @ x
        self.A_inv -= np.outer(Ax, Ax) / (1.0 + x @ Ax)
        self.b += reward * x
        self.theta = self.A_inv @ self.b
        self.n += 1

    def ucb(self, x: np.ndarray, alpha: float) -> float:
        return float(x @ self.theta + alpha * np.sqrt(max(x @ self.A_inv @ x, 0.0)))

    def thompson(self, x: np.ndarray, rng: np.random.Generator, scale: float) -> float:
        # Sherman-Morrison drift can leave A_inv slightly asymmetric;
        # symmetrize + jitter keeps the sampler's covariance valid.
        cov = scale * self.A_inv
        cov = (cov + cov.T) / 2.0 + 1e-9 * np.eye(cov.shape[0])
        theta_s = rng.multivariate_normal(self.theta, cov)
        return float(x @ theta_s)


class BanditConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BanditLinUCB)
        self.num_rollout_workers = 0
        self.exploration = "ucb"  # "ucb" | "thompson"
        self.ucb_alpha = 1.0
        self.ts_scale = 1.0
        self.ridge_lambda = 1.0
        self.steps_per_iter = 100

    def training(self, *, exploration=None, ucb_alpha=None, ts_scale=None,
                 ridge_lambda=None, steps_per_iter=None, **kwargs) -> "BanditConfig":
        super().training(**kwargs)
        for name, val in (
            ("exploration", exploration), ("ucb_alpha", ucb_alpha),
            ("ts_scale", ts_scale), ("ridge_lambda", ridge_lambda),
            ("steps_per_iter", steps_per_iter),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class BanditLinUCB(Algorithm):
    """LinUCB (reference: BanditLinUCB)."""

    _exploration = "ucb"

    @classmethod
    def get_default_config(cls) -> BanditConfig:
        cfg = BanditConfig(cls)
        cfg.exploration = cls._exploration
        return cfg

    def setup(self, config: dict) -> None:
        import gymnasium as gym

        cfg: BanditConfig = self._algo_config
        self.env = _make_env(cfg.env, EnvContext(dict(cfg.env_config), 0, 0))
        assert isinstance(self.env.action_space, gym.spaces.Discrete), "bandits need discrete arms"
        self.n_arms = int(self.env.action_space.n)
        self.dim = int(np.prod(self.env.observation_space.shape))
        self.arms = [_LinearArm(self.dim, cfg.ridge_lambda) for _ in range(self.n_arms)]
        self._rng = np.random.default_rng(cfg.seed)
        self._obs, _ = self.env.reset(seed=cfg.seed)
        self._timesteps_total = 0
        self._episode_reward_window: list = []
        self._cumulative_reward = 0.0

    def _score(self, x: np.ndarray) -> np.ndarray:
        cfg: BanditConfig = self._algo_config
        if cfg.exploration == "thompson":
            return np.asarray([a.thompson(x, self._rng, cfg.ts_scale) for a in self.arms])
        return np.asarray([a.ucb(x, cfg.ucb_alpha) for a in self.arms])

    def training_step(self) -> dict:
        cfg: BanditConfig = self._algo_config
        rewards = []
        for _ in range(cfg.steps_per_iter):
            x = np.asarray(self._obs, np.float64).reshape(-1)
            arm = int(np.argmax(self._score(x)))
            obs, r, term, trunc, _ = self.env.step(arm)
            self.arms[arm].update(x, float(r))
            rewards.append(float(r))
            self._cumulative_reward += float(r)
            self._timesteps_total += 1
            if term or trunc:
                obs, _ = self.env.reset()
            self._obs = obs
        self._episode_reward_window += rewards
        self._episode_reward_window = self._episode_reward_window[-1000:]
        return {
            "mean_reward": float(np.mean(rewards)),
            "cumulative_reward": self._cumulative_reward,
            "arm_pulls": [a.n for a in self.arms],
        }

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = float(np.mean(self._episode_reward_window))
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def compute_single_action(self, obs, explore: bool = False):
        x = np.asarray(obs, np.float64).reshape(-1)
        if explore:
            return int(np.argmax(self._score(x)))
        return int(np.argmax([x @ a.theta for a in self.arms]))

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "arms": [(a.A_inv, a.b, a.theta, a.n) for a in self.arms],
            "timesteps": self._timesteps_total,
        })

    def load_checkpoint(self, checkpoint) -> None:
        data = checkpoint.to_dict()
        for arm, (A_inv, b, theta, n) in zip(self.arms, data["arms"]):
            arm.A_inv, arm.b, arm.theta, arm.n = np.asarray(A_inv), np.asarray(b), np.asarray(theta), n
        self._timesteps_total = data.get("timesteps", 0)

    def cleanup(self) -> None:
        env = getattr(self, "env", None)
        if env is not None:
            try:
                env.close()
            except Exception:
                pass


class BanditLinTS(BanditLinUCB):
    """Linear Thompson sampling (reference: BanditLinTS)."""

    _exploration = "thompson"
