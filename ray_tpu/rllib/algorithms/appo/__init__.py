from ray_tpu.rllib.algorithms.appo.appo import APPO, APPOConfig  # noqa: F401
