"""APPO — asynchronous PPO (IMPALA architecture + clipped surrogate).

Reference: rllib/algorithms/appo/appo.py (+ appo_torch_policy loss): the
IMPALA actor-learner decoupling (behavior-policy rollouts, V-trace targets)
with PPO's clipped-surrogate objective computed against the V-trace policy-
gradient advantages, plus a target network whose KL anchors the update
(use_kl_loss). TPU shape matches our IMPALA: decoupled staleness is modeled
by broadcast_interval, the correction lives inside one jitted loss.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    FRAG_CUT,
    LOGPS,
    NEXT_VF_PREDS,
    OBS,
    REWARDS,
    SampleBatch,
)


def appo_loss(params, batch, spec, cfg):
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.core import rl_module
    from ray_tpu.rllib.utils.vtrace import vtrace

    logp, entropy, values = rl_module.action_logp_and_entropy(
        params, batch[OBS], batch[ACTIONS], spec
    )
    nonterminal = 1.0 - batch[DONES].astype(values.dtype)
    cuts = batch[FRAG_CUT].astype(values.dtype)
    vs, pg_adv, _ = vtrace(
        jax.lax.stop_gradient(values), batch[NEXT_VF_PREDS], logp, batch[LOGPS],
        batch[REWARDS], nonterminal, cuts, cfg["gamma"], cfg["rho_bar"], cfg["c_bar"],
    )
    # PPO surrogate on the V-trace advantages (reference: appo loss).
    ratio = jnp.exp(logp - batch[LOGPS])
    clip = cfg["clip_param"]
    surrogate = jnp.minimum(ratio * pg_adv, jnp.clip(ratio, 1 - clip, 1 + clip) * pg_adv)
    policy_loss = -surrogate.mean()
    vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
    entropy_mean = entropy.mean()
    # KL(behavior || current) as a soft anchor (reference: use_kl_loss).
    kl = (batch[LOGPS] - logp).mean()
    total = (
        policy_loss
        + cfg["vf_loss_coeff"] * vf_loss
        - cfg["entropy_coeff"] * entropy_mean
        + cfg["kl_coeff"] * jnp.maximum(kl, 0.0)
    )
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy_mean,
        "kl": kl,
    }


class APPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.lr = 5e-4
        self.train_batch_size = 2000
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.kl_coeff = 0.2
        self.grad_clip = 40.0
        self.rho_bar = 1.0
        self.c_bar = 1.0
        self.num_sgd_iter = 2
        self.broadcast_interval = 1
        # Background-thread actors (see IMPALAConfig.async_sampling): the
        # v-trace + PPO-clip loss absorbs the added staleness.
        self.async_sampling = False

    def training(self, *, clip_param: Optional[float] = None, vf_loss_coeff: Optional[float] = None,
                 entropy_coeff: Optional[float] = None, kl_coeff: Optional[float] = None,
                 rho_bar: Optional[float] = None, c_bar: Optional[float] = None,
                 num_sgd_iter: Optional[int] = None, broadcast_interval: Optional[int] = None,
                 async_sampling: Optional[bool] = None,
                 **kwargs) -> "APPOConfig":
        super().training(**kwargs)
        for name, value in (
            ("clip_param", clip_param), ("vf_loss_coeff", vf_loss_coeff),
            ("entropy_coeff", entropy_coeff), ("kl_coeff", kl_coeff),
            ("rho_bar", rho_bar), ("c_bar", c_bar),
            ("num_sgd_iter", num_sgd_iter), ("broadcast_interval", broadcast_interval),
            ("async_sampling", async_sampling),
        ):
            if value is not None:
                setattr(self, name, value)
        return self


class APPO(Algorithm):
    @classmethod
    def get_default_config(cls) -> APPOConfig:
        return APPOConfig(cls)

    def _build_learner_group(self, cfg: APPOConfig) -> LearnerGroup:
        return LearnerGroup(
            self.module_spec,
            appo_loss,
            lr=cfg.lr,
            grad_clip=cfg.grad_clip,
            seed=cfg.seed,
            num_learners=cfg.num_learners,
            num_tpus_per_learner=cfg.num_tpus_per_learner,
            use_mesh=getattr(cfg, "learner_mesh", False),
            grad_sync=getattr(cfg, "grad_sync", "host"),
        )

    def training_step(self) -> dict:
        cfg: APPOConfig = self._algo_config
        batches = self._gather_rollouts(cfg.train_batch_size, cfg.async_sampling)
        if not batches:
            return {"async_waiting": 1.0}
        batch = SampleBatch.concat_samples(batches)
        self._timesteps_total += batch.count
        loss_cfg = {
            "gamma": cfg.gamma,
            "rho_bar": cfg.rho_bar,
            "c_bar": cfg.c_bar,
            "clip_param": cfg.clip_param,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
            "kl_coeff": cfg.kl_coeff,
        }
        # V-trace needs contiguous time order — whole-batch epochs, no
        # shuffled minibatches (same constraint as IMPALA).
        metrics = {}
        for _ in range(cfg.num_sgd_iter):
            metrics = self.learner_group.update(batch, loss_cfg)
        if self.iteration % max(cfg.broadcast_interval, 1) == 0:
            # Podracer seam: device-object group broadcast when configured.
            self.sync_worker_weights()
        metrics["num_env_steps_sampled_this_iter"] = batch.count
        return dict(metrics)
