"""Shared plumbing for the replay-based algorithms (SAC/DDPG/TD3/CQL).

These all hold `self.params` / `self.target` pytrees and a timestep counter;
step timing, the 100-episode reward window, and params/target checkpointing
are identical — one mixin instead of three copies (the reference similarly
shares via Algorithm + build_policy hooks).
"""

from __future__ import annotations

import time

import numpy as np


class OffPolicyTraining:
    def step(self) -> dict:
        t0 = time.time()
        result = self.training_step()
        window = getattr(self, "_episode_reward_window", [])
        result["episode_reward_mean"] = (
            float(np.mean(window)) if window else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def save_checkpoint(self):
        import jax

        from ray_tpu.air.checkpoint import Checkpoint

        # Optimizer state, RNGs, and the policy-delay counter are part of the
        # training state: dropping them silently resets Adam moments and
        # DDPG/TD3's delayed-actor phase on restore (reference policy state
        # includes optimizer variables).
        state = {
            "params": jax.tree_util.tree_map(np.asarray, self.params),
            "target": jax.tree_util.tree_map(np.asarray, self.target),
            "opt_state": jax.tree_util.tree_map(np.asarray, self.opt_state),
            "rng": np.asarray(self._rng),
            # Snapshot the bit-generator state dict, not the live Generator:
            # the object would keep mutating after save (and aliasing it on
            # load would share one stream between algorithms).
            # Offline algos (CQL) have no exploration rng.
            "np_rng_state": (
                self._np_rng.bit_generator.state if hasattr(self, "_np_rng") else None
            ),
            "timesteps": self._timesteps_total,
        }
        if hasattr(self, "_updates"):
            state["updates"] = self._updates
        return Checkpoint.from_dict(state)

    def load_checkpoint(self, checkpoint) -> None:
        import jax
        import jax.numpy as jnp

        data = checkpoint.to_dict()
        self.params = jax.tree_util.tree_map(jnp.asarray, data["params"])
        self.target = jax.tree_util.tree_map(jnp.asarray, data["target"])
        if "opt_state" in data:
            self.opt_state = jax.tree_util.tree_map(jnp.asarray, data["opt_state"])
        if "rng" in data:
            self._rng = jnp.asarray(data["rng"])
        if data.get("np_rng_state") is not None:
            self._np_rng = np.random.default_rng()
            self._np_rng.bit_generator.state = data["np_rng_state"]
        if "updates" in data:
            self._updates = data["updates"]
        self._timesteps_total = data.get("timesteps", 0)

    def cleanup(self) -> None:
        env = getattr(self, "env", None)
        if env is not None:
            env.close()


def floats(metric_tree) -> dict:
    """Convert a jitted step's metric pytree to host floats — call ONCE per
    iteration after the update loop, not per gradient step (each conversion
    blocks on the device and would defeat async dispatch in the hot loop)."""
    return {k: float(v) for k, v in dict(metric_tree).items()}
