"""SlateQ — Q-learning for slate recommendation (Ie et al. 2019).

Reference: rllib/algorithms/slateq/ (slateq.py, slateq_torch_policy.py):
the combinatorial slate action space is made tractable by SlateQ's
DECOMPOSITION under a conditional-logistic user choice model:

    Q(s, slate) = sum_i P(click i | s, slate) * q(s, d_i)

where q(s, d) is a learned per-DOCUMENT Q-value and the click
probabilities come from a choice model with learned user/doc affinity
scores. The TD target bootstraps with the best next slate, found by the
reference's default greedy optimizer (top-k by v(s,d)*q(s,d) score — exact
for this choice-model family). Both the per-item q-network and the choice
model's affinity head train jointly: q by SARSA-style decomposed TD on
clicked items, the choice model by maximum likelihood on observed clicks.

TPU-native shape: candidates are a [C, F] tensor; per-item q and affinity
are batched matmuls over all candidates at once, and the greedy slate is a
top-k — no per-item Python, one jitted update.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.off_policy import OffPolicyTraining
from ray_tpu.rllib.algorithms.sac.sac import _mlp_apply, _mlp_params
from ray_tpu.rllib.env.recsys import SlateRecEnv
from ray_tpu.rllib.utils.replay_buffers import ColumnReplayBuffer


class SlateQConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SlateQ)
        self.lr = 1e-3
        self.choice_lr = 1e-3
        self.num_rollout_workers = 0
        self.train_batch_size = 64
        self.replay_buffer_capacity = 50_000
        self.learning_starts = 500
        self.target_network_update_freq = 100
        self.rollout_steps_per_iter = 400
        self.train_intensity = 4
        self.epsilon_timesteps = 6000
        self.initial_epsilon = 1.0
        self.final_epsilon = 0.05
        self.model_hiddens = (64, 64)

    def training(self, *, choice_lr=None, replay_buffer_capacity=None,
                 learning_starts=None, target_network_update_freq=None,
                 rollout_steps_per_iter=None, train_intensity=None,
                 epsilon_timesteps=None, final_epsilon=None, **kwargs) -> "SlateQConfig":
        super().training(**kwargs)
        for name, val in (
            ("choice_lr", choice_lr),
            ("replay_buffer_capacity", replay_buffer_capacity),
            ("learning_starts", learning_starts),
            ("target_network_update_freq", target_network_update_freq),
            ("rollout_steps_per_iter", rollout_steps_per_iter),
            ("train_intensity", train_intensity),
            ("epsilon_timesteps", epsilon_timesteps),
            ("final_epsilon", final_epsilon),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class SlateQ(OffPolicyTraining, Algorithm):
    @classmethod
    def get_default_config(cls) -> SlateQConfig:
        return SlateQConfig(cls)

    def setup(self, config: dict) -> None:
        import jax
        import optax

        cfg: SlateQConfig = self._algo_config
        env = cfg.env(dict(cfg.env_config)) if callable(cfg.env) else cfg.env
        assert isinstance(env, SlateRecEnv), (
            "SlateQ requires a SlateRecEnv-style slate environment "
            "(user state + candidate docs + slate actions)"
        )
        self.env = env
        self.C = env.num_candidates
        self.K = env.slate_size
        self.F = env.num_topics + 1  # doc features + quality
        self.user_dim = env.num_topics
        self.no_click_mass = env.no_click_mass

        keys = jax.random.split(jax.random.PRNGKey(cfg.seed), 2)
        H = cfg.model_hiddens
        # Per-item q(s, d) and choice-affinity v(s, d): both take
        # [user_state, doc_features] and emit a scalar.
        self.params = {
            "q": _mlp_params(keys[0], self.user_dim + self.F, H, 1),
            "choice": _mlp_params(keys[1], self.user_dim + self.F, H, 1),
        }
        # Target tree stays DEVICE-side: converting per update would
        # re-upload both MLPs on every gradient step.
        self.target_params = self.params
        self.tx = optax.multi_transform(
            {
                "q": optax.adam(cfg.lr),
                "choice": optax.adam(cfg.choice_lr),
            },
            param_labels={"q": "q", "choice": "choice"},
        )
        self.opt_state = self.tx.init(self.params)
        self.buffer = ColumnReplayBuffer(cfg.replay_buffer_capacity, cfg.seed)
        self._timesteps_total = 0
        self._updates = 0
        self._episode_reward_window: list = []
        self._ep_reward = 0.0
        self._rng = np.random.default_rng(cfg.seed)
        self._obs, _ = env.reset(seed=cfg.seed)
        self._build_fns(cfg)

    # -- obs helpers ----------------------------------------------------

    def _split_obs(self, obs):
        user = obs[..., : self.user_dim]
        docs = obs[..., self.user_dim :].reshape(*obs.shape[:-1], self.C, self.F)
        return user, docs

    def _build_fns(self, cfg: SlateQConfig):
        import jax
        import jax.numpy as jnp
        import optax

        K, C = self.K, self.C
        gamma = cfg.gamma
        no_click = self.no_click_mass
        user_dim = self.user_dim
        F = self.F
        tx = self.tx

        def per_item(params_head, user, docs):
            """[B,user] x [B,C,F] -> [B,C] scalars."""
            B = user.shape[0]
            inp = jnp.concatenate(
                [jnp.broadcast_to(user[:, None, :], (B, C, user_dim)), docs], -1
            )
            return _mlp_apply(params_head, inp.reshape(B * C, user_dim + F)).reshape(B, C)

        def greedy_slate_value(params, user, docs):
            """Decomposed value of the greedy slate (reference: greedy slate
            optimizer — for conditional-logistic choice, top-k by v*q score
            is the optimizer's default)."""
            q = per_item(params["q"], user, docs)        # [B,C]
            v = per_item(params["choice"], user, docs)   # [B,C] affinities
            # Ie et al.'s exactness proof for top-k-by-exp(v)*q assumes
            # q >= 0. For q <= 0 the affinity weight inverts the ordering
            # (high-v bad items score MORE negative than low-v worse items),
            # and a bare max(q,0) clamp ties all negative items at 0 so
            # top_k seats them by index. Rank positives by the proven score
            # and negatives by raw q (least harmful first, no ties): every
            # positive item still outranks every negative one.
            score = jnp.where(q > 0, jnp.exp(v) * q, q)
            top = jax.lax.top_k(score, K)[1]             # [B,K]
            v_top = jnp.take_along_axis(v, top, 1)
            q_top = jnp.take_along_axis(q, top, 1)
            w = jnp.exp(v_top)
            denom = w.sum(1) + no_click
            return (w * q_top).sum(1) / denom, top

        self._greedy = jax.jit(lambda p, u, d: greedy_slate_value(p, u, d)[1])

        def update(params, target_params, opt_state, batch):
            user, docs = batch["user"], batch["docs"]
            nuser, ndocs = batch["next_user"], batch["next_docs"]
            slate = batch["slate"].astype(jnp.int32)      # [B,K]
            clicked = batch["clicked"].astype(jnp.int32)  # [B] index into slate or -1
            rew = batch["reward"]
            dones = batch["done"]

            next_val, _ = greedy_slate_value(target_params, nuser, ndocs)
            y = rew + gamma * (1.0 - dones) * next_val
            y = jax.lax.stop_gradient(y)

            def loss_fn(p):
                q_all = per_item(p["q"], user, docs)
                v_all = per_item(p["choice"], user, docs)
                q_slate = jnp.take_along_axis(q_all, slate, 1)  # [B,K]
                v_slate = jnp.take_along_axis(v_all, slate, 1)
                # --- decomposed TD: regress the CLICKED item's q to y ---
                did_click = clicked >= 0
                safe_click = jnp.maximum(clicked, 0)
                q_clicked = jnp.take_along_axis(q_slate, safe_click[:, None], 1)[:, 0]
                td = jnp.where(did_click, q_clicked - y, 0.0)
                q_loss = jnp.sum(jnp.square(td)) / jnp.maximum(did_click.sum(), 1)
                # --- choice model: MLE of the observed click/no-click ---
                logits = jnp.concatenate(
                    [v_slate, jnp.full((v_slate.shape[0], 1), jnp.log(no_click))], 1
                )
                logp = jax.nn.log_softmax(logits, -1)
                choice_idx = jnp.where(did_click, safe_click, K)  # K = no-click slot
                nll = -jnp.take_along_axis(logp, choice_idx[:, None], 1)[:, 0]
                choice_loss = nll.mean()
                return q_loss + choice_loss, {
                    "q_loss": q_loss,
                    "choice_loss": choice_loss,
                    "click_rate": did_click.mean(),
                }

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux = dict(aux)
            aux["total_loss"] = loss
            return params, opt_state, aux

        self._update = jax.jit(update)

    def _epsilon(self) -> float:
        cfg = self._algo_config
        frac = min(1.0, self._timesteps_total / max(cfg.epsilon_timesteps, 1))
        return cfg.initial_epsilon + frac * (cfg.final_epsilon - cfg.initial_epsilon)

    def _pick_slate(self, obs, explore: bool):
        import jax.numpy as jnp

        if explore and self._rng.random() < self._epsilon():
            return self._rng.choice(self.C, self.K, replace=False)
        user, docs = self._split_obs(np.asarray(obs, np.float32))
        slate = np.asarray(
            self._greedy(
                self._as_jax(self.params), jnp.asarray(user[None]), jnp.asarray(docs[None])
            )
        )[0]
        return slate

    def training_step(self) -> dict:
        cfg: SlateQConfig = self._algo_config
        metrics: dict = {}
        for _ in range(cfg.rollout_steps_per_iter):
            obs = self._obs
            slate = self._pick_slate(obs, explore=True)
            nobs, reward, done, _trunc, info = self.env.step(slate)
            user, docs = self._split_obs(np.asarray(obs, np.float32))
            nuser, ndocs = self._split_obs(np.asarray(nobs, np.float32))
            clicked_doc = info.get("clicked", -1)
            clicked_pos = -1
            for pos, doc in enumerate(slate):
                if doc == clicked_doc:
                    clicked_pos = pos
                    break
            self.buffer.add({
                "user": user, "docs": docs, "next_user": nuser, "next_docs": ndocs,
                "slate": np.asarray(slate, np.int32),
                "clicked": np.int32(clicked_pos),
                "reward": np.float32(reward), "done": np.float32(done),
            })
            self._ep_reward += reward
            self._timesteps_total += 1
            if done:
                self._episode_reward_window.append(self._ep_reward)
                self._episode_reward_window = self._episode_reward_window[-100:]
                self._ep_reward = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nobs
            if (
                len(self.buffer) >= cfg.learning_starts
                and self._timesteps_total % max(1, cfg.train_intensity) == 0
            ):
                metrics = self._train_once()
        metrics["epsilon"] = self._epsilon()
        return metrics

    def _train_once(self) -> dict:
        import jax
        import jax.numpy as jnp

        cfg = self._algo_config
        batch = {k: jnp.asarray(v) for k, v in self.buffer.sample(cfg.train_batch_size).items()}
        self.params, self.opt_state, aux = self._update(
            self.params, self.target_params, self.opt_state, batch
        )
        self._updates += 1
        if self._updates % cfg.target_network_update_freq == 0:
            # Hard sync: the params tree is immutable (updates build new
            # trees), so aliasing is a correct snapshot.
            self.target_params = self.params
        return {k: float(v) for k, v in aux.items()}

    @staticmethod
    def _as_jax(tree):
        import jax
        import jax.numpy as jnp

        return jax.tree_util.tree_map(jnp.asarray, tree)

    def compute_single_action(self, obs, explore: bool = False):
        return self._pick_slate(obs, explore=explore)

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "params": self.params,
            "target": self.target_params,
            "opt_state": self.opt_state,
            "timesteps": self._timesteps_total,
            # Training state a resume must not silently reset: the target-
            # sync phase and the epsilon-greedy exploration stream.
            "updates": self._updates,
            "np_rng_state": self._rng.bit_generator.state,
        })

    def load_checkpoint(self, checkpoint) -> None:
        data = checkpoint.to_dict()
        self.params = data["params"]
        self.target_params = data["target"]
        self.opt_state = data["opt_state"]
        self._timesteps_total = data.get("timesteps", 0)
        self._updates = data.get("updates", 0)
        if "np_rng_state" in data:
            self._rng.bit_generator.state = data["np_rng_state"]

    def cleanup(self) -> None:
        if getattr(self, "env", None) is not None:
            self.env.close()
