from ray_tpu.rllib.algorithms.slateq.slateq import SlateQ, SlateQConfig

__all__ = ["SlateQ", "SlateQConfig"]
