"""PG — vanilla policy gradient (REINFORCE).

Reference: rllib/algorithms/pg/{pg.py,pg_torch_policy.py}: the simplest
on-policy algorithm — no critic, no clipping; the gradient weight is the
Monte-Carlo return-to-go, batch-normalized as a variance-reduction baseline
(the reference's advantages with use_critic=False reduce to the same thing).
Kept as its own algorithm (not an A2C flag) mirroring the reference's
separate pg/ family and as the minimal template for new on-policy algos.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.policy.sample_batch import ACTIONS, OBS, VALUE_TARGETS, SampleBatch


def pg_loss(params, batch, spec, cfg):
    import jax.numpy as jnp

    from ray_tpu.rllib.core import rl_module

    logp, entropy, _value = rl_module.action_logp_and_entropy(
        params, batch[OBS], batch[ACTIONS], spec
    )
    ret = batch[VALUE_TARGETS]  # discounted returns-to-go
    ret = (ret - ret.mean()) / (ret.std() + 1e-8)
    entropy_mean = entropy.mean()
    total = -jnp.mean(logp * ret) - cfg["entropy_coeff"] * entropy_mean
    return total, {"policy_loss": total, "entropy": entropy_mean}


class PGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PG)
        self.lr = 4e-3
        self.train_batch_size = 2000
        self.entropy_coeff = 0.0
        self.grad_clip = 40.0
        # REINFORCE uses Monte-Carlo returns: lambda_=1 collapses GAE to
        # discounted returns minus the value prediction; with the critic
        # untrained the loss re-centers by the batch mean anyway. lambda_ is
        # the field WorkerSet actually consumes for GAE.
        self.lambda_ = 1.0

    def training(self, *, entropy_coeff: Optional[float] = None, **kwargs) -> "PGConfig":
        super().training(**kwargs)
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        return self


class PG(Algorithm):
    @classmethod
    def get_default_config(cls) -> PGConfig:
        return PGConfig(cls)

    def _build_learner_group(self, cfg: PGConfig) -> LearnerGroup:
        return LearnerGroup(
            self.module_spec,
            pg_loss,
            lr=cfg.lr,
            grad_clip=cfg.grad_clip,
            seed=cfg.seed,
            num_learners=cfg.num_learners,
            num_tpus_per_learner=cfg.num_tpus_per_learner,
        )

    def training_step(self) -> dict:
        cfg: PGConfig = self._algo_config
        per_worker = max(
            1, cfg.train_batch_size // max(self.workers.num_workers, 1) // cfg.num_envs_per_worker
        )
        batches = self.workers.sample(per_worker)
        batch = SampleBatch.concat_samples(batches)
        self._timesteps_total += batch.count
        metrics = self.learner_group.update(batch, {"entropy_coeff": cfg.entropy_coeff})
        self.workers.sync_weights(self.learner_group.get_weights())
        metrics["num_env_steps_sampled_this_iter"] = batch.count
        return dict(metrics)
