from ray_tpu.rllib.algorithms.pg.pg import PG, PGConfig  # noqa: F401
