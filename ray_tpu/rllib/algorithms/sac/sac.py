"""SAC — soft actor-critic (continuous and discrete action spaces).

Reference: rllib/algorithms/sac/ (sac.py, sac_torch_policy.py,
sac_torch_model.py): off-policy replay, twin Q networks with Polyak-averaged
targets, tanh-squashed gaussian policy (continuous) or categorical policy with
exact expectations (discrete), and automatic entropy-temperature tuning.
TPU-native design: actor, twin critics, targets, and the alpha update are one
pytree stepped by a single jitted function — the three optimizer updates fuse
into one XLA program instead of three sequential torch backward passes.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.off_policy import OffPolicyTraining, floats
from ray_tpu.rllib.env.vector_env import VectorEnv
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def _true_transition(env, dones, infos):
    """(next_obs, terminated-mask) for replay: at episode boundaries the true
    s' is the PRE-reset observation, and only real terminations (not
    time-limit truncations) zero the TD bootstrap."""
    next_obs = env.current_obs().astype(np.float32).reshape(env.num_envs, -1)
    terminateds = np.zeros(env.num_envs, np.float32)
    for i, (d, info) in enumerate(zip(dones, infos)):
        if d:
            next_obs[i] = np.asarray(info["final_observation"], np.float32).reshape(-1)
            terminateds[i] = float(info.get("terminated", True))
    return next_obs, terminateds


def _dense(key, din, dout):
    import jax
    import jax.numpy as jnp

    w = jax.nn.initializers.glorot_uniform()(key, (din, dout), jnp.float32)
    return {"w": w, "b": jnp.zeros((dout,), jnp.float32)}


def _mlp_params(key, din, hiddens, dout):
    import jax

    keys = jax.random.split(key, len(hiddens) + 1)
    layers = []
    for i, h in enumerate(hiddens):
        layers.append(_dense(keys[i], din, h))
        din = h
    layers.append(_dense(keys[-1], din, dout))
    return layers


def _mlp_apply(layers, x):
    import jax

    for layer in layers[:-1]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    return x @ layers[-1]["w"] + layers[-1]["b"]


def init_sac_params(rng, obs_dim, action_dim, discrete, hiddens):
    import jax

    ka, k1, k2 = jax.random.split(rng, 3)
    if discrete:
        actor = _mlp_params(ka, obs_dim, hiddens, action_dim)
        q1 = _mlp_params(k1, obs_dim, hiddens, action_dim)
        q2 = _mlp_params(k2, obs_dim, hiddens, action_dim)
    else:
        actor = _mlp_params(ka, obs_dim, hiddens, 2 * action_dim)
        q1 = _mlp_params(k1, obs_dim + action_dim, hiddens, 1)
        q2 = _mlp_params(k2, obs_dim + action_dim, hiddens, 1)
    import jax.numpy as jnp

    return {"actor": actor, "q1": q1, "q2": q2, "log_alpha": jnp.zeros(())}


def _squashed_sample(actor, obs, key, action_dim):
    """tanh-squashed gaussian: sample, logp with the tanh jacobian term."""
    import jax
    import jax.numpy as jnp

    out = _mlp_apply(actor, obs)
    mean, log_std = out[:, :action_dim], out[:, action_dim:]
    log_std = jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)
    std = jnp.exp(log_std)
    u = mean + std * jax.random.normal(key, mean.shape)
    a = jnp.tanh(u)
    logp = -0.5 * jnp.sum(((u - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi), axis=-1)
    logp -= jnp.sum(2.0 * (jnp.log(2.0) - u - jax.nn.softplus(-2.0 * u)), axis=-1)
    return a, logp, jnp.tanh(mean)


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self.lr = 3e-4
        self.num_rollout_workers = 0  # off-policy: collect in-process
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.learning_starts = 1500
        self.tau = 5e-3
        self.initial_alpha = 1.0
        self.target_entropy: Optional[float] = None  # None -> auto
        self.rollout_steps_per_iter = 1000
        self.train_intensity = 1  # gradient steps per env step
        self.model_hiddens = (256, 256)

    def training(self, *, replay_buffer_capacity=None, learning_starts=None,
                 tau=None, initial_alpha=None, target_entropy=None,
                 rollout_steps_per_iter=None, train_intensity=None, **kwargs) -> "SACConfig":
        super().training(**kwargs)
        for name, val in (
            ("replay_buffer_capacity", replay_buffer_capacity),
            ("learning_starts", learning_starts),
            ("tau", tau),
            ("initial_alpha", initial_alpha),
            ("target_entropy", target_entropy),
            ("rollout_steps_per_iter", rollout_steps_per_iter),
            ("train_intensity", train_intensity),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class SAC(OffPolicyTraining, Algorithm):
    @classmethod
    def get_default_config(cls) -> SACConfig:
        return SACConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        self.cleanup()  # re-setup: close any previous env
        cfg: SACConfig = self._algo_config
        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        self.discrete = isinstance(probe.action_space, gym.spaces.Discrete)
        self.obs_dim = int(np.prod(probe.observation_space.shape))
        if self.discrete:
            self.action_dim = int(probe.action_space.n)
            self._act_scale = self._act_offset = None
        else:
            self.action_dim = int(np.prod(probe.action_space.shape))
            low = np.asarray(probe.action_space.low, np.float32)
            high = np.asarray(probe.action_space.high, np.float32)
            self._act_scale = (high - low) / 2.0
            self._act_offset = (high + low) / 2.0
        probe.close()
        self.env = VectorEnv(cfg.env, max(cfg.num_envs_per_worker, 1), cfg.env_config, 0, seed=cfg.seed)
        self.params = init_sac_params(
            jax.random.PRNGKey(cfg.seed), self.obs_dim, self.action_dim, self.discrete, cfg.model_hiddens
        )
        self.params["log_alpha"] = jnp.log(jnp.asarray(cfg.initial_alpha, jnp.float32))
        self.target = {"q1": self.params["q1"], "q2": self.params["q2"]}
        if cfg.target_entropy is not None:
            self.target_entropy = float(cfg.target_entropy)
        elif self.discrete:
            self.target_entropy = 0.98 * float(np.log(self.action_dim))
        else:
            self.target_entropy = -float(self.action_dim)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self.buffer = ReplayBuffer(cfg.replay_buffer_capacity, seed=cfg.seed)
        self._rng = jax.random.PRNGKey(cfg.seed + 1)
        self._np_rng = np.random.default_rng(cfg.seed)
        self._timesteps_total = 0
        self._episode_reward_window: list = []
        self._build_fns(cfg)

    def _build_fns(self, cfg: SACConfig):
        import jax
        import jax.numpy as jnp

        discrete, action_dim = self.discrete, self.action_dim
        gamma, tau, target_entropy = cfg.gamma, cfg.tau, self.target_entropy
        tx = self.tx

        def loss_fn(params, target, batch, key):
            obs, next_obs = batch[OBS], batch[NEXT_OBS]
            rewards, dones = batch[REWARDS], batch[DONES]
            alpha = jax.lax.stop_gradient(jnp.exp(params["log_alpha"]))
            if discrete:
                logits = _mlp_apply(params["actor"], obs)
                logpi = jax.nn.log_softmax(logits)
                pi = jnp.exp(logpi)
                next_logits = _mlp_apply(params["actor"], next_obs)
                next_logpi = jax.nn.log_softmax(next_logits)
                next_pi = jnp.exp(next_logpi)
                tq = jnp.minimum(_mlp_apply(target["q1"], next_obs), _mlp_apply(target["q2"], next_obs))
                next_v = jnp.sum(next_pi * (tq - alpha * next_logpi), axis=-1)
                td_target = jax.lax.stop_gradient(rewards + gamma * (1 - dones) * next_v)
                idx = batch[ACTIONS].astype(jnp.int32)
                q1 = _mlp_apply(params["q1"], obs)[jnp.arange(obs.shape[0]), idx]
                q2 = _mlp_apply(params["q2"], obs)[jnp.arange(obs.shape[0]), idx]
                critic_loss = 0.5 * (jnp.mean((q1 - td_target) ** 2) + jnp.mean((q2 - td_target) ** 2))
                q_min = jax.lax.stop_gradient(
                    jnp.minimum(_mlp_apply(params["q1"], obs), _mlp_apply(params["q2"], obs))
                )
                actor_loss = jnp.mean(jnp.sum(pi * (alpha * logpi - q_min), axis=-1))
                entropy = -jnp.sum(pi * logpi, axis=-1).mean()
                alpha_loss = params["log_alpha"] * jax.lax.stop_gradient(entropy - target_entropy)
            else:
                k1, k2 = jax.random.split(key)
                next_a, next_logp, _ = _squashed_sample(params["actor"], next_obs, k1, action_dim)
                tq1 = _mlp_apply(target["q1"], jnp.concatenate([next_obs, next_a], -1))[:, 0]
                tq2 = _mlp_apply(target["q2"], jnp.concatenate([next_obs, next_a], -1))[:, 0]
                next_v = jnp.minimum(tq1, tq2) - alpha * next_logp
                td_target = jax.lax.stop_gradient(rewards + gamma * (1 - dones) * next_v)
                sa = jnp.concatenate([obs, batch[ACTIONS]], -1)
                q1 = _mlp_apply(params["q1"], sa)[:, 0]
                q2 = _mlp_apply(params["q2"], sa)[:, 0]
                critic_loss = 0.5 * (jnp.mean((q1 - td_target) ** 2) + jnp.mean((q2 - td_target) ** 2))
                a, logp, _ = _squashed_sample(params["actor"], obs, k2, action_dim)
                # Critic params are stop-gradiented in the actor term: with a
                # single optimizer over the whole tree, -q_pi would otherwise
                # train q1/q2 to inflate Q on policy actions (the discrete
                # branch's q_min stop_gradient is the same guard).
                q_pi = jnp.minimum(
                    _mlp_apply(jax.lax.stop_gradient(params["q1"]), jnp.concatenate([obs, a], -1))[:, 0],
                    _mlp_apply(jax.lax.stop_gradient(params["q2"]), jnp.concatenate([obs, a], -1))[:, 0],
                )
                actor_loss = jnp.mean(alpha * logp - q_pi)
                entropy = -logp.mean()
                alpha_loss = params["log_alpha"] * jax.lax.stop_gradient(entropy - target_entropy)
            total = critic_loss + actor_loss + alpha_loss
            return total, {
                "critic_loss": critic_loss,
                "actor_loss": actor_loss,
                "alpha": alpha,
                "entropy": entropy,
                "mean_q": q1.mean(),
            }

        def train_step(params, target, opt_state, batch, key):
            (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, target, batch, key)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            target = jax.tree_util.tree_map(
                lambda t, p: (1 - tau) * t + tau * p,
                target,
                {"q1": params["q1"], "q2": params["q2"]},
            )
            return params, target, opt_state, metrics

        self._train_step = jax.jit(train_step)

        def act(params, obs, key, explore):
            if discrete:
                logits = _mlp_apply(params["actor"], obs)
                return jax.lax.cond(
                    explore,
                    lambda: jax.random.categorical(key, logits, axis=-1),
                    lambda: jnp.argmax(logits, axis=-1),
                )
            a, _, det = _squashed_sample(params["actor"], obs, key, action_dim)
            return jnp.where(explore, a, det)

        self._act = jax.jit(act)

    def _env_action(self, a):
        if self.discrete:
            return np.asarray(a)
        return np.asarray(a) * self._act_scale + self._act_offset

    def training_step(self) -> dict:
        import jax
        import jax.numpy as jnp

        cfg: SACConfig = self._algo_config
        last_m = None
        for _ in range(cfg.rollout_steps_per_iter):
            obs = self.env.current_obs().astype(np.float32).reshape(self.env.num_envs, -1)
            if self._timesteps_total < cfg.learning_starts:
                if self.discrete:
                    a = self._np_rng.integers(0, self.action_dim, self.env.num_envs)
                else:
                    a = self._np_rng.uniform(-1, 1, (self.env.num_envs, self.action_dim)).astype(np.float32)
            else:
                self._rng, key = jax.random.split(self._rng)
                a = np.asarray(self._act(self.params, jnp.asarray(obs), key, True))
            _, rewards, dones, infos = self.env.step(self._env_action(a))
            next_obs, terminateds = _true_transition(self.env, dones, infos)
            self.buffer.add(SampleBatch({
                OBS: obs, ACTIONS: a, REWARDS: rewards,
                DONES: terminateds, NEXT_OBS: next_obs,
            }))
            self._timesteps_total += self.env.num_envs
            if self._timesteps_total >= cfg.learning_starts:
                for _ in range(cfg.train_intensity):
                    batch = self.buffer.sample(cfg.train_batch_size)
                    jb = {k: jnp.asarray(v) for k, v in batch.items()}
                    self._rng, key = jax.random.split(self._rng)
                    self.params, self.target, self.opt_state, last_m = self._train_step(
                        self.params, self.target, self.opt_state, jb, key
                    )
        stats_r, _ = self.env.pop_episode_stats()
        self._episode_reward_window += stats_r
        self._episode_reward_window = self._episode_reward_window[-100:]
        return floats(last_m) if last_m is not None else {}

    def compute_single_action(self, obs, explore: bool = False):
        import jax
        import jax.numpy as jnp

        obs = np.asarray(obs, np.float32).reshape(1, -1)
        self._rng, key = jax.random.split(self._rng)
        a = np.asarray(self._act(self.params, jnp.asarray(obs), key, explore))[0]
        if self.discrete:
            return int(a)
        return self._env_action(a)
