"""LeelaChessZero — two-player zero-sum AlphaZero with the Lc0 network heads.

Reference: rllib/algorithms/leela_chess_zero/ (leela_chess_zero.py,
lc0_mcts.py, lc0_model.py): AlphaZero-style self-play for alternating-move
zero-sum board games, with the Lc0 network additions over plain AlphaZero —
a POLICY head masked to legal moves, a VALUE head (tanh, mover's
perspective), and a MOVES-LEFT head (Lc0's MLH, regressing remaining game
length; used as a training auxiliary that sharpens endgame play). The
reference binds it to chess through python-chess; here the algorithm runs
on any env/board_env.BoardGameEnv (TicTacToe in-tree — the image carries
no chess move-generator), which is the same separation the reference draws
between algorithm and MultiAgentEnv board wrapper.

Differences from the in-tree single-player AlphaZero (alpha_zero/):
* search values SIGN-FLIP between plies (zero-sum, alternating moves);
* no ranked-rewards transform — outcomes are already ±1/0;
* legal-action masks gate both the network policy and the search;
* the extra moves-left head, trained on |remaining plies|.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig


# ---------------------------------------------------------------------------
# Lc0-style network: shared torso, policy/value/moves-left heads.
# ---------------------------------------------------------------------------

def init_lc0_params(key, obs_dim: int, n_actions: int, hiddens):
    import jax

    dims = (obs_dim,) + tuple(hiddens)
    ks = jax.random.split(key, len(dims) + 2)
    torso = [
        {
            "w": jax.random.normal(k, (din, dout)) * (2.0 / din) ** 0.5,
            "b": jax.numpy.zeros(dout),
        }
        for k, din, dout in zip(ks[:-3], dims[:-1], dims[1:])
    ]
    h = dims[-1]
    s = h**-0.5

    def head(k, dout):
        return {"w": jax.random.normal(k, (h, dout)) * s, "b": jax.numpy.zeros(dout)}

    return {
        "torso": torso,
        "policy": head(ks[-3], n_actions),
        "value": head(ks[-2], 1),
        "mlh": head(ks[-1], 1),
    }


def lc0_forward(params, obs, legal_mask):
    """Returns (masked log-policy, value in [-1,1], moves_left >= 0)."""
    import jax
    import jax.numpy as jnp

    x = obs
    for layer in params["torso"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["policy"]["w"] + params["policy"]["b"]
    logits = jnp.where(legal_mask, logits, -1e9)
    logp = jax.nn.log_softmax(logits, axis=-1)
    value = jnp.tanh(x @ params["value"]["w"] + params["value"]["b"])[..., 0]
    moves_left = jax.nn.softplus(x @ params["mlh"]["w"] + params["mlh"]["b"])[..., 0]
    return logp, value, moves_left


# ---------------------------------------------------------------------------
# Zero-sum PUCT search (lc0_mcts.py analog).
# ---------------------------------------------------------------------------

class _Node:
    __slots__ = ("state", "obs", "legal", "done", "reward", "children", "N", "W", "P")

    def __init__(self, state, obs, legal, done, reward):
        self.state = state
        self.obs = obs
        self.legal = legal
        self.done = done
        self.reward = reward  # terminal reward to the player who JUST moved
        self.children = {}
        n = len(legal)
        self.N = np.zeros(n, np.float32)
        self.W = np.zeros(n, np.float32)
        self.P = np.zeros(n, np.float32)


class ZeroSumMCTS:
    """PUCT over a cloneable BoardGameEnv; values flip sign per ply."""

    def __init__(self, env, predict, *, num_sims=50, c_puct=1.5,
                 dirichlet_alpha=0.6, dirichlet_eps=0.25, rng=None):
        self.env = env
        self.predict = predict  # obs, legal -> (prior probs, value)
        self.num_sims = num_sims
        self.c_puct = c_puct
        self.alpha = dirichlet_alpha
        self.eps = dirichlet_eps
        self.rng = rng or np.random.default_rng()

    def search(self, temperature: float = 1.0):
        root_state = self.env.get_state()
        root_obs = self.env.observe()
        root = _Node(root_state, root_obs, self.env.legal_actions(), False, 0.0)
        priors, _ = self.predict(root_obs, root.legal)
        noise = self.rng.dirichlet([self.alpha] * int(root.legal.sum()))
        p = priors.copy()
        p[root.legal] = (1 - self.eps) * p[root.legal] + self.eps * noise
        root.P = p

        for _ in range(self.num_sims):
            node = root
            path = []
            self.env.set_state(node.state)
            # -- selection --
            while True:
                if node.done:
                    value = 0.0 if node.reward == 0 else -node.reward
                    # value is from the perspective of the player to move at
                    # `node` (who just lost if reward=1 for the mover).
                    break
                a = self._select(node)
                path.append((node, a))
                if a not in node.children:
                    # -- expansion --
                    self.env.set_state(node.state)
                    obs, reward, done = self.env.step(a)
                    child = _Node(
                        self.env.get_state(), obs,
                        self.env.legal_actions() if not done else np.zeros_like(node.legal),
                        done, reward,
                    )
                    node.children[a] = child
                    if done:
                        value = 0.0 if reward == 0 else -reward
                    else:
                        probs, v = self.predict(obs, child.legal)
                        child.P = probs
                        value = v
                    node = child
                    break
                node = node.children[a]

            # -- backup with sign flip per ply --
            for parent, a in reversed(path):
                value = -value  # child's perspective -> parent's
                parent.N[a] += 1.0
                parent.W[a] += value

        visits = root.N
        if temperature <= 1e-6:
            pi = np.zeros_like(visits)
            pi[visits.argmax()] = 1.0
        else:
            v = visits ** (1.0 / temperature)
            pi = v / v.sum() if v.sum() > 0 else root.legal / root.legal.sum()
        self.env.set_state(root_state)
        q_root = float((root.W.sum() / max(root.N.sum(), 1.0)))
        return pi, q_root

    def _select(self, node: _Node) -> int:
        total = node.N.sum()
        q = np.where(node.N > 0, node.W / np.maximum(node.N, 1), 0.0)
        u = self.c_puct * node.P * math.sqrt(total + 1.0) / (1.0 + node.N)
        score = np.where(node.legal, q + u, -np.inf)
        return int(score.argmax())


class LeelaChessZeroConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or LeelaChessZero)
        self.lr = 2e-3
        self.num_sims = 60
        self.c_puct = 1.5
        self.dirichlet_alpha = 0.6
        self.dirichlet_eps = 0.25
        self.games_per_iter = 12
        self.temperature_moves = 4   # sample by visits for the first k plies
        self.train_batch_size = 256
        self.sgd_iters = 4
        self.replay_games = 400
        self.mlh_loss_coeff = 0.1
        self.model_hiddens = (128, 128)

    def training(self, *, num_sims=None, c_puct=None, dirichlet_alpha=None,
                 dirichlet_eps=None, games_per_iter=None, temperature_moves=None,
                 sgd_iters=None, replay_games=None, mlh_loss_coeff=None, **kwargs):
        super().training(**kwargs)
        for name, val in (
            ("num_sims", num_sims), ("c_puct", c_puct),
            ("dirichlet_alpha", dirichlet_alpha), ("dirichlet_eps", dirichlet_eps),
            ("games_per_iter", games_per_iter), ("temperature_moves", temperature_moves),
            ("sgd_iters", sgd_iters), ("replay_games", replay_games),
            ("mlh_loss_coeff", mlh_loss_coeff),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class LeelaChessZero(Algorithm):
    @classmethod
    def get_default_config(cls) -> LeelaChessZeroConfig:
        return LeelaChessZeroConfig(cls)

    def setup(self, config: dict) -> None:
        import jax
        import optax

        self.cleanup()
        cfg: LeelaChessZeroConfig = self._algo_config
        self.env = cfg.env(dict(cfg.env_config)) if callable(cfg.env) else cfg.env
        assert hasattr(self.env, "legal_actions") and hasattr(self.env, "get_state"), (
            "LeelaChessZero needs a BoardGameEnv (legal_actions/get_state/set_state)"
        )
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.n_actions = int(self.env.action_space.n)
        self.params = init_lc0_params(
            jax.random.PRNGKey(cfg.seed), self.obs_dim, self.n_actions, cfg.model_hiddens
        )
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        mlh_coeff = cfg.mlh_loss_coeff

        def predict(params, obs, legal):
            logp, v, _ = lc0_forward(params, obs[None], legal[None])
            return jax.numpy.exp(logp)[0], v[0]

        self._predict = jax.jit(predict)

        def update(params, opt_state, obs, legal, target_pi, target_v, target_ml):
            def loss_fn(p):
                logp, v, ml = lc0_forward(p, obs, legal)
                pi_loss = -(target_pi * logp).sum(-1).mean()
                v_loss = ((v - target_v) ** 2).mean()
                ml_loss = ((ml - target_ml) ** 2).mean()
                return pi_loss + v_loss + mlh_coeff * ml_loss, (pi_loss, v_loss, ml_loss)

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(update)
        self._np_rng = np.random.default_rng(cfg.seed)
        # Replay of recent self-play positions (obs, legal, pi, z, ml).
        self._replay: list = []
        self._timesteps_total = 0
        self._episode_reward_window: list = []

    def _mcts(self) -> ZeroSumMCTS:
        cfg = self._algo_config

        def predict(obs, legal):
            p, v = self._predict(self.params, np.asarray(obs, np.float32), np.asarray(legal))
            return np.asarray(p), float(v)

        return ZeroSumMCTS(
            self.env, predict, num_sims=cfg.num_sims, c_puct=cfg.c_puct,
            dirichlet_alpha=cfg.dirichlet_alpha, dirichlet_eps=cfg.dirichlet_eps,
            rng=self._np_rng,
        )

    def _self_play_game(self):
        """One self-play game; returns per-position training rows."""
        cfg: LeelaChessZeroConfig = self._algo_config
        obs = self.env.reset()
        mcts = self._mcts()
        rows = []  # (obs, legal, pi, player_sign)
        outcome = 0.0  # from player +1 (first mover) perspective
        sign = 1.0
        ply = 0
        while True:
            legal = self.env.legal_actions()
            temp = 1.0 if ply < cfg.temperature_moves else 1e-7
            pi, _ = mcts.search(temperature=temp)
            rows.append((np.asarray(obs, np.float32), legal.copy(), pi, sign, ply))
            a = int(self._np_rng.choice(self.n_actions, p=pi / pi.sum()))
            obs, reward, done = self.env.step(a)
            self._timesteps_total += 1
            ply += 1
            if done:
                outcome = reward * sign  # mover's reward -> first-mover frame
                break
            sign = -sign
        total_plies = ply
        out = []
        for o, legal, pi, s, p_idx in rows:
            # z from THIS position's player-to-move perspective.
            z = outcome * s
            moves_left = float(total_plies - p_idx)
            out.append((o, legal, pi.astype(np.float32), np.float32(z), np.float32(moves_left)))
        return out, outcome

    def training_step(self) -> dict:
        import jax.numpy as jnp

        cfg: LeelaChessZeroConfig = self._algo_config
        first_mover_results = []
        for _ in range(cfg.games_per_iter):
            rows, outcome = self._self_play_game()
            self._replay.append(rows)
            first_mover_results.append(outcome)
        self._replay = self._replay[-cfg.replay_games:]
        flat = [r for game in self._replay for r in game]
        metrics: dict = {}
        if len(flat) >= cfg.train_batch_size:
            for _ in range(cfg.sgd_iters):
                idx = self._np_rng.choice(len(flat), cfg.train_batch_size, replace=False)
                obs = jnp.asarray(np.stack([flat[i][0] for i in idx]))
                legal = jnp.asarray(np.stack([flat[i][1] for i in idx]))
                pi = jnp.asarray(np.stack([flat[i][2] for i in idx]))
                z = jnp.asarray(np.stack([flat[i][3] for i in idx]))
                ml = jnp.asarray(np.stack([flat[i][4] for i in idx]))
                self.params, self.opt_state, loss, aux = self._update(
                    self.params, self.opt_state, obs, legal, pi, z, ml
                )
            metrics = {
                "total_loss": float(loss),
                "policy_loss": float(aux[0]),
                "value_loss": float(aux[1]),
                "moves_left_loss": float(aux[2]),
            }
        self._episode_reward_window += first_mover_results
        self._episode_reward_window = self._episode_reward_window[-100:]
        metrics["replay_positions"] = len(flat)
        # Draw rate is the convergence signal on solved games (perfect
        # tic-tac-toe play is all draws).
        metrics["draw_rate"] = float(np.mean([r == 0.0 for r in first_mover_results]))
        return metrics

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window))
            if self._episode_reward_window
            else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def compute_single_action(self, obs=None, explore: bool = False, num_sims: Optional[int] = None):
        """Best move for the env's CURRENT position by fresh search (greedy;
        the board protocol is stateful, so obs is taken from the env)."""
        mcts = self._mcts()
        if num_sims:
            mcts.num_sims = num_sims
        pi, _ = mcts.search(temperature=1e-7)
        return int(pi.argmax())

    def save_checkpoint(self):
        import jax

        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "weights": jax.tree_util.tree_map(np.asarray, self.params),
            "timesteps": self._timesteps_total,
        })

    def load_checkpoint(self, checkpoint) -> None:
        import jax
        import jax.numpy as jnp

        data = checkpoint.to_dict()
        self.params = jax.tree_util.tree_map(jnp.asarray, data["weights"])
        self._timesteps_total = data.get("timesteps", 0)

    def cleanup(self) -> None:
        env = getattr(self, "env", None)
        if env is not None:
            try:
                env.close()
            except Exception:
                pass
            self.env = None
        eval_ws = getattr(self, "_eval_workers", None)
        if eval_ws is not None:
            eval_ws.stop()
            self._eval_workers = None
