from ray_tpu.rllib.algorithms.leela_chess_zero.leela_chess_zero import (  # noqa: F401
    LeelaChessZero,
    LeelaChessZeroConfig,
)
