"""DT — Decision Transformer (offline RL as sequence modeling).

Reference: rllib/algorithms/dt/ (Chen et al. 2021): trajectories become
sequences of (return-to-go, state, action) token triples; a causal
transformer is trained to predict the action at each state token, and at
evaluation time acting is conditional generation — prompt with the TARGET
return and the model produces the behavior that achieves it.

TPU-native: the attention inside each block is the Pallas flash kernel
(ops/attention.py) when shapes are tileable, so the same hot op backs the
flagship LM and offline RL.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import ACTIONS, DONES, OBS, REWARDS


def _init_linear(key, n_in, n_out, scale=None):
    import jax

    scale = scale if scale is not None else np.sqrt(2.0 / n_in)
    return {
        "w": jax.random.normal(key, (n_in, n_out)) * scale,
        "b": np.zeros((n_out,), np.float32),
    }


def _linear(p, x):
    return x @ p["w"] + p["b"]


def _layernorm(x, eps=1e-5):
    import jax.numpy as jnp

    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps)


def init_dt_params(key, obs_dim, n_actions, d, n_layers, n_heads, max_len):
    import jax

    keys = jax.random.split(key, 6 + 4 * n_layers)
    params = {
        "emb_rtg": _init_linear(keys[0], 1, d),
        "emb_obs": _init_linear(keys[1], obs_dim, d),
        "emb_act": _init_linear(keys[2], n_actions, d),
        "emb_t": jax.random.normal(keys[3], (max_len, d)) * 0.02,
        "head": _init_linear(keys[4], d, n_actions, scale=0.01),
        "blocks": [],
    }
    for i in range(n_layers):
        k = keys[5 + 4 * i : 9 + 4 * i]
        params["blocks"].append({
            "qkv": _init_linear(k[0], d, 3 * d),
            "proj": _init_linear(k[1], d, d),
            "ff1": _init_linear(k[2], d, 4 * d),
            "ff2": _init_linear(k[3], 4 * d, d),
        })
    return params


def dt_forward(params, rtg, obs, act_onehot, timesteps, n_heads):
    """rtg [B,K,1], obs [B,K,obs_dim], act_onehot [B,K,n_actions],
    timesteps [B,K] int -> action logits [B,K,n_actions] (per state token)."""
    import jax.numpy as jnp

    from ray_tpu.ops.attention import flash_attention

    B, K = timesteps.shape
    pos = params["emb_t"][timesteps]                      # [B,K,d]
    tok_r = _linear(params["emb_rtg"], rtg) + pos
    tok_s = _linear(params["emb_obs"], obs) + pos
    tok_a = _linear(params["emb_act"], act_onehot) + pos
    # Interleave (r_t, s_t, a_t): [B, 3K, d]
    x = jnp.stack([tok_r, tok_s, tok_a], axis=2).reshape(B, 3 * K, -1)
    d = x.shape[-1]
    dh = d // n_heads
    for blk in params["blocks"]:
        h = _layernorm(x)
        qkv = _linear(blk["qkv"], h).reshape(B, 3 * K, 3, n_heads, dh)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]   # [B,3K,H,dh]
        o = flash_attention(q, k, v, causal=True)
        x = x + _linear(blk["proj"], o.reshape(B, 3 * K, d))
        h = _layernorm(x)
        x = x + _linear(blk["ff2"], jnp.maximum(_linear(blk["ff1"], h), 0.0))
    x = _layernorm(x)
    state_tokens = x.reshape(B, K, 3, d)[:, :, 1]          # predict action FROM s_t
    return _linear(params["head"], state_tokens)           # [B,K,n_actions]


class DTConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DT)
        self.lr = 1e-3
        self.train_batch_size = 64
        self.context_length = 20
        self.embed_dim = 64
        self.n_layers = 2
        self.n_heads = 2
        self.max_ep_len = 1000
        self.target_return = None  # default: best dataset return
        self.updates_per_iter = 100
        self.eval_episodes = 5
        self.offline_input: str | None = None  # JsonReader path

    def training(self, *, context_length=None, embed_dim=None, n_layers=None,
                 n_heads=None, target_return=None, updates_per_iter=None,
                 eval_episodes=None, max_ep_len=None, **kwargs) -> "DTConfig":
        super().training(**kwargs)
        for name, val in (
            ("context_length", context_length), ("embed_dim", embed_dim),
            ("n_layers", n_layers), ("n_heads", n_heads),
            ("target_return", target_return), ("updates_per_iter", updates_per_iter),
            ("eval_episodes", eval_episodes), ("max_ep_len", max_ep_len),
        ):
            if val is not None:
                setattr(self, name, val)
        return self

    def offline_data(self, input_: str) -> "DTConfig":
        self.offline_input = input_
        return self


class DT(Algorithm):
    @classmethod
    def get_default_config(cls) -> DTConfig:
        return DTConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        cfg: DTConfig = self._algo_config
        env = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        self.env = env
        self.obs_dim = int(np.prod(env.observation_space.shape))
        self.n_actions = int(env.action_space.n)
        assert cfg.offline_input, "DT is offline: configure .offline_data(path)"

        from ray_tpu.rllib.offline import JsonReader

        reader = JsonReader(cfg.offline_input, gamma=1.0)
        batch = reader.next()  # full dataset
        self.trajectories = self._segment(batch)
        assert self.trajectories, "offline dataset contains no complete episode"
        # Length-weighted trajectory sampling probabilities (reference does
        # the same); fixed dataset -> computed once.
        lens = np.array([len(t["actions"]) for t in self.trajectories], np.float64)
        self._traj_probs = lens / lens.sum()
        returns = [t["rtg"][0] for t in self.trajectories]
        self.target_return = float(cfg.target_return or max(returns))

        key = jax.random.PRNGKey(cfg.seed)
        self.params = init_dt_params(
            key, self.obs_dim, self.n_actions, cfg.embed_dim, cfg.n_layers,
            cfg.n_heads, cfg.max_ep_len + cfg.context_length,
        )
        self.tx = optax.adamw(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        n_heads, K = cfg.n_heads, cfg.context_length

        def loss_fn(params, rtg, obs, act_oh, ts, actions, mask):
            logits = dt_forward(params, rtg, obs, act_oh, ts, n_heads)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, actions[..., None], axis=-1)[..., 0]
            return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        def train_step(params, opt_state, *args):
            loss, grads = jax.value_and_grad(loss_fn)(params, *args)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss

        self._train_step = jax.jit(train_step)
        self._logits_fn = jax.jit(
            lambda p, rtg, obs, act, ts: dt_forward(p, rtg, obs, act, ts, n_heads)
        )
        self._rng = np.random.default_rng(cfg.seed)
        self._timesteps_total = 0
        self._episode_reward_window: list = []

    def _segment(self, batch) -> list[dict]:
        """Split the flat offline batch into episodes with returns-to-go."""
        obs = np.asarray(batch[OBS], np.float32).reshape(len(batch[OBS]), -1)
        acts = np.asarray(batch[ACTIONS]).astype(np.int64).reshape(-1)
        rews = np.asarray(batch[REWARDS], np.float32).reshape(-1)
        dones = np.asarray(batch[DONES], np.float32).reshape(-1)
        out, start = [], 0
        for i in range(len(dones)):
            if dones[i] > 0:
                r = rews[start : i + 1]
                rtg = np.cumsum(r[::-1])[::-1].astype(np.float32)
                out.append({
                    "obs": obs[start : i + 1],
                    "actions": acts[start : i + 1],
                    "rtg": rtg,
                })
                start = i + 1
        return out

    def _sample_windows(self, n: int, K: int):
        obs = np.zeros((n, K, self.obs_dim), np.float32)
        rtg = np.zeros((n, K, 1), np.float32)
        act = np.zeros((n, K), np.int64)
        act_oh = np.zeros((n, K, self.n_actions), np.float32)
        ts = np.zeros((n, K), np.int32)
        mask = np.zeros((n, K), np.float32)
        for i in range(n):
            t = self.trajectories[self._rng.choice(len(self.trajectories), p=self._traj_probs)]
            L = len(t["actions"])
            end = self._rng.integers(1, L + 1)
            startw = max(0, end - K)
            w = end - startw
            obs[i, :w] = t["obs"][startw:end]
            rtg[i, :w, 0] = t["rtg"][startw:end]
            act[i, :w] = t["actions"][startw:end]
            act_oh[i, np.arange(w), t["actions"][startw:end]] = 1.0
            # Action inputs are PREVIOUS actions at prediction time; the
            # causal mask already hides a_t from s_t's prediction (a_t comes
            # after s_t in the token order), so feeding the true actions is safe.
            ts[i, :w] = np.arange(startw, end)
            mask[i, :w] = 1.0
        return rtg, obs, act_oh, ts, act, mask

    def training_step(self) -> dict:
        import jax.numpy as jnp

        cfg: DTConfig = self._algo_config
        loss = None
        for _ in range(cfg.updates_per_iter):
            parts = self._sample_windows(cfg.train_batch_size, cfg.context_length)
            jparts = [jnp.asarray(p) for p in parts]
            self.params, self.opt_state, loss = self._train_step(
                self.params, self.opt_state, *jparts
            )
            self._timesteps_total += cfg.train_batch_size * cfg.context_length
        rewards = [self._eval_episode() for _ in range(cfg.eval_episodes)]
        self._episode_reward_window = (self._episode_reward_window + rewards)[-100:]
        return {"loss": float(loss) if loss is not None else float("nan")}

    def _eval_episode(self) -> float:
        import jax.numpy as jnp

        cfg: DTConfig = self._algo_config
        K = cfg.context_length
        obs, _ = self.env.reset(seed=int(self._rng.integers(1 << 31)))
        rtg_hist = [self.target_return]
        obs_hist = [np.asarray(obs, np.float32).ravel()]
        act_hist: list = []
        total, t = 0.0, 0
        while t < cfg.max_ep_len:
            w = min(len(obs_hist), K)
            rtg = np.zeros((1, K, 1), np.float32)
            ob = np.zeros((1, K, self.obs_dim), np.float32)
            ah = np.zeros((1, K, self.n_actions), np.float32)
            ts = np.zeros((1, K), np.int32)
            rtg[0, :w, 0] = rtg_hist[-w:]
            ob[0, :w] = obs_hist[-w:]
            # Window covers timesteps t-w+1..t; position j holds the action
            # TAKEN AT that position's timestep (matching _sample_windows).
            # The current step's action (pos w-1) hasn't happened yet — its
            # token stays zero and is causally after the s_t query anyway.
            for j, a in enumerate(act_hist[t - w + 1 : t]):
                ah[0, j, a] = 1.0
            ts[0, :w] = np.arange(max(0, t - w + 1), t + 1)
            logits = np.asarray(self._logits_fn(
                self.params, jnp.asarray(rtg), jnp.asarray(ob), jnp.asarray(ah), jnp.asarray(ts)
            ))
            a = int(logits[0, w - 1].argmax())
            obs, r, term, trunc, _ = self.env.step(a)
            total += float(r)
            t += 1
            act_hist.append(a)
            obs_hist.append(np.asarray(obs, np.float32).ravel())
            rtg_hist.append(rtg_hist[-1] - float(r))
            if term or trunc:
                break
        return total

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window))
            if self._episode_reward_window
            else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def save_checkpoint(self):
        import jax

        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "params": jax.tree_util.tree_map(np.asarray, self.params),
            "opt_state": jax.tree_util.tree_map(np.asarray, self.opt_state),
            "target_return": self.target_return,
            "timesteps": self._timesteps_total,
        })

    def load_checkpoint(self, checkpoint) -> None:
        import jax
        import jax.numpy as jnp

        data = checkpoint.to_dict()
        self.params = jax.tree_util.tree_map(jnp.asarray, data["params"])
        if "opt_state" in data:
            self.opt_state = jax.tree_util.tree_map(jnp.asarray, data["opt_state"])
        self.target_return = data.get("target_return", self.target_return)
        self._timesteps_total = data.get("timesteps", 0)

    def cleanup(self) -> None:
        try:
            self.env.close()
        except Exception:
            pass

    def compute_single_action(self, obs, explore: bool = False):
        raise NotImplementedError("DT acts with return conditioning; use evaluation")
