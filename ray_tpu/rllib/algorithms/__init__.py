from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig  # noqa: F401
