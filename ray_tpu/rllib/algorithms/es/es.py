"""ES — evolution strategies (OpenAI-ES style).

Reference: rllib/algorithms/es/ (es.py, es_tf_policy.py, optimizers.py,
utils.py): black-box optimization — worker actors evaluate antithetic
parameter perturbations for whole episodes; the driver combines
centered-rank-weighted noise into a gradient estimate. The shared-noise-table
trick of the reference becomes shared *seeds*: workers regenerate each
perturbation from its integer seed, so only (seed, return) pairs cross the
object store, never parameter-sized noise.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.rl_module import RLModuleSpec


def _flatten(tree):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    flat = np.concatenate([np.asarray(l).ravel() for l in leaves])
    shapes = [np.asarray(l).shape for l in leaves]
    return flat.astype(np.float32), treedef, shapes


def _unflatten(flat, treedef, shapes):
    import jax

    leaves, off = [], 0
    for s in shapes:
        n = int(np.prod(s)) if s else 1
        leaves.append(np.asarray(flat[off : off + n]).reshape(s))
        off += n
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _centered_ranks(x: np.ndarray) -> np.ndarray:
    """Map returns to centered ranks in [-0.5, 0.5] (reference: utils.py)."""
    ranks = np.empty(len(x), dtype=np.float32)
    ranks[x.argsort()] = np.arange(len(x), dtype=np.float32)
    return ranks / (len(x) - 1) - 0.5


class _ESWorker:
    """Evaluates perturbed policies for whole episodes on CPU."""

    def __init__(self, env_spec, spec: RLModuleSpec, env_config, shapes, seed):
        import jax

        jax.config.update("jax_platforms", "cpu")
        from ray_tpu.rllib.core import rl_module
        from ray_tpu.rllib.env.vector_env import EnvContext, _make_env

        self.env = _make_env(env_spec, EnvContext(env_config or {}, 0, 0))
        self.spec = spec
        self.shapes = shapes
        # Rebuild the treedef worker-side from a params template (treedefs
        # don't pickle portably across processes).
        params = rl_module.init_params(jax.random.PRNGKey(0), spec)
        _, self.treedef, _ = _flatten(params)
        self._forward = jax.jit(lambda p, o: rl_module.forward(p, o, spec)[0])
        self._np_rng = np.random.default_rng(seed)

    def _episode_return(self, flat, episode_horizon: int) -> tuple:
        import jax.numpy as jnp

        params = _unflatten(flat, self.treedef, self.shapes)
        obs, _ = self.env.reset(seed=int(self._np_rng.integers(1 << 31)))
        total, steps = 0.0, 0
        while steps < episode_horizon:
            out = np.asarray(self._forward(params, jnp.asarray(np.asarray(obs, np.float32).reshape(1, -1))))[0]
            action = int(out.argmax()) if self.spec.discrete else np.tanh(out)
            obs, r, terminated, truncated, _ = self.env.step(action)
            total += float(r)
            steps += 1
            if terminated or truncated:
                break
        return total, steps

    def rollout(self, flat_params: np.ndarray, seeds: list, sigma: float, episode_horizon: int):
        """Antithetic evaluation: for each seed return (R+, R-, env steps)."""
        out = []
        for s in seeds:
            noise = np.random.default_rng(int(s)).standard_normal(len(flat_params)).astype(np.float32)
            r_pos, n_pos = self._episode_return(flat_params + sigma * noise, episode_horizon)
            r_neg, n_neg = self._episode_return(flat_params - sigma * noise, episode_horizon)
            out.append((r_pos, r_neg, n_pos + n_neg))
        return out

    def evaluate(self, flat_params: np.ndarray, episodes: int, episode_horizon: int) -> list:
        """Returns (reward, env steps) per episode."""
        return [self._episode_return(flat_params, episode_horizon) for _ in range(episodes)]

    def stop(self):
        try:
            self.env.close()
        except Exception:
            pass
        return True


class ESConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ES)
        self.num_rollout_workers = 4
        self.episodes_per_batch = 40  # perturbation pairs per iteration
        self.noise_stdev = 0.02
        self.stepsize = 0.01
        self.l2_coeff = 0.005
        self.episode_horizon = 1000
        self.eval_episodes = 5

    def training(self, *, episodes_per_batch=None, noise_stdev=None, stepsize=None,
                 l2_coeff=None, episode_horizon=None, eval_episodes=None, **kwargs) -> "ESConfig":
        super().training(**kwargs)
        for name, val in (
            ("episodes_per_batch", episodes_per_batch),
            ("noise_stdev", noise_stdev),
            ("stepsize", stepsize),
            ("l2_coeff", l2_coeff),
            ("episode_horizon", episode_horizon),
            ("eval_episodes", eval_episodes),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class ES(Algorithm):
    # Subclasses (ARS) substitute their own worker actor class.
    _worker_cls = _ESWorker

    @classmethod
    def get_default_config(cls) -> ESConfig:
        return ESConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax

        # Re-setup must not orphan the previous worker gang (same guard as
        # base Algorithm.setup — leaked actors hold CPU reservations).
        self.cleanup()
        cfg: ESConfig = self._algo_config
        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        from ray_tpu.rllib.models import ModelCatalog

        self.module_spec = ModelCatalog.get_model_spec(
            probe.observation_space, probe.action_space, cfg.model_config()
        )
        probe.close()
        from ray_tpu.rllib.core import rl_module

        params = rl_module.init_params(jax.random.PRNGKey(cfg.seed), self.module_spec)
        self.flat, self._treedef, self._shapes = _flatten(params)
        # Adam state for the ES gradient estimate (reference: optimizers.py).
        self._m = np.zeros_like(self.flat)
        self._v = np.zeros_like(self.flat)
        self._t = 0
        self._np_rng = np.random.default_rng(cfg.seed)
        make = ray_tpu.remote(num_cpus=1)(self._worker_cls).remote
        self._workers = [
            make(cfg.env, self.module_spec, cfg.env_config, self._shapes, cfg.seed + i)
            for i in range(max(cfg.num_rollout_workers, 1))
        ]
        self._timesteps_total = 0
        self._episode_reward_window: list = []

    def training_step(self) -> dict:
        cfg: ESConfig = self._algo_config
        n_pairs = cfg.episodes_per_batch
        seeds = self._np_rng.integers(0, 1 << 31, n_pairs)
        per_worker = np.array_split(seeds, len(self._workers))
        refs = [
            w.rollout.remote(self.flat, list(map(int, chunk)), cfg.noise_stdev, cfg.episode_horizon)
            for w, chunk in zip(self._workers, per_worker)
            if len(chunk)
        ]
        pairs: list = []
        used_seeds: list = []
        steps_this_iter = 0
        for ref, chunk in zip(refs, [c for c in per_worker if len(c)]):
            try:
                res = ray_tpu.get(ref, timeout=600)
                pairs += [(rp, rn) for rp, rn, _ in res]
                steps_this_iter += sum(n for _, _, n in res)
                used_seeds += list(chunk)
            except Exception:
                pass  # lost worker: proceed with the survivors' episodes
        if not pairs:
            return {"es_update_skipped": 1.0}
        returns = np.asarray(pairs, np.float32)  # [n, 2] = (R+, R-)
        # Centered-rank transform over ALL evaluations, antithetic pairing.
        ranks = _centered_ranks(returns.ravel()).reshape(returns.shape)
        weights = ranks[:, 0] - ranks[:, 1]
        grad = np.zeros_like(self.flat)
        for w, s in zip(weights, used_seeds):
            noise = np.random.default_rng(int(s)).standard_normal(len(self.flat)).astype(np.float32)
            grad += w * noise
        grad /= len(weights) * cfg.noise_stdev
        grad -= cfg.l2_coeff * self.flat  # weight decay
        # Adam ascent.
        self._t += 1
        b1, b2, eps = 0.9, 0.999, 1e-8
        self._m = b1 * self._m + (1 - b1) * grad
        self._v = b2 * self._v + (1 - b2) * grad * grad
        mhat = self._m / (1 - b1**self._t)
        vhat = self._v / (1 - b2**self._t)
        self.flat = self.flat + cfg.stepsize * mhat / (np.sqrt(vhat) + eps)
        # Evaluate the unperturbed policy for the reported reward.
        eval_refs = [self._workers[0].evaluate.remote(self.flat, cfg.eval_episodes, cfg.episode_horizon)]
        try:
            evals = ray_tpu.get(eval_refs[0], timeout=600)
        except Exception:
            evals = []
        rewards = [r for r, _ in evals]
        steps_this_iter += sum(n for _, n in evals)
        # Real env-step counts from the workers (an estimate here would leak
        # into stop criteria like stop_timesteps).
        self._timesteps_total += steps_this_iter
        self._episode_reward_window += rewards
        self._episode_reward_window = self._episode_reward_window[-100:]
        return {
            "episode_reward_mean": float(np.mean(rewards)) if rewards else float("nan"),
            "grad_norm": float(np.linalg.norm(grad)),
            "perturbations_this_iter": float(len(weights) * 2),
        }

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result.setdefault(
            "episode_reward_mean",
            float(np.mean(self._episode_reward_window)) if self._episode_reward_window else float("nan"),
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def compute_single_action(self, obs, explore: bool = False):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.core import rl_module

        params = _unflatten(self.flat, self._treedef, self._shapes)
        out = np.asarray(
            rl_module.forward(
                jax.tree_util.tree_map(jnp.asarray, params),
                jnp.asarray(np.asarray(obs, np.float32).reshape(1, -1)),
                self.module_spec,
            )[0]
        )[0]
        return int(out.argmax()) if self.module_spec.discrete else np.tanh(out)

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        # Adam moments and the seed stream are training state: without them a
        # pause/resume (routine under sync HyperBand) spikes the step size
        # (fresh bias correction) and replays the same noise directions.
        return Checkpoint.from_dict({
            "flat": self.flat,
            "timesteps": self._timesteps_total,
            "adam_m": np.asarray(self._m),
            "adam_v": np.asarray(self._v),
            "adam_t": self._t,
            "np_rng_state": self._np_rng.bit_generator.state,
        })

    def load_checkpoint(self, checkpoint) -> None:
        data = checkpoint.to_dict()
        self.flat = np.asarray(data["flat"], np.float32)
        self._timesteps_total = data.get("timesteps", 0)
        if "adam_m" in data:
            self._m = np.asarray(data["adam_m"], np.float32)
            self._v = np.asarray(data["adam_v"], np.float32)
            self._t = int(data["adam_t"])
        if data.get("np_rng_state") is not None:
            self._np_rng = np.random.default_rng()
            self._np_rng.bit_generator.state = data["np_rng_state"]

    def cleanup(self) -> None:
        for w in getattr(self, "_workers", []):
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self._workers = []
