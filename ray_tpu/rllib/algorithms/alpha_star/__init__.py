from ray_tpu.rllib.algorithms.alpha_star.alpha_star import AlphaStar, AlphaStarConfig  # noqa: F401
