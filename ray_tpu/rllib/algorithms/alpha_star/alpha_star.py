"""AlphaStar — league-based self-play training.

Reference: rllib/algorithms/alpha_star/ (alpha_star.py, league_builder.py;
Vinyals et al. 2019): a LEAGUE of policies trains concurrently —
* the MAIN agent, trained with prioritized fictitious self-play (PFSP)
  against frozen league snapshots (hard opponents weighted up) mixed with
  self-play against its live self;
* MAIN EXPLOITERS, trained only against the live main agent to find its
  weaknesses;
* LEAGUE EXPLOITERS, trained PFSP against the whole league;
and the main agent is periodically FROZEN into the league as a new
snapshot (league_builder.py AlphaStarLeagueBuilder: the same three slot
kinds, snapshot-on-winrate). Win-rates drive both matchmaking and
snapshotting.

This is the league ARCHITECTURE on simultaneous-move zero-sum envs
(env/two_player.py protocol); the reference binds the same machinery to
StarCraft II. Policy updates are jitted A2C steps on the learner side of
each match; opponents act frozen. Scripted opponents can be seeded into
the league (tests anchor on exploiting a biased rock-paper-scissors
player).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    ADVANTAGES,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    VALUE_TARGETS,
    VF_PREDS,
    SampleBatch,
    compute_gae,
)


class _LeagueMember:
    """One frozen league entry: a parameter snapshot or a scripted actor."""

    def __init__(self, name: str, params=None, scripted: Optional[Callable] = None):
        self.name = name
        self.params = params
        self.scripted = scripted
        # Per-learner win-rate bookkeeping: learner name -> [wins, games].
        self.results: Dict[str, List[float]] = {}

    def record(self, learner: str, win: float):
        w, g = self.results.get(learner, [0.0, 0.0])
        self.results[learner] = [w + win, g + 1.0]

    def winrate_of(self, learner: str) -> float:
        w, g = self.results.get(learner, [0.0, 0.0])
        return w / g if g else 0.5


class AlphaStarConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or AlphaStar)
        self.lr = 5e-3
        self.entropy_coeff = 0.01
        self.vf_loss_coeff = 0.5
        self.grad_clip = 1.0
        self.num_main_exploiters = 1
        self.num_league_exploiters = 1
        self.episodes_per_slot = 8
        # Main-agent matchmaking mix (reference league_builder defaults:
        # 35% self-play / PFSP for the rest; we fold old-main PFSP in).
        self.self_play_fraction = 0.35
        self.snapshot_interval = 10       # iterations between league freezes
        self.snapshot_min_winrate = 0.6   # freeze only a main that's winning
        self.pfsp_power = 2.0             # (1 - winrate)^power weighting
        # Scripted league seeds: list of (name, callable(obs)->action).
        self.scripted_league_seeds: list = []

    def training(self, *, entropy_coeff=None, vf_loss_coeff=None,
                 num_main_exploiters=None, num_league_exploiters=None,
                 episodes_per_slot=None, self_play_fraction=None,
                 snapshot_interval=None, snapshot_min_winrate=None,
                 pfsp_power=None, scripted_league_seeds=None, **kwargs) -> "AlphaStarConfig":
        super().training(**kwargs)
        for name, val in (
            ("entropy_coeff", entropy_coeff),
            ("vf_loss_coeff", vf_loss_coeff),
            ("num_main_exploiters", num_main_exploiters),
            ("num_league_exploiters", num_league_exploiters),
            ("episodes_per_slot", episodes_per_slot),
            ("self_play_fraction", self_play_fraction),
            ("snapshot_interval", snapshot_interval),
            ("snapshot_min_winrate", snapshot_min_winrate),
            ("pfsp_power", pfsp_power),
            ("scripted_league_seeds", scripted_league_seeds),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class AlphaStar(Algorithm):
    @classmethod
    def get_default_config(cls) -> AlphaStarConfig:
        return AlphaStarConfig(cls)

    def setup(self, config: dict) -> None:
        import jax
        import optax

        self.cleanup()
        cfg: AlphaStarConfig = self._algo_config
        self.env = cfg.env(dict(cfg.env_config)) if callable(cfg.env) else cfg.env
        assert hasattr(self.env, "step") and hasattr(self.env, "reset"), "two-player env required"
        import gymnasium as gym

        assert isinstance(self.env.action_space, gym.spaces.Discrete), (
            "AlphaStar league (this build) supports discrete simultaneous-move envs"
        )
        from ray_tpu.rllib.models import ModelCatalog

        self.module_spec = ModelCatalog.get_model_spec(
            self.env.observation_space, self.env.action_space, cfg.model_config()
        )
        from ray_tpu.rllib.core import rl_module

        # Learning slots: main + exploiters, each with its own optimizer.
        self._tx = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip or 1e9), optax.adam(cfg.lr)
        )
        self.slots: Dict[str, dict] = {}
        names = (
            ["main"]
            + [f"main_exploiter_{i}" for i in range(cfg.num_main_exploiters)]
            + [f"league_exploiter_{i}" for i in range(cfg.num_league_exploiters)]
        )
        for i, name in enumerate(names):
            params = rl_module.init_params(jax.random.PRNGKey(cfg.seed + i), self.module_spec)
            self.slots[name] = {"params": params, "opt": self._tx.init(params)}
        # League of frozen members; scripted seeds join immediately.
        self.league: List[_LeagueMember] = [
            _LeagueMember(name, scripted=fn) for name, fn in cfg.scripted_league_seeds
        ]
        self._snapshots = 0
        spec = self.module_spec

        def a2c_step(params, opt_state, batch, cfg_):
            def loss_fn(p):
                logp, entropy, value = rl_module.action_logp_and_entropy(
                    p, batch[OBS], batch[ACTIONS], spec
                )
                adv = batch[ADVANTAGES]
                adv = (adv - adv.mean()) / (adv.std() + 1e-8)
                pl = -(logp * adv).mean()
                vl = ((value - batch[VALUE_TARGETS]) ** 2).mean()
                ent = entropy.mean()
                return pl + cfg_["vf"] * vl - cfg_["ent"] * ent, (pl, vl, ent)

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss

        self._a2c_step = jax.jit(a2c_step)
        self._sample_fn = jax.jit(
            lambda p, o, k: rl_module.sample_actions(p, o, k, spec, True)
        )
        self._rng = jax.random.PRNGKey(cfg.seed + 99)
        self._np_rng = np.random.default_rng(cfg.seed)
        self._timesteps_total = 0
        self._episode_reward_window: list = []
        self._iter = 0

    # -- matchmaking (reference: league_builder PFSP) ----------------------
    def _pfsp_pick(self, learner: str, candidates: List[_LeagueMember]) -> _LeagueMember:
        cfg: AlphaStarConfig = self._algo_config
        if not candidates:
            return None
        # Hard opponents (low learner win-rate) weighted up.
        w = np.array([
            (1.0 - m.winrate_of(learner)) ** cfg.pfsp_power + 1e-3 for m in candidates
        ])
        return candidates[self._np_rng.choice(len(candidates), p=w / w.sum())]

    def _choose_opponent(self, slot_name: str):
        """Returns (kind, member_or_params): per-slot matchmaking rules."""
        cfg: AlphaStarConfig = self._algo_config
        if slot_name.startswith("main_exploiter"):
            return "live_main", None
        if slot_name.startswith("league_exploiter"):
            m = self._pfsp_pick(slot_name, self.league)
            return ("league", m) if m is not None else ("live_main", None)
        # Main agent: self-play fraction vs live self, else PFSP league.
        if not self.league or self._np_rng.random() < cfg.self_play_fraction:
            return "self", None
        return "league", self._pfsp_pick(slot_name, self.league)

    # -- match execution ---------------------------------------------------
    def _opponent_actor(self, kind, member):
        import jax.numpy as jnp
        import jax

        if kind in ("self", "live_main"):
            params = self.slots["main"]["params"]
        elif member.scripted is not None:
            fn = member.scripted
            return lambda obs: int(fn(obs))
        else:
            params = member.params

        def act(obs):
            self._rng, key = jax.random.split(self._rng)
            a, _, _ = self._sample_fn(params, jnp.asarray(obs, jnp.float32)[None], key)
            return int(np.asarray(a)[0])

        return act

    def _play_episode(self, learner_params, opponent_act):
        """One episode; returns (learner fragment cols, learner return)."""
        import jax
        import jax.numpy as jnp

        obs_a, obs_b = self.env.reset()
        cols = {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, LOGPS, VF_PREDS)}
        total = 0.0
        while True:
            o = np.asarray(obs_a, np.float32)
            self._rng, key = jax.random.split(self._rng)
            a, logp, v = self._sample_fn(learner_params, jnp.asarray(o)[None], key)
            act_a = int(np.asarray(a)[0])
            act_b = opponent_act(np.asarray(obs_b, np.float32))
            obs_a, obs_b, r_a, _, done = self.env.step(act_a, act_b)
            total += r_a
            cols[OBS].append(o)
            cols[ACTIONS].append(np.int32(act_a))
            cols[REWARDS].append(np.float32(r_a))
            cols[DONES].append(np.float32(done))
            cols[LOGPS].append(np.asarray(logp)[0])
            cols[VF_PREDS].append(np.asarray(v)[0])
            self._timesteps_total += 1
            if done:
                break
        frag = SampleBatch({k: np.stack(v) for k, v in cols.items()})
        cfg = self._algo_config
        frag = compute_gae(frag, 0.0, cfg.gamma, cfg.lambda_)
        return frag, total

    def training_step(self) -> dict:
        import jax.numpy as jnp

        cfg: AlphaStarConfig = self._algo_config
        self._iter += 1
        loss_cfg = {"vf": cfg.vf_loss_coeff, "ent": cfg.entropy_coeff}
        metrics: dict = {}
        for name, slot in self.slots.items():
            frags, wins, games = [], 0.0, 0
            for _ in range(cfg.episodes_per_slot):
                kind, member = self._choose_opponent(name)
                opponent = self._opponent_actor(kind, member)
                frag, ret = self._play_episode(slot["params"], opponent)
                frags.append(frag)
                win = 1.0 if ret > 0 else (0.5 if ret == 0 else 0.0)
                wins += win
                games += 1
                if kind == "league" and member is not None:
                    member.record(name, win)
                if name == "main":
                    self._episode_reward_window.append(ret)
            batch = SampleBatch.concat_samples(frags)
            jb = {k: jnp.asarray(v) for k, v in batch.items()}
            slot["params"], slot["opt"], loss = self._a2c_step(
                slot["params"], slot["opt"], jb, loss_cfg
            )
            metrics[f"{name}/winrate"] = wins / max(games, 1)
            metrics[f"{name}/loss"] = float(loss)
        # League building: freeze a winning main (reference: snapshot when
        # the main agent's league win-rate clears the bar).
        if (
            self._iter % cfg.snapshot_interval == 0
            and metrics.get("main/winrate", 0.0) >= cfg.snapshot_min_winrate
        ):
            self._freeze("main")
        self._episode_reward_window = self._episode_reward_window[-100:]
        metrics["league_size"] = len(self.league)
        return metrics

    def _freeze(self, slot_name: str):
        import jax

        self._snapshots += 1
        self.league.append(
            _LeagueMember(
                f"{slot_name}_snap_{self._snapshots}",
                params=jax.tree_util.tree_map(lambda x: x, self.slots[slot_name]["params"]),
            )
        )

    def winrate_vs(self, member_name: str, learner: str = "main",
                   episodes: int = 20) -> float:
        """Evaluation probe: fresh matches of `learner` against a named
        league member (bypasses the PFSP bookkeeping)."""
        member = next(m for m in self.league if m.name == member_name)
        opponent = self._opponent_actor("league", member)
        wins = 0.0
        for _ in range(episodes):
            _, ret = self._play_episode(self.slots[learner]["params"], opponent)
            wins += 1.0 if ret > 0 else (0.5 if ret == 0 else 0.0)
        return wins / episodes

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window))
            if self._episode_reward_window
            else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def compute_single_action(self, obs, explore: bool = False):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.core import rl_module

        actions, _, _ = rl_module.sample_actions(
            self.slots["main"]["params"],
            jnp.asarray(np.asarray(obs, np.float32))[None],
            jax.random.PRNGKey(0), self.module_spec, explore,
        )
        return int(np.asarray(actions)[0])

    def save_checkpoint(self):
        import jax

        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "slots": {
                n: jax.tree_util.tree_map(np.asarray, s["params"])
                for n, s in self.slots.items()
            },
            "league": [
                (m.name, jax.tree_util.tree_map(np.asarray, m.params))
                for m in self.league
                if m.params is not None
            ],
            "timesteps": self._timesteps_total,
        })

    def load_checkpoint(self, checkpoint) -> None:
        import jax
        import jax.numpy as jnp

        data = checkpoint.to_dict()
        for n, w in data["slots"].items():
            if n in self.slots:
                self.slots[n]["params"] = jax.tree_util.tree_map(jnp.asarray, w)
        # Scripted seeds persist via config; param snapshots reload here.
        self.league = [m for m in self.league if m.scripted is not None] + [
            _LeagueMember(name, params=jax.tree_util.tree_map(jnp.asarray, w))
            for name, w in data.get("league", [])
        ]
        self._timesteps_total = data.get("timesteps", 0)

    def cleanup(self) -> None:
        env = getattr(self, "env", None)
        if env is not None:
            try:
                env.close()
            except Exception:
                pass
            self.env = None
        eval_ws = getattr(self, "_eval_workers", None)
        if eval_ws is not None:
            eval_ws.stop()
            self._eval_workers = None
