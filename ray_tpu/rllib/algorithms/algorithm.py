"""Algorithm — the RLlib trainer base, a Tune Trainable.

Reference: rllib/algorithms/algorithm.py:149 (Algorithm extends Trainable,
setup :510 builds WorkerSet + LearnerGroup, training_step :1347) and
algorithm_config.py (fluent AlgorithmConfig builder).
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Type

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.evaluation.rollout_worker import WorkerSet
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Fluent config builder (reference: algorithm_config.py)."""

    def __init__(self, algo_class: Optional[Type["Algorithm"]] = None):
        self.algo_class = algo_class
        self.env = None
        self.env_config: dict = {}
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 1
        self.rollout_fragment_length = 200
        self.observation_filter: Optional[str] = None
        # Connector pipelines (reference: .env_runners(env_to_module_connector)
        # / legacy agent+action connectors): lists of stage instances shipped
        # to every rollout AND eval worker.
        self.agent_connectors: Optional[list] = None
        self.action_connectors: Optional[list] = None
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.lr = 5e-5
        self.train_batch_size = 4000
        self.grad_clip: Optional[float] = None
        # Weight-sync transport (Podracer topology, arXiv:2104.06272):
        # "host" ships the params pytree through the object store per
        # worker; "device_broadcast" packs them into ONE device-resident
        # vector and fans the payload to the whole sampler fleet with one
        # group operation (experimental.device_object.broadcast).
        self.weight_sync = "host"
        self.weight_sync_group = "rllib_weights"
        self.weight_sync_backend = "cpu"  # "tpu" on hardware: ICI broadcast seam
        # Gradient-sync transport for multi-learner data parallelism:
        # "host" allreduces each grad leaf through the ring collective;
        # "device_allreduce" packs grads into ONE flat vector and rides the
        # relay-tree allreduce (reduce up the binomial tree, broadcast back
        # down) — same plane the Podracer weight broadcast uses.
        self.grad_sync = "host"
        # Podracer learner mesh: shard the update's batch over every local
        # device (pjit data-parallel cell) instead of single-device jit.
        self.learner_mesh = False
        self.model_hiddens = (64, 64)
        self.model_conv_filters = None  # [(out_ch, kernel, stride), ...] for image obs
        self.seed = 0
        self.num_learners = 0
        self.num_tpus_per_learner = 0.0
        self.explore = True
        # Evaluation (reference: algorithm_config.py:383 .evaluation()):
        # None = never evaluate; N = every N training iterations.
        self.evaluation_interval: Optional[int] = None
        self.evaluation_num_workers = 1
        self.evaluation_duration = 10
        self.evaluation_duration_unit = "episodes"  # or "timesteps"
        # Fault tolerance (reference: algorithm_config.py .fault_tolerance()):
        # dead rollout workers are respawned up to max_worker_restarts times
        # total; with recreate_failed_workers=False the set degrades instead.
        self.recreate_failed_workers = True
        self.max_worker_restarts = 100
        # Reporting (reference: .reporting()):
        self.metrics_num_episodes_for_smoothing = 100
        self.min_time_s_per_iteration: Optional[float] = None
        # Offline data (reference: .offline_data()); consumed by the offline
        # families (MARWIL/BC/CQL/CRR/DT) which override these defaults.
        self.input_ = None
        self.output = None
        self.input_reader_kwargs: dict = {}
        # Callbacks class (reference: .callbacks()).
        self.callbacks_class = None
        self.extra: dict = {}

    # -- fluent sections (reference: .environment/.rollouts/.training) ----
    def environment(self, env=None, *, env_config: Optional[dict] = None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None, num_envs_per_worker: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None,
                 observation_filter: Optional[str] = None,
                 agent_connectors: Optional[list] = None,
                 action_connectors: Optional[list] = None) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if observation_filter is not None:
            self.observation_filter = observation_filter
        if agent_connectors is not None:
            self.agent_connectors = list(agent_connectors)
        if action_connectors is not None:
            self.action_connectors = list(action_connectors)
        return self

    def training(self, *, lr: Optional[float] = None, gamma: Optional[float] = None,
                 train_batch_size: Optional[int] = None, grad_clip: Optional[float] = None,
                 model_hiddens=None, model_conv_filters=None,
                 weight_sync: Optional[str] = None,
                 weight_sync_backend: Optional[str] = None,
                 grad_sync: Optional[str] = None,
                 learner_mesh: Optional[bool] = None, **extra) -> "AlgorithmConfig":
        if lr is not None:
            self.lr = lr
        if gamma is not None:
            self.gamma = gamma
        if train_batch_size is not None:
            self.train_batch_size = train_batch_size
        if grad_clip is not None:
            self.grad_clip = grad_clip
        if weight_sync is not None:
            assert weight_sync in ("host", "device_broadcast"), weight_sync
            self.weight_sync = weight_sync
        if weight_sync_backend is not None:
            self.weight_sync_backend = weight_sync_backend
        if grad_sync is not None:
            assert grad_sync in ("host", "device_allreduce"), grad_sync
            self.grad_sync = grad_sync
        if learner_mesh is not None:
            self.learner_mesh = learner_mesh
        if model_hiddens is not None:
            self.model_hiddens = tuple(model_hiddens)
        if model_conv_filters is not None:
            self.model_conv_filters = tuple(tuple(f) for f in model_conv_filters)
        self.extra.update(extra)
        return self

    def resources(self, *, num_learners: Optional[int] = None, num_tpus_per_learner: Optional[float] = None) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if num_tpus_per_learner is not None:
            self.num_tpus_per_learner = num_tpus_per_learner
        return self

    def evaluation(self, *, evaluation_interval: Optional[int] = None,
                   evaluation_num_workers: Optional[int] = None,
                   evaluation_duration: Optional[int] = None,
                   evaluation_duration_unit: Optional[str] = None) -> "AlgorithmConfig":
        """Dedicated greedy evaluation every ``evaluation_interval`` training
        iterations (reference: algorithm_config.py:383). Eval rollouts use
        explore=False on a separate worker set (or a driver-local env for
        algorithms without the standard rollout stack) so exploration noise
        and training episode stats are never mixed into eval metrics."""
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_num_workers is not None:
            self.evaluation_num_workers = evaluation_num_workers
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        if evaluation_duration_unit is not None:
            assert evaluation_duration_unit in ("episodes", "timesteps")
            self.evaluation_duration_unit = evaluation_duration_unit
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def exploration(self, *, explore: Optional[bool] = None,
                    exploration_config: Optional[dict] = None) -> "AlgorithmConfig":
        """Exploration switches (reference: algorithm_config.py
        .exploration()). ``explore`` gates stochastic action sampling at
        compute-action time; ``exploration_config`` entries land on the
        algorithm config's matching attributes (epsilon schedules for the
        Q-family, noise scales for the deterministic-policy family — each
        algo config declares its own)."""
        if explore is not None:
            self.explore = explore
        if exploration_config:
            self.update_from_dict(dict(exploration_config))
        return self

    def fault_tolerance(self, *, recreate_failed_workers: Optional[bool] = None,
                        max_worker_restarts: Optional[int] = None) -> "AlgorithmConfig":
        """Rollout-worker failure policy (reference: .fault_tolerance()):
        respawn dead workers (WorkerSet._replace_worker) up to a budget, or
        degrade to the survivors."""
        if recreate_failed_workers is not None:
            self.recreate_failed_workers = recreate_failed_workers
        if max_worker_restarts is not None:
            self.max_worker_restarts = max_worker_restarts
        return self

    def reporting(self, *, metrics_num_episodes_for_smoothing: Optional[int] = None,
                  min_time_s_per_iteration: Optional[float] = None) -> "AlgorithmConfig":
        """Result-shaping knobs (reference: .reporting()):
        episode_reward_mean smoothing window and a minimum wall-clock per
        train() iteration (step() keeps running training_steps until it is
        reached — the reference's min_time_s_per_iteration semantics)."""
        if metrics_num_episodes_for_smoothing is not None:
            self.metrics_num_episodes_for_smoothing = metrics_num_episodes_for_smoothing
        if min_time_s_per_iteration is not None:
            self.min_time_s_per_iteration = min_time_s_per_iteration
        return self

    def offline_data(
        self, *, input_=None, output=None, input_reader_kwargs=None
    ) -> "AlgorithmConfig":
        """Offline dataset source/sink (reference: .offline_data()). The
        offline families consume ``input_`` (path/glob/list/Dataset/live
        PolicyServerInput); online families may set ``output`` to log
        rollouts (JSON writer). ``input_reader_kwargs`` reach the
        constructed reader (e.g. timeout_s/min_episodes/window_rows for
        slow external simulators)."""
        if input_ is not None:
            self.input_ = input_
        if output is not None:
            self.output = output
        if input_reader_kwargs is not None:
            self.input_reader_kwargs = dict(input_reader_kwargs)
        return self

    def callbacks(self, callbacks_class) -> "AlgorithmConfig":
        """Attach a DefaultCallbacks subclass (reference: .callbacks())."""
        self.callbacks_class = callbacks_class
        return self

    def framework(self, framework: Optional[str] = None, **_ignored) -> "AlgorithmConfig":
        """Parity shim: this stack is JAX-native; "jax" (or None) is the
        only accepted value — naming torch/tf here is a porting bug we
        surface loudly instead of silently training something else."""
        if framework not in (None, "jax"):
            raise ValueError(
                f"framework {framework!r} unavailable: ray_tpu.rllib is JAX-native"
            )
        return self

    def update_from_dict(self, overrides: dict) -> "AlgorithmConfig":
        """Apply {attr: value} overrides; unknown keys land in .extra
        (shared by the CLI, tuned-example runner, and __init__)."""
        for key, value in (overrides or {}).items():
            if hasattr(self, key):
                setattr(self, key, value)
            else:
                self.extra[key] = value
        return self

    def model_config(self) -> dict:
        """Catalog-shaped model config (reference: config.model dict)."""
        return {
            "fcnet_hiddens": self.model_hiddens,
            "conv_filters": self.model_conv_filters,
        }

    def to_dict(self) -> dict:
        return dict(self.__dict__)

    def build(self) -> "Algorithm":
        assert self.algo_class is not None, "config not bound to an algorithm"
        return self.algo_class(config=self)


# Per-process counter making each Algorithm instance's weight-group name
# unique (see _setup_device_weight_sync).
_WEIGHT_GROUP_SEQ = 0


class Algorithm(Trainable):
    """Extends the Tune Trainable so `tune.Tuner(PPO, ...)` works the same
    way as the reference (§3.6 of the survey)."""

    _config_class = AlgorithmConfig

    def __init__(self, config=None, **kwargs):
        if isinstance(config, AlgorithmConfig):
            self._algo_config = config
        else:
            self._algo_config = self.get_default_config().update_from_dict(config or {})
        from ray_tpu.rllib.callbacks import make_callbacks

        self.callbacks = make_callbacks(getattr(self._algo_config, "callbacks_class", None))
        super().__init__(config=self._algo_config.to_dict())
        # Trainable.__init__ ran setup(); the algorithm is live now.
        self.callbacks.on_algorithm_init(algorithm=self)

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return AlgorithmConfig(algo_class=cls)

    # -- Trainable protocol -------------------------------------------------
    def setup(self, config: dict) -> None:
        # Trainable.__init__ already ran setup; a second explicit setup()
        # (common in user code and tests) must not orphan the first worker
        # set — leaked rollout actors hold CPU reservations forever.
        existing = getattr(self, "workers", None)
        if existing is not None:
            existing.stop()
        existing_eval = getattr(self, "_eval_workers", None)
        if existing_eval is not None:
            existing_eval.stop()
            self._eval_workers = None
        existing_lg = getattr(self, "learner_group", None)
        if existing_lg is not None and hasattr(existing_lg, "stop"):
            existing_lg.stop()
        cfg = self._algo_config
        import gymnasium as gym

        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        from ray_tpu.rllib.models import ModelCatalog

        self.module_spec = ModelCatalog.get_model_spec(
            probe.observation_space, probe.action_space, cfg.model_config()
        )
        probe.close()
        self.workers = WorkerSet(
            cfg.env,
            self.module_spec,
            num_workers=cfg.num_rollout_workers,
            num_envs_per_worker=cfg.num_envs_per_worker,
            env_config=cfg.env_config,
            gamma=cfg.gamma,
            lambda_=cfg.lambda_,
            seed=cfg.seed,
            observation_filter=getattr(cfg, "observation_filter", None),
            agent_connectors=getattr(cfg, "agent_connectors", None),
            action_connectors=getattr(cfg, "action_connectors", None),
            recreate_failed_workers=getattr(cfg, "recreate_failed_workers", True),
            max_worker_restarts=getattr(cfg, "max_worker_restarts", 100),
        )
        self.learner_group = self._build_learner_group(cfg)
        self._device_sync_ready = False
        if getattr(cfg, "weight_sync", "host") == "device_broadcast":
            self._setup_device_weight_sync(cfg)
        self.sync_worker_weights()
        self._episode_reward_window: list = []
        self._timesteps_total = 0

    def _setup_device_weight_sync(self, cfg) -> None:
        """Form the learner↔sampler weight group (Podracer topology): the
        learner/driver is rank 0 (the holder the broadcast fans out from),
        samplers take ranks 1..N. Best-effort — a failed gang init (e.g. a
        worker died during setup) degrades to the host path rather than
        failing setup."""
        # Group names are per-process singletons and nothing outside this
        # Algorithm ever joins its weight group, so suffix the configured
        # name with an instance counter: two live Algorithms in one driver
        # (train + eval experiment, two in-process trials) must not hijack
        # each other's group/address rows.
        global _WEIGHT_GROUP_SEQ
        _WEIGHT_GROUP_SEQ += 1
        group = self._weight_group = f"{cfg.weight_sync_group}-{_WEIGHT_GROUP_SEQ}"
        backend = getattr(cfg, "weight_sync_backend", "cpu")
        world = 1 + self.workers.num_workers
        try:
            from ray_tpu.util import collective as col

            # A re-setup of THIS instance may still hold the name locally.
            col.destroy_collective_group(group)
            self.learner_group.init_weight_collective(world, 0, backend, group)
            self.workers.init_weight_group(group, backend=backend, world_size=world, base_rank=1)
            self._device_sync_ready = True
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "device weight-sync group init failed; falling back to host sync",
                exc_info=True,
            )

    def sync_worker_weights(self):
        """One weight sync, on whichever transport the config picked. The
        device path broadcasts ONE device-object descriptor's payload to
        the fleet (strict=False: a dead sampler is the sync loop's business
        — it respawns the worker, which re-registers into the group at its
        old rank, so the FIRST post-respawn sync is already back on the
        broadcast plane) and never lets a broadcast failure break training:
        any error degrades that sync to the host path."""
        cfg = self._algo_config
        if (
            getattr(cfg, "weight_sync", "host") == "device_broadcast"
            and getattr(self, "_device_sync_ready", False)
        ):
            try:
                from ray_tpu.experimental import device_object

                # Self-heal the roster first: a live sampler that a prior
                # broadcast evicted on a transient stall re-joins, so this
                # sync already covers it over the group plane.
                self.workers.ensure_registered()
                ref = self.learner_group.pack_weight_ref()
                device_object.broadcast(ref, self._weight_group, strict=False)
                self.workers.sync_packed_weights(ref)
                return
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "device-broadcast weight sync failed; using host sync for "
                    "this round", exc_info=True,
                )
        self.workers.sync_weights(self.learner_group.get_weights())

    def resize_workers(self, num_workers: int) -> int:
        """Autoscale the sampler fleet mid-training (Podracer elasticity).
        Growing joins the new samplers into the weight group at fresh tail
        ranks; shrinking evicts the tail ranks from the roster — either way
        the roster epoch bumps, the learner's next broadcast snapshots the
        new membership, and weight sync stays on the group plane (no
        teardown/re-form of the group, no permanent pull-path fallback).
        Syncs weights immediately so grown workers can sample at once.
        Returns the new worker count."""
        n = self.workers.resize(num_workers)
        self.sync_worker_weights()
        if getattr(self._algo_config, "observation_filter", None):
            # Grown workers start with empty filter stats; hand them the
            # merged base so their first fragments are normalized like the
            # rest of the fleet's.
            self.workers.sync_filters()
        return n

    # -- evaluation (reference: Algorithm.evaluate, algorithm.py:850) ------
    @property
    def eval_workers(self):
        """Dedicated evaluation WorkerSet, built lazily on first use so
        algorithms that never evaluate pay nothing (reference: setup builds
        evaluation_workers only when evaluation_interval is set)."""
        ws = getattr(self, "_eval_workers", None)
        if ws is None:
            cfg = self._algo_config
            ws = WorkerSet(
                cfg.env,
                self.module_spec,
                num_workers=max(1, cfg.evaluation_num_workers),
                num_envs_per_worker=cfg.num_envs_per_worker,
                env_config=cfg.env_config,
                gamma=cfg.gamma,
                lambda_=cfg.lambda_,
                # Offset so eval envs never mirror training-env seeds.
                seed=cfg.seed + 100_000,
                observation_filter=getattr(cfg, "observation_filter", None),
                # Eval samples through the SAME pipelines as training
                # (transform-only for stateful stages; reference: eval
                # workers share connector config).
                agent_connectors=getattr(cfg, "agent_connectors", None),
                action_connectors=getattr(cfg, "action_connectors", None),
            )
            self._eval_workers = ws
        return ws

    def _has_rollout_stack(self) -> bool:
        """True when this algorithm uses the standard WorkerSet+LearnerGroup
        stack (base setup); custom-stack algorithms evaluate driver-locally
        through their compute_single_action."""
        return (
            getattr(self, "learner_group", None) is not None
            and isinstance(getattr(self, "workers", None), WorkerSet)
            and getattr(self, "module_spec", None) is not None
        )

    def evaluate(self) -> dict:
        """Run one evaluation round with explore=False and return
        ``{"evaluation": {...metrics...}}``. Eval rollouts happen on a
        dedicated worker set (or a driver-local env for custom-stack
        algorithms), so exploration noise and training episode stats never
        leak into the reported numbers."""
        cfg = self._algo_config
        duration = int(cfg.evaluation_duration)
        by_episodes = cfg.evaluation_duration_unit != "timesteps"
        if self._has_rollout_stack():
            rewards, lens = self._evaluate_with_workers(duration, by_episodes)
        else:
            rewards, lens = self._evaluate_local(duration, by_episodes)
        metrics = {
            "episode_reward_mean": float(np.mean(rewards)) if rewards else float("nan"),
            "episode_reward_min": float(np.min(rewards)) if rewards else float("nan"),
            "episode_reward_max": float(np.max(rewards)) if rewards else float("nan"),
            "episode_len_mean": float(np.mean(lens)) if lens else float("nan"),
            "episodes_this_iter": len(rewards),
        }
        return {"evaluation": metrics}

    def _evaluate_with_workers(self, duration: int, by_episodes: bool):
        ws = self.eval_workers
        ws.sync_weights(self.get_policy_weights())
        if getattr(ws, "observation_filter", None):
            # Eval policies must see the same filtered observations as
            # training; copy the training filter base across.
            ws._filter_base = getattr(self.workers, "_filter_base", None)
            ws.sync_filters()
        rewards: list = []
        lens: list = []
        steps = 0
        fragment = max(16, self._algo_config.rollout_fragment_length)
        # Cap rounds so an env that never terminates can't spin forever.
        for _ in range(64):
            batches = ws.sample(fragment, explore=False)
            steps += sum(len(b) for b in batches)
            stats = ws.episode_stats()
            rewards += stats["episode_rewards"]
            lens += stats["episode_lens"]
            if (by_episodes and len(rewards) >= duration) or (
                not by_episodes and steps >= duration
            ):
                break
        return rewards, lens

    def _make_eval_env(self):
        """Fresh driver-local env for one evaluation round. Created per
        evaluate() call and closed right after (cheap for gym envs) —
        caching it would leak through the custom-stack algorithms' cleanup
        overrides and go stale across re-setup with a new env config."""
        import gymnasium as gym

        cfg = self._algo_config
        return (
            gym.make(cfg.env)
            if isinstance(cfg.env, str)
            else cfg.env(dict(cfg.env_config))
        )

    def _evaluate_local(self, duration: int, by_episodes: bool):
        """Greedy episodes on a driver-local env via compute_single_action
        (used by algorithms with custom learner stacks — DQN family, ES/ARS,
        offline algos — which all expose compute_single_action)."""
        env = self._make_eval_env()
        rewards: list = []
        lens: list = []
        steps = 0
        budget = duration if by_episodes else 64
        try:
            for _ in range(budget):
                obs, _ = env.reset()
                total, length = 0.0, 0
                for _ in range(10_000):
                    action = self.compute_single_action(obs, explore=False)
                    obs, r, terminated, truncated, _ = env.step(action)
                    total += float(r)
                    length += 1
                    steps += 1
                    if terminated or truncated:
                        break
                    if not by_episodes and steps >= duration:
                        break
                rewards.append(total)
                lens.append(length)
                if not by_episodes and steps >= duration:
                    break
        finally:
            try:
                env.close()
            except Exception:
                pass
        return rewards, lens

    def train(self) -> dict:
        """One training iteration + (when due) an evaluation round attached
        under result["evaluation"] (reference: Algorithm.step wiring
        evaluate() by evaluation_interval), then the on_train_result
        callback (which may mutate the result in place)."""
        result = super().train()
        interval = getattr(self._algo_config, "evaluation_interval", None)
        if interval and self.iteration % int(interval) == 0:
            result.update(self.evaluate())
            self.callbacks.on_evaluate_end(
                algorithm=self, evaluation_metrics=result.get("evaluation", {})
            )
        self.callbacks.on_train_result(algorithm=self, result=result)
        return result

    def _build_learner_group(self, cfg: AlgorithmConfig) -> LearnerGroup:
        raise NotImplementedError

    def _gather_rollouts(self, train_batch_size: int, async_sampling: bool = False):
        """Shared sampling front-end (IMPALA/APPO): sync parallel rounds, or
        draining the background env-runners. May return [] in async mode
        (nothing ready yet) — callers should skip the update for that
        iteration."""
        cfg = self._algo_config
        if async_sampling:
            if not self.workers.is_async:
                self.workers.start_async(cfg.rollout_fragment_length)
            batches = self.workers.sample_async(train_batch_size)
            if not batches:
                # Mass worker failure respawns runners WITHOUT weights; they
                # idle until the next broadcast, which the empty-batch early
                # return would skip — re-broadcast here or the trainer
                # livelocks in async_waiting forever.
                self.sync_worker_weights()
            return batches
        per_worker = max(
            1,
            train_batch_size // max(self.workers.num_workers, 1) // cfg.num_envs_per_worker,
        )
        return self.workers.sample(per_worker)

    def training_step(self) -> dict:
        raise NotImplementedError

    def step(self) -> dict:
        t0 = time.time()
        result = self.training_step()
        # Honor the reporting floor: keep running training_steps until the
        # iteration has consumed min_time_s_per_iteration of wall clock
        # (reference: .reporting() min_time_s_per_iteration).
        min_time = getattr(self._algo_config, "min_time_s_per_iteration", None)
        while min_time and time.time() - t0 < float(min_time):
            result = self.training_step()
        # Keep observation-filter statistics consistent across workers
        # (reference: FilterManager.synchronize each iteration).
        if getattr(self.workers, "observation_filter", None):
            self.workers.sync_filters()
        stats = self.workers.episode_stats()
        window = int(getattr(self._algo_config, "metrics_num_episodes_for_smoothing", 100))
        self._episode_reward_window += stats["episode_rewards"]
        self._episode_reward_window = self._episode_reward_window[-window:]
        result.setdefault("episode_reward_mean", float(np.mean(self._episode_reward_window)) if self._episode_reward_window else float("nan"))
        result["episodes_this_iter"] = len(stats["episode_rewards"])
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def save(self) -> Checkpoint:
        """Trainable.save + the checkpoint callback — overriding here (not
        save_checkpoint) covers every algorithm's custom checkpoint
        format."""
        ckpt = super().save()
        self.callbacks.on_checkpoint_saved(algorithm=self, checkpoint=ckpt)
        return ckpt

    def restore(self, checkpoint: Checkpoint) -> None:
        super().restore(checkpoint)
        self.callbacks.on_checkpoint_loaded(algorithm=self)

    def save_checkpoint(self) -> Checkpoint:
        return Checkpoint.from_dict({"weights": self.learner_group.get_weights(), "timesteps": self._timesteps_total})

    def load_checkpoint(self, checkpoint: Checkpoint) -> None:
        data = checkpoint.to_dict()
        self.learner_group.set_weights(data["weights"])
        self._timesteps_total = data.get("timesteps", 0)
        self.workers.sync_weights(data["weights"])

    def cleanup(self) -> None:
        if getattr(self, "_device_sync_ready", False):
            # Release the weight group's name in THIS process (sampler/
            # learner members die with their actors below).
            try:
                from ray_tpu.util import collective as col

                col.destroy_collective_group(self._weight_group)
            except Exception:
                pass
            self._device_sync_ready = False
        workers = getattr(self, "workers", None)
        if workers is not None:
            workers.stop()
        eval_ws = getattr(self, "_eval_workers", None)
        if eval_ws is not None:
            eval_ws.stop()
            self._eval_workers = None
        lg = getattr(self, "learner_group", None)
        if lg is not None and hasattr(lg, "stop"):
            lg.stop()

    # -- convenience (reference: Algorithm.compute_single_action) ----------
    def compute_single_action(self, obs, explore: bool = False):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.core import rl_module

        obs = np.asarray(obs, np.float32)
        # Policies trained behind an observation filter must see filtered
        # observations at inference too.
        base = getattr(self.workers, "_filter_base", None)
        if base is not None:
            from ray_tpu.rllib.connectors import MeanStdFilter

            f = MeanStdFilter()
            f.set_state(base)
            obs = f.transform(obs[None])[0]
        params = jax.tree_util.tree_map(jnp.asarray, self.learner_group.get_weights())
        actions, _, _ = rl_module.sample_actions(
            params, jnp.asarray(np.asarray(obs, np.float32))[None], jax.random.PRNGKey(0), self.module_spec, explore
        )
        a = np.asarray(actions)[0]
        return a.item() if self.module_spec.discrete else a

    def get_policy_weights(self):
        return self.learner_group.get_weights()

    def get_policy(self):
        """Legacy-API view of the trained module (reference:
        Algorithm.get_policy → rllib/policy/policy.py:175). The returned
        Policy shares NO live state — it snapshots current weights (and the
        observation-filter statistics, which a filtered policy needs at
        inference); call again after more training for fresh ones."""
        from ray_tpu.rllib.policy.policy import Policy

        return Policy(
            self.module_spec,
            self.learner_group.get_weights(),
            config={
                "gamma": getattr(self.config, "gamma", 0.99),
                "lambda": getattr(self.config, "lambda_", 0.95),
            },
            obs_filter_state=getattr(self.workers, "_filter_base", None),
        )
