from ray_tpu.rllib.algorithms.mbmpo.mbmpo import MBMPO, MBMPOConfig  # noqa: F401
