"""MBMPO — model-based meta-policy optimization.

Reference: rllib/algorithms/mbmpo/mbmpo.py (Clavera et al. 2018): learn an
ENSEMBLE of dynamics models from real transitions; treat each model as a
"task" and run MAML across the ensemble — inner-adapt the policy inside
each model's imagined MDP, meta-update through the adaptation — so the
policy is robust to model error (the ensemble spread IS the task
distribution). Real env steps are only spent on (a) collecting transitions
to fit the models and (b) the reported true-env return; the PG updates run
on imagined data (mbmpo.py training_step + model_ensemble.py).

TPU-native shape: imagined rollouts are a ``lax.scan`` over the horizon
with the policy forward and the learned dynamics fused in one jitted
program — no Python env stepping, no host transfers — and the dynamics
ensemble trains as a single vmapped update over the model axis. The MAML
inner/outer machinery is imported from algorithms/maml (same jitted
functions, different task source).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.maml.maml import (
    MAMLConfig,
    make_inner_adapt,
    outer_surrogate_loss,
)
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    ADVANTAGES,
    DONES,
    LOGPS,
    OBS,
    REWARDS,
    VALUE_TARGETS,
    VF_PREDS,
    SampleBatch,
    compute_gae,
)


def _dyn_init(key, obs_dim, act_dim, hiddens):
    import jax

    dims = (obs_dim + act_dim,) + tuple(hiddens) + (obs_dim,)
    ks = jax.random.split(key, len(dims) - 1)
    return [
        {
            "w": jax.random.normal(k, (din, dout)) * (2.0 / din) ** 0.5,
            "b": jax.numpy.zeros(dout),
        }
        for k, din, dout in zip(ks, dims[:-1], dims[1:])
    ]


def _dyn_apply(layers, x):
    import jax.numpy as jnp

    for layer in layers[:-1]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    return x @ layers[-1]["w"] + layers[-1]["b"]


class MBMPOConfig(MAMLConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MBMPO)
        self.ensemble_size = 5
        self.dynamics_hiddens = (64, 64)
        self.dynamics_lr = 1e-3
        self.dynamics_train_epochs = 30
        self.dynamics_batch_size = 256
        self.real_episodes_per_iter = 20
        self.imagined_episodes_per_task = 20
        self.replay_capacity = 20_000
        self.num_rollout_workers = 0  # real-env collection is driver-local

    def training(self, *, ensemble_size: Optional[int] = None,
                 dynamics_hiddens=None, dynamics_lr: Optional[float] = None,
                 dynamics_train_epochs: Optional[int] = None,
                 real_episodes_per_iter: Optional[int] = None,
                 imagined_episodes_per_task: Optional[int] = None, **kwargs) -> "MBMPOConfig":
        super().training(**kwargs)
        for name, val in (
            ("ensemble_size", ensemble_size),
            ("dynamics_hiddens", tuple(dynamics_hiddens) if dynamics_hiddens else None),
            ("dynamics_lr", dynamics_lr),
            ("dynamics_train_epochs", dynamics_train_epochs),
            ("real_episodes_per_iter", real_episodes_per_iter),
            ("imagined_episodes_per_task", imagined_episodes_per_task),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class MBMPO(Algorithm):
    @classmethod
    def get_default_config(cls) -> MBMPOConfig:
        return MBMPOConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax
        import optax

        self.cleanup()
        cfg: MBMPOConfig = self._algo_config
        self.env = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        reward_fn = getattr(self.env, "reward_fn", None)
        assert reward_fn is not None, (
            "MBMPO needs the env to expose a jax-traceable "
            "reward_fn(obs, action, next_obs[, task]) (reference: mbmpo.py "
            "validate_config requires env.reward())"
        )
        from ray_tpu.rllib.models import ModelCatalog

        self.module_spec = ModelCatalog.get_model_spec(
            self.env.observation_space, self.env.action_space, cfg.model_config()
        )
        assert not self.module_spec.discrete, "MBMPO supports continuous control"
        self.obs_dim = self.module_spec.obs_dim
        self.act_dim = self.module_spec.action_dim
        from ray_tpu.rllib.core import rl_module

        self.params = rl_module.init_params(jax.random.PRNGKey(cfg.seed), self.module_spec)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        # Dynamics ensemble: stacked [K, ...] params, vmapped training.
        keys = jax.random.split(jax.random.PRNGKey(cfg.seed + 7), cfg.ensemble_size)
        per_model = [_dyn_init(k, self.obs_dim, self.act_dim, cfg.dynamics_hiddens) for k in keys]
        self.dyn_params = jax.tree_util.tree_map(lambda *xs: jax.numpy.stack(xs), *per_model)
        self.dyn_tx = optax.adam(cfg.dynamics_lr)
        self.dyn_opt = self.dyn_tx.init(self.dyn_params)
        self._replay_obs = np.zeros((0, self.obs_dim), np.float32)
        self._replay_act = np.zeros((0, self.act_dim), np.float32)
        self._replay_next = np.zeros((0, self.obs_dim), np.float32)
        self._start_obs = np.zeros((0, self.obs_dim), np.float32)
        self._rng = jax.random.PRNGKey(cfg.seed + 13)
        self._np_rng = np.random.default_rng(cfg.seed)
        self._horizon = int(getattr(self.env, "horizon", 20))
        self._timesteps_total = 0
        self._episode_reward_window: list = []
        self._build_fns(cfg)

    # ------------------------------------------------------------------
    def _build_fns(self, cfg: MBMPOConfig):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.core import rl_module

        spec = self.module_spec
        dyn_tx = self.dyn_tx
        tx = self.tx
        reward_fn = self.env.reward_fn
        import inspect

        n_reward_args = len(inspect.signature(reward_fn).parameters)
        task = None
        if n_reward_args >= 4:
            task = jnp.asarray(np.asarray(self.env.get_task(), np.float32))

        def reward(obs, act, nxt):
            if task is not None:
                return reward_fn(obs, act, nxt, task)
            return reward_fn(obs, act, nxt)

        # -- ensemble supervised update (vmapped over the model axis) ----
        def model_loss(p, obs, act, nxt):
            pred = _dyn_apply(p, jnp.concatenate([obs, act], -1))
            return jnp.mean((pred - (nxt - obs)) ** 2)

        def ensemble_update(dyn, opt, obs_k, act_k, nxt_k):
            # obs_k: [K, B, obs_dim] — each model sees its own bootstrap.
            losses, grads = jax.vmap(jax.value_and_grad(model_loss))(dyn, obs_k, act_k, nxt_k)
            updates, opt = dyn_tx.update(grads, opt, dyn)
            dyn = jax.tree_util.tree_map(lambda p, u: p + u, dyn, updates)
            return dyn, opt, losses.mean()

        self._ensemble_update = jax.jit(ensemble_update)

        # -- imagined rollout inside one model (lax.scan over horizon) ---
        horizon = self._horizon

        def imagine(policy, model, starts, key):
            """starts [B, obs_dim] -> per-step cols stacked [H, B, ...]."""

            def step(carry, _):
                s, k = carry
                k, sk = jax.random.split(k)
                a, logp, v = rl_module.sample_actions(policy, s, sk, spec, True)
                a_clip = jnp.clip(a, -1.0, 1.0)
                nxt = s + _dyn_apply(model, jnp.concatenate([s, a_clip], -1))
                r = reward(s, a_clip, nxt)
                return (nxt, k), (s, a, r, logp, v)

            (_, _), (obs, act, rew, logp, vf) = jax.lax.scan(
                step, (starts, key), None, length=horizon
            )
            return obs, act, rew, logp, vf

        self._imagine = jax.jit(imagine)

        # -- MAML machinery (shared with algorithms/maml) ----------------
        adapt = make_inner_adapt(spec, cfg.inner_lr, cfg.inner_adaptation_steps)
        loss_cfg = {
            "clip_param": cfg.clip_param,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
        }

        def per_task_outer(params, pre_batch, post_batch):
            adapted = adapt(params, pre_batch)
            return outer_surrogate_loss(adapted, post_batch, spec, loss_cfg)

        def meta_update(params, opt_state, pre_stack, post_stack):
            def meta_loss(p):
                return jax.vmap(per_task_outer, in_axes=(None, 0, 0))(
                    p, pre_stack, post_stack
                ).mean()

            loss, grads = jax.value_and_grad(meta_loss)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss

        self._meta_update = jax.jit(meta_update)
        self._adapt = jax.jit(adapt)

    # ------------------------------------------------------------------
    def _collect_real(self, n_episodes: int):
        """Real-env episodes with the current meta-policy; fills the
        transition replay the ensemble trains on."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.core import rl_module

        cfg: MBMPOConfig = self._algo_config
        sample = jax.jit(lambda p, o, k: rl_module.sample_actions(p, o, k, self.module_spec, True))
        rewards = []
        obs_l, act_l, nxt_l, starts = [], [], [], []
        low = self.env.action_space.low
        high = self.env.action_space.high
        for _ in range(n_episodes):
            obs, _ = self.env.reset()
            starts.append(np.asarray(obs, np.float32))
            total = 0.0
            while True:
                o = np.asarray(obs, np.float32)
                self._rng, key = jax.random.split(self._rng)
                a, _, _ = sample(self.params, jnp.asarray(o)[None], key)
                a_np = np.clip(np.asarray(a)[0], low, high).astype(np.float32)
                obs, r, terminated, truncated, _ = self.env.step(a_np)
                total += float(r)
                obs_l.append(o)
                act_l.append(a_np)
                nxt_l.append(np.asarray(obs, np.float32))
                self._timesteps_total += 1
                if terminated or truncated:
                    break
            rewards.append(total)
        self._replay_obs = np.concatenate([self._replay_obs, np.stack(obs_l)])[-cfg.replay_capacity:]
        self._replay_act = np.concatenate([self._replay_act, np.stack(act_l)])[-cfg.replay_capacity:]
        self._replay_next = np.concatenate([self._replay_next, np.stack(nxt_l)])[-cfg.replay_capacity:]
        self._start_obs = np.concatenate([self._start_obs, np.stack(starts)])[-2048:]
        return rewards

    def _train_ensemble(self) -> float:
        import jax.numpy as jnp

        cfg: MBMPOConfig = self._algo_config
        n = len(self._replay_obs)
        bs = min(cfg.dynamics_batch_size, n)
        loss = float("nan")
        for _ in range(cfg.dynamics_train_epochs):
            # Independent bootstrap draw per model — the ensemble spread
            # (= the MAML task distribution) comes from here.
            idx = self._np_rng.integers(0, n, (cfg.ensemble_size, bs))
            self.dyn_params, self.dyn_opt, loss = self._ensemble_update(
                self.dyn_params, self.dyn_opt,
                jnp.asarray(self._replay_obs[idx]),
                jnp.asarray(self._replay_act[idx]),
                jnp.asarray(self._replay_next[idx]),
            )
        return float(loss)

    def _imagined_batch(self, policy_params, model_np):
        """One imagined 'task batch' from a single ensemble member, GAE'd
        to the same column layout the MAML update expects."""
        import jax
        import jax.numpy as jnp

        cfg: MBMPOConfig = self._algo_config
        B = cfg.imagined_episodes_per_task
        starts = self._start_obs[self._np_rng.integers(0, len(self._start_obs), B)]
        self._rng, key = jax.random.split(self._rng)
        obs, act, rew, logp, vf = self._imagine(
            policy_params, model_np, jnp.asarray(starts), key
        )
        # [H, B, ...] -> per-episode fragments -> GAE -> concat.
        obs, act, rew, logp, vf = (np.asarray(x) for x in (obs, act, rew, logp, vf))
        frags = []
        for e in range(B):
            frag = SampleBatch({
                OBS: obs[:, e], ACTIONS: act[:, e], REWARDS: rew[:, e],
                DONES: np.zeros(len(rew), np.float32), LOGPS: logp[:, e],
                VF_PREDS: vf[:, e],
            })
            # Fixed-horizon imagined episodes bootstrap with the policy's
            # own value at the cut — approximated by the final vf pred.
            frags.append(compute_gae(frag, float(vf[-1, e]), cfg.gamma, cfg.lambda_))
        batch = SampleBatch.concat_samples(frags)
        return batch, float(rew.sum(axis=0).mean())

    @staticmethod
    def _stack(batches):
        import jax.numpy as jnp

        keys = batches[0].keys()
        return {k: jnp.asarray(np.stack([b[k] for b in batches])) for k in keys}

    def _model_slice(self, k: int):
        import jax

        return jax.tree_util.tree_map(lambda x: x[k], self.dyn_params)

    def training_step(self) -> dict:
        import jax

        cfg: MBMPOConfig = self._algo_config
        # 1. Real-env data + true return (the reported metric).
        real_rewards = self._collect_real(cfg.real_episodes_per_iter)
        self._episode_reward_window += real_rewards
        self._episode_reward_window = self._episode_reward_window[-100:]
        # 2. Fit the dynamics ensemble.
        model_loss = self._train_ensemble()
        # 3. MAML across the ensemble: model k == task k.
        models = [self._model_slice(k) for k in range(cfg.ensemble_size)]
        pre, pre_rew = zip(*[self._imagined_batch(self.params, m) for m in models])
        pre_stack = self._stack(list(pre))
        adapted_stack = jax.vmap(self._adapt, in_axes=(None, 0))(self.params, pre_stack)
        post, post_rew = [], []
        for k, m in enumerate(models):
            adapted_k = jax.tree_util.tree_map(lambda x, k=k: x[k], adapted_stack)
            b, r = self._imagined_batch(adapted_k, m)
            post.append(b)
            post_rew.append(r)
        post_stack = self._stack(post)
        loss = None
        for _ in range(cfg.maml_optimizer_steps):
            self.params, self.opt_state, loss = self._meta_update(
                self.params, self.opt_state, pre_stack, post_stack
            )
        return {
            "meta_loss": float(loss),
            "dynamics_loss": model_loss,
            "real_episode_reward_mean": float(np.mean(real_rewards)),
            "imagined_pre_adaptation_reward": float(np.mean(pre_rew)),
            "imagined_post_adaptation_reward": float(np.mean(post_rew)),
            "adaptation_delta": float(np.mean(post_rew)) - float(np.mean(pre_rew)),
        }

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window))
            if self._episode_reward_window
            else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def compute_single_action(self, obs, explore: bool = False):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.core import rl_module

        actions, _, _ = rl_module.sample_actions(
            self.params, jnp.asarray(np.asarray(obs, np.float32))[None],
            jax.random.PRNGKey(0), self.module_spec, explore,
        )
        return np.asarray(actions)[0]

    def save_checkpoint(self):
        import jax

        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "weights": jax.tree_util.tree_map(np.asarray, self.params),
            "dyn": jax.tree_util.tree_map(np.asarray, self.dyn_params),
            "timesteps": self._timesteps_total,
        })

    def load_checkpoint(self, checkpoint) -> None:
        import jax
        import jax.numpy as jnp

        data = checkpoint.to_dict()
        self.params = jax.tree_util.tree_map(jnp.asarray, data["weights"])
        self.dyn_params = jax.tree_util.tree_map(jnp.asarray, data["dyn"])
        self._timesteps_total = data.get("timesteps", 0)

    def cleanup(self) -> None:
        env = getattr(self, "env", None)
        if env is not None:
            try:
                env.close()
            except Exception:
                pass
            self.env = None
        eval_ws = getattr(self, "_eval_workers", None)
        if eval_ws is not None:
            eval_ws.stop()
            self._eval_workers = None
