"""A3C — asynchronous advantage actor-critic.

Reference: rllib/algorithms/a3c/a3c.py (Mnih et al. 2016): each rollout
worker computes policy gradients on its OWN fragment and ships gradients
(not samples) to the driver, which applies them to the central weights as
they arrive — no synchronization barrier across workers — and returns fresh
weights to just that worker (training_step :190: `async_parallel_requests`
over `sample_and_compute_grads`).

TPU-native shape: the gradient computation is the jitted A2C loss running on
the worker's CPU device (rollouts stay off-chip, rollout_worker.py:52); the
driver holds params + optax state and applies each incoming gradient in
arrival order. The asynchrony is real — the driver waits on whichever worker
finishes first (`ray_tpu.wait(num_returns=1)`), so a slow worker never gates
the others, at the cost of gradient staleness exactly like the reference.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.a2c.a2c import a2c_loss
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.evaluation.rollout_worker import RolloutWorker


class _A3CWorker(RolloutWorker):
    """RolloutWorker that also computes the A2C gradient on its fragment."""

    def __init__(self, *args, loss_cfg=None, **kwargs):
        super().__init__(*args, **kwargs)
        import jax

        cfg = dict(loss_cfg or {})
        spec = self.spec

        def grads_fn(params, batch):
            (loss, metrics), grads = jax.value_and_grad(
                lambda p: a2c_loss(p, batch, spec, cfg), has_aux=True
            )(params)
            metrics = dict(metrics)
            metrics["total_loss"] = loss
            return grads, metrics

        self._grads_fn = jax.jit(grads_fn)

    def sample_and_grad(self, num_steps: int):
        import jax
        import jax.numpy as jnp

        batch = self.sample(num_steps, explore=True)
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        grads, metrics = self._grads_fn(self._params, jb)
        rewards, lens = self.env.pop_episode_stats()
        return (
            jax.tree_util.tree_map(np.asarray, grads),
            {k: float(v) for k, v in metrics.items()},
            batch.count,
            rewards,
        )


class A3CConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A3C)
        self.lr = 1e-4
        self.grad_clip = 40.0
        self.rollout_fragment_length = 50
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        # Gradient applications per training_step() call (iteration sizing
        # only — the update stream itself is barrier-free).
        self.grads_per_step = 16

    def training(self, *, vf_loss_coeff: Optional[float] = None,
                 entropy_coeff: Optional[float] = None,
                 grads_per_step: Optional[int] = None, **kwargs) -> "A3CConfig":
        super().training(**kwargs)
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        if grads_per_step is not None:
            self.grads_per_step = grads_per_step
        return self


class A3C(Algorithm):
    @classmethod
    def get_default_config(cls) -> A3CConfig:
        return A3CConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax
        import optax

        self.cleanup()
        cfg: A3CConfig = self._algo_config
        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        from ray_tpu.rllib.models import ModelCatalog

        self.module_spec = ModelCatalog.get_model_spec(
            probe.observation_space, probe.action_space, cfg.model_config()
        )
        probe.close()
        from ray_tpu.rllib.core import rl_module

        self.params = rl_module.init_params(jax.random.PRNGKey(cfg.seed), self.module_spec)
        chain = []
        if cfg.grad_clip:
            chain.append(optax.clip_by_global_norm(cfg.grad_clip))
        chain.append(optax.adam(cfg.lr))
        self.tx = optax.chain(*chain)
        self.opt_state = self.tx.init(self.params)
        self._apply = jax.jit(
            lambda params, opt_state, grads: self._apply_impl(params, opt_state, grads)
        )
        loss_cfg = {"vf_loss_coeff": cfg.vf_loss_coeff, "entropy_coeff": cfg.entropy_coeff}
        n = max(cfg.num_rollout_workers, 1)
        worker_cls = ray_tpu.remote(num_cpus=1)(_A3CWorker)
        self.workers = [
            worker_cls.remote(
                cfg.env, self.module_spec, i, max(cfg.num_envs_per_worker, 1),
                dict(cfg.env_config), cfg.gamma, cfg.lambda_, cfg.seed,
                cfg.observation_filter, loss_cfg=loss_cfg,
            )
            for i in range(n)
        ]
        weights = self.get_policy_weights()
        ray_tpu.get([w.set_weights.remote(weights) for w in self.workers], timeout=300)
        # One in-flight gradient task per worker, resubmitted as each lands.
        self._inflight = {
            w.sample_and_grad.remote(cfg.rollout_fragment_length): w for w in self.workers
        }
        self._timesteps_total = 0
        self._episode_reward_window: list = []

    def _apply_impl(self, params, opt_state, grads):
        updates, opt_state = self.tx.update(grads, opt_state, params)
        import jax

        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state

    def get_policy_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def training_step(self) -> dict:
        import jax.numpy as jnp
        import jax

        cfg: A3CConfig = self._algo_config
        metrics: dict = {}
        for _ in range(cfg.grads_per_step):
            # Apply whichever worker's gradient lands first; only THAT
            # worker gets fresh weights and a new task — no barrier.
            done, _ = ray_tpu.wait(list(self._inflight), num_returns=1, timeout=120)
            if not done:
                break
            ref = done[0]
            worker = self._inflight.pop(ref)
            try:
                grads, m, count, rewards = ray_tpu.get(ref, timeout=60)
            except Exception:
                # Worker died mid-fragment: drop its task; respawn-free
                # degradation (remaining workers keep the stream alive).
                self.workers = [w for w in self.workers if w is not worker]
                if not self.workers:
                    raise
                continue
            jgrads = jax.tree_util.tree_map(jnp.asarray, grads)
            self.params, self.opt_state = self._apply(self.params, self.opt_state, jgrads)
            metrics = m
            self._timesteps_total += count
            self._episode_reward_window += rewards
            worker.set_weights.remote(self.get_policy_weights())
            self._inflight[
                worker.sample_and_grad.remote(cfg.rollout_fragment_length)
            ] = worker
        self._episode_reward_window = self._episode_reward_window[-100:]
        return metrics

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window))
            if self._episode_reward_window
            else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def compute_single_action(self, obs, explore: bool = False):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.core import rl_module

        actions, _, _ = rl_module.sample_actions(
            self.params, jnp.asarray(np.asarray(obs, np.float32))[None],
            jax.random.PRNGKey(0), self.module_spec, explore,
        )
        a = np.asarray(actions)[0]
        return a.item() if self.module_spec.discrete else a

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict(
            {"weights": self.get_policy_weights(), "timesteps": self._timesteps_total}
        )

    def load_checkpoint(self, checkpoint) -> None:
        import jax
        import jax.numpy as jnp

        data = checkpoint.to_dict()
        self.params = jax.tree_util.tree_map(jnp.asarray, data["weights"])
        self._timesteps_total = data.get("timesteps", 0)
        ray_tpu.get(
            [w.set_weights.remote(self.get_policy_weights()) for w in self.workers],
            timeout=300,
        )

    def cleanup(self) -> None:
        for w in getattr(self, "workers", []):
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        self._inflight = {}
        eval_ws = getattr(self, "_eval_workers", None)
        if eval_ws is not None:
            eval_ws.stop()
            self._eval_workers = None
