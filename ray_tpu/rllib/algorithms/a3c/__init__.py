from ray_tpu.rllib.algorithms.a3c.a3c import A3C, A3CConfig  # noqa: F401
