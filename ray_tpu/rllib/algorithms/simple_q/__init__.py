from ray_tpu.rllib.algorithms.simple_q.simple_q import SimpleQ, SimpleQConfig  # noqa: F401
