"""SimpleQ — vanilla deep Q-learning without the DQN extensions.

Reference: rllib/algorithms/simple_q/simple_q.py (SimpleQ is the minimal
Q-learner the reference's DQN extends: single Q network + target net,
uniform replay, epsilon-greedy — no double-Q, no prioritized replay, no
n-step, no dueling). Here the relationship is inverted the same way the
config flags allow: SimpleQ is DQN with every extension switched off and
locked off, so the two stay behaviorally distinct even through
``.training()`` overrides.
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig


class SimpleQConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SimpleQ)
        self.double_q = False
        self.prioritized_replay = False
        self.target_network_update_freq = 250

    def training(self, *, double_q=None, prioritized_replay=None, **kwargs) -> "SimpleQConfig":
        # The whole point of SimpleQ is the absence of the extensions;
        # silently honoring these would make it DQN with a different name.
        if double_q or prioritized_replay:
            raise ValueError(
                "SimpleQ is the extension-free Q-learner; use DQNConfig for "
                "double_q/prioritized_replay"
            )
        super().training(**kwargs)
        return self


class SimpleQ(DQN):
    @classmethod
    def get_default_config(cls) -> SimpleQConfig:
        return SimpleQConfig(cls)
