"""CQL — conservative Q-learning (offline RL).

Reference: rllib/algorithms/cql/ (cql.py, cql_torch_policy.py): SAC's
actor-critic updated purely from a fixed dataset, with the CQL(H) regularizer
pushing down Q on out-of-distribution actions (logsumexp over sampled
actions) and up on dataset actions. Reuses SAC's networks and squashed
policy; data comes from the offline readers (rllib/offline), never an env.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.off_policy import OffPolicyTraining, floats
from ray_tpu.rllib.algorithms.sac.sac import (
    _mlp_apply,
    _squashed_sample,
    init_sac_params,
)
from ray_tpu.rllib.offline import make_input_reader
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    NEXT_OBS,
    OBS,
    REWARDS,
)


class CQLConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CQL)
        self.lr = 3e-4
        self.num_rollout_workers = 0
        self.train_batch_size = 256
        self.tau = 5e-3
        self.initial_alpha = 1.0
        self.cql_alpha = 1.0  # conservative penalty weight
        self.num_cql_actions = 4  # sampled actions for the logsumexp
        self.updates_per_iter = 200
        self.input_: Optional[object] = None  # path / list / Dataset
        self.model_hiddens = (256, 256)

    def offline_data(self, *, input_=None, input_reader_kwargs=None) -> "CQLConfig":
        if input_ is not None:
            self.input_ = input_
        if input_reader_kwargs is not None:
            self.input_reader_kwargs = dict(input_reader_kwargs)
        return self

    def training(self, *, tau=None, initial_alpha=None, cql_alpha=None,
                 num_cql_actions=None, updates_per_iter=None, **kwargs) -> "CQLConfig":
        super().training(**kwargs)
        for name, val in (
            ("tau", tau), ("initial_alpha", initial_alpha), ("cql_alpha", cql_alpha),
            ("num_cql_actions", num_cql_actions), ("updates_per_iter", updates_per_iter),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class CQL(OffPolicyTraining, Algorithm):
    @classmethod
    def get_default_config(cls) -> CQLConfig:
        return CQLConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        cfg: CQLConfig = self._algo_config
        assert cfg.input_ is not None, "CQL needs offline data: config.offline_data(input_=...)"
        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        self.discrete = isinstance(probe.action_space, gym.spaces.Discrete)
        self.obs_dim = int(np.prod(probe.observation_space.shape))
        if self.discrete:
            self.action_dim = int(probe.action_space.n)
            self._act_scale = self._act_offset = None
        else:
            # Dataset actions are in env units; the squashed policy and the
            # CQL logsumexp both live in [-1,1] — normalize at the data edge.
            self.action_dim = int(np.prod(probe.action_space.shape))
            low = np.asarray(probe.action_space.low, np.float32)
            high = np.asarray(probe.action_space.high, np.float32)
            self._act_scale = (high - low) / 2.0
            self._act_offset = (high + low) / 2.0
        probe.close()
        self.reader = make_input_reader(
            cfg.input_, gamma=cfg.gamma, seed=cfg.seed,
            **cfg.input_reader_kwargs,
        )
        self.params = init_sac_params(
            jax.random.PRNGKey(cfg.seed), self.obs_dim, self.action_dim, self.discrete, cfg.model_hiddens
        )
        self.params["log_alpha"] = jnp.log(jnp.asarray(cfg.initial_alpha, jnp.float32))
        self.target = {"q1": self.params["q1"], "q2": self.params["q2"]}
        self.target_entropy = (
            0.98 * float(np.log(self.action_dim)) if self.discrete else -float(self.action_dim)
        )
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self._rng = jax.random.PRNGKey(cfg.seed + 1)
        self._timesteps_total = 0
        self._build_fns(cfg)

    def _build_fns(self, cfg: CQLConfig):
        import jax
        import jax.numpy as jnp

        discrete, action_dim = self.discrete, self.action_dim
        gamma, tau = cfg.gamma, cfg.tau
        cql_alpha, n_cql = cfg.cql_alpha, cfg.num_cql_actions
        target_entropy = self.target_entropy
        tx = self.tx

        def loss_fn(params, target, batch, key):
            obs, next_obs = batch[OBS], batch[NEXT_OBS]
            rewards, dones = batch[REWARDS], batch[DONES]
            alpha = jax.lax.stop_gradient(jnp.exp(params["log_alpha"]))
            if discrete:
                # SAC-discrete backup + exact logsumexp penalty.
                next_logpi = jax.nn.log_softmax(_mlp_apply(params["actor"], next_obs))
                next_pi = jnp.exp(next_logpi)
                tq = jnp.minimum(_mlp_apply(target["q1"], next_obs), _mlp_apply(target["q2"], next_obs))
                next_v = jnp.sum(next_pi * (tq - alpha * next_logpi), axis=-1)
                td_target = jax.lax.stop_gradient(rewards + gamma * (1 - dones) * next_v)
                idx = batch[ACTIONS].astype(jnp.int32)
                q1_all = _mlp_apply(params["q1"], obs)
                q2_all = _mlp_apply(params["q2"], obs)
                rows = jnp.arange(obs.shape[0])
                q1, q2 = q1_all[rows, idx], q2_all[rows, idx]
                bellman = 0.5 * (jnp.mean((q1 - td_target) ** 2) + jnp.mean((q2 - td_target) ** 2))
                cql_term = (
                    jnp.mean(jax.scipy.special.logsumexp(q1_all, axis=-1) - q1)
                    + jnp.mean(jax.scipy.special.logsumexp(q2_all, axis=-1) - q2)
                )
                logpi = jax.nn.log_softmax(_mlp_apply(params["actor"], obs))
                pi = jnp.exp(logpi)
                q_min = jax.lax.stop_gradient(jnp.minimum(q1_all, q2_all))
                actor_loss = jnp.mean(jnp.sum(pi * (alpha * logpi - q_min), axis=-1))
                entropy = -jnp.sum(pi * logpi, axis=-1).mean()
            else:
                k1, k2, k3, k4 = jax.random.split(key, 4)
                next_a, next_logp, _ = _squashed_sample(params["actor"], next_obs, k1, action_dim)
                tq1 = _mlp_apply(target["q1"], jnp.concatenate([next_obs, next_a], -1))[:, 0]
                tq2 = _mlp_apply(target["q2"], jnp.concatenate([next_obs, next_a], -1))[:, 0]
                td_target = jax.lax.stop_gradient(
                    rewards + gamma * (1 - dones) * (jnp.minimum(tq1, tq2) - alpha * next_logp)
                )
                sa = jnp.concatenate([obs, batch[ACTIONS]], -1)
                q1 = _mlp_apply(params["q1"], sa)[:, 0]
                q2 = _mlp_apply(params["q2"], sa)[:, 0]
                bellman = 0.5 * (jnp.mean((q1 - td_target) ** 2) + jnp.mean((q2 - td_target) ** 2))

                # CQL(H): logsumexp over uniform + policy actions with
                # importance weights (reference: cql_torch_policy.py).
                B = obs.shape[0]

                def q_of(qp, o, a):
                    rep = jnp.repeat(o, a.shape[1], axis=0)
                    flat = a.reshape(-1, action_dim)
                    return _mlp_apply(qp, jnp.concatenate([rep, flat], -1))[:, 0].reshape(B, -1)

                rand_a = jax.random.uniform(k2, (B, n_cql, action_dim), minval=-1.0, maxval=1.0)
                pol_a, pol_logp, _ = _squashed_sample(
                    params["actor"], jnp.repeat(obs, n_cql, axis=0), k3, action_dim
                )
                # The conservative penalty must not train the actor: without
                # this stop_gradient, minimizing logsumexp Q(s, pi(s)) drives
                # the policy toward low-Q actions through pol_a (same shared-
                # optimizer leak class as the q_pi term below).
                pol_a = jax.lax.stop_gradient(pol_a)
                pol_a = pol_a.reshape(B, n_cql, action_dim)
                pol_logp = pol_logp.reshape(B, n_cql)
                log_u = -action_dim * jnp.log(2.0)  # uniform density on [-1,1]^d
                cql_term = 0.0
                for qp, qd in ((params["q1"], q1), (params["q2"], q2)):
                    cat = jnp.concatenate(
                        [q_of(qp, obs, rand_a) - log_u, q_of(qp, obs, pol_a) - jax.lax.stop_gradient(pol_logp)],
                        axis=1,
                    )
                    cql_term = cql_term + jnp.mean(
                        jax.scipy.special.logsumexp(cat, axis=1) - jnp.log(2.0 * n_cql) - qd
                    )
                a_pi, logp_pi, _ = _squashed_sample(params["actor"], obs, k4, action_dim)
                # Stop-gradient the critics in the actor term: the shared
                # optimizer would otherwise push Q UP on policy actions,
                # directly fighting the CQL conservative penalty above.
                q_pi = jnp.minimum(
                    _mlp_apply(jax.lax.stop_gradient(params["q1"]), jnp.concatenate([obs, a_pi], -1))[:, 0],
                    _mlp_apply(jax.lax.stop_gradient(params["q2"]), jnp.concatenate([obs, a_pi], -1))[:, 0],
                )
                actor_loss = jnp.mean(alpha * logp_pi - q_pi)
                entropy = -logp_pi.mean()
            alpha_loss = params["log_alpha"] * jax.lax.stop_gradient(entropy - target_entropy)
            total = bellman + cql_alpha * cql_term + actor_loss + alpha_loss
            return total, {
                "bellman_loss": bellman,
                "cql_term": cql_term,
                "actor_loss": actor_loss,
                "alpha": alpha,
            }

        def train_step(params, target, opt_state, batch, key):
            (_, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, target, batch, key)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            target = jax.tree_util.tree_map(
                lambda t, p: (1 - tau) * t + tau * p,
                target,
                {"q1": params["q1"], "q2": params["q2"]},
            )
            return params, target, opt_state, metrics

        self._train_step = jax.jit(train_step)

    def training_step(self) -> dict:
        import jax
        import jax.numpy as jnp

        cfg: CQLConfig = self._algo_config
        last_m = None
        for _ in range(cfg.updates_per_iter):
            batch = self.reader.next(cfg.train_batch_size)
            actions = np.asarray(batch[ACTIONS])
            if not self.discrete:
                actions = np.clip(
                    (actions.reshape(len(actions), -1).astype(np.float32) - self._act_offset)
                    / np.maximum(self._act_scale, 1e-8),
                    -1.0,
                    1.0,
                )
            jb = {
                OBS: jnp.asarray(np.asarray(batch[OBS], np.float32)),
                ACTIONS: jnp.asarray(actions),
                REWARDS: jnp.asarray(np.asarray(batch[REWARDS], np.float32)),
                DONES: jnp.asarray(np.asarray(batch.get(DONES, np.zeros(len(batch))), np.float32)),
                NEXT_OBS: jnp.asarray(np.asarray(batch[NEXT_OBS], np.float32)),
            }
            self._rng, key = jax.random.split(self._rng)
            self.params, self.target, self.opt_state, last_m = self._train_step(
                self.params, self.target, self.opt_state, jb, key
            )
            self._timesteps_total += cfg.train_batch_size
        return floats(last_m) if last_m is not None else {}

    def compute_single_action(self, obs, explore: bool = False):
        import jax
        import jax.numpy as jnp

        obs = jnp.asarray(np.asarray(obs, np.float32).reshape(1, -1))
        if self.discrete:
            logits = _mlp_apply(self.params["actor"], obs)
            return int(np.asarray(jnp.argmax(logits, -1))[0])
        self._rng, key = jax.random.split(self._rng)
        a, _, det = _squashed_sample(self.params["actor"], obs, key, self.action_dim)
        return np.asarray(a if explore else det)[0] * self._act_scale + self._act_offset
