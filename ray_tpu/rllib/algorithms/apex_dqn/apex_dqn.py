"""Ape-X DQN — distributed prioritized experience replay.

Reference: rllib/algorithms/apex_dqn/apex_dqn.py (Horgan et al. 2018): many
rollout-worker actors explore with a per-worker epsilon ladder and feed
actor-sharded prioritized replay buffers; the learner samples shards
round-robin, trains the double-Q TD loss, pushes updated priorities back to
the owning shard, and broadcasts weights periodically. The replay memory
therefore scales horizontally with shard actors instead of living in the
learner process (VERDICT r1 #9: distributed replay).
"""

from __future__ import annotations

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.dqn.dqn import DQNConfig, dqn_loss, q_forward
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)
from ray_tpu.rllib.utils.replay_buffers import PrioritizedReplayBuffer


class _ApexWorker:
    """Rollout actor: explores with its own fixed epsilon (Ape-X ladder
    eps_i = 0.4^(1 + 7 i/(N-1))) against the latest broadcast weights."""

    def __init__(self, env, env_config, spec, worker_index, num_workers, num_envs, seed):
        import jax

        # Rollouts stay off-chip (same rule as rollout_worker.py): on a TPU
        # host an unpinned jax init would contend with the learner's chip.
        jax.config.update("jax_platforms", "cpu")
        from ray_tpu.rllib.env.vector_env import VectorEnv

        self.spec = spec
        self.env = VectorEnv(env, num_envs, env_config, worker_index, seed=seed + worker_index)
        self._q = jax.jit(lambda p, o: q_forward(p, o, spec))
        self.params = None
        denom = max(num_workers - 1, 1)
        self.epsilon = 0.4 ** (1 + 7 * worker_index / denom)
        self._rng = np.random.default_rng(seed * 9973 + worker_index)

    def set_weights(self, weights):
        import jax
        import jax.numpy as jnp

        self.params = jax.tree_util.tree_map(jnp.asarray, weights)
        return True

    def sample(self, n_steps: int):
        import jax.numpy as jnp

        cols = {OBS: [], ACTIONS: [], REWARDS: [], DONES: [], NEXT_OBS: []}
        for _ in range(n_steps):
            obs = self.env.current_obs().astype(np.float32)
            q = np.asarray(self._q(self.params, jnp.asarray(obs)))
            actions = q.argmax(axis=-1)
            mask = self._rng.random(len(actions)) < self.epsilon
            actions = np.where(
                mask, self._rng.integers(0, self.spec.action_dim, len(actions)), actions
            )
            next_obs, rewards, dones, _ = self.env.step(actions)
            cols[OBS].append(obs)
            cols[ACTIONS].append(actions)
            cols[REWARDS].append(rewards)
            cols[DONES].append(dones.astype(np.float32))
            cols[NEXT_OBS].append(next_obs.astype(np.float32))
        out = {k: np.concatenate(v) for k, v in cols.items()}
        rews, lens = self.env.pop_episode_stats()
        return out, rews, len(out[OBS])

    def stop(self):
        self.env.close()
        return True


class _ReplayShard:
    """One shard of the distributed prioritized replay memory."""

    def __init__(self, capacity: int, seed: int):
        self.buf = PrioritizedReplayBuffer(capacity, seed=seed)

    def add(self, cols: dict):
        self.buf.add(SampleBatch({k: np.asarray(v) for k, v in cols.items()}))
        return len(self.buf)

    def sample_with_idx(self, n: int):
        if len(self.buf) < n:
            return None
        out, idx = self.buf.sample_with_indices(n)
        return dict(out), idx

    def update_priorities(self, idx, td_errors):
        # Addressed by explicit indices: other learner rounds may have
        # sampled in between (the implicit last-idx protocol doesn't
        # survive interleaving).
        self.buf.update_priorities_at(idx, td_errors)
        return True

    def size(self) -> int:
        return len(self.buf)


class ApexDQNConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ApexDQN)
        self.num_rollout_workers = 2
        self.num_replay_shards = 2
        self.rollout_fragment_length = 50
        self.weight_sync_period_updates = 16
        self.train_rounds_per_iter = 8
        self.updates_per_round = 4

    def training(self, *, num_replay_shards=None, rollout_fragment_length=None,
                 weight_sync_period_updates=None, train_rounds_per_iter=None,
                 updates_per_round=None, **kwargs) -> "ApexDQNConfig":
        if "epsilon_timesteps" in kwargs or "final_epsilon" in kwargs:
            # Ape-X never anneals: workers use the fixed per-worker ladder
            # eps_i = 0.4^(1+7i/(N-1)). Accepting a schedule silently would
            # imply annealing that doesn't happen.
            import warnings

            warnings.warn(
                "ApexDQN ignores epsilon schedule fields (epsilon_timesteps/"
                "final_epsilon): exploration uses the fixed per-worker "
                "epsilon ladder", stacklevel=2,
            )
            kwargs.pop("epsilon_timesteps", None)
            kwargs.pop("final_epsilon", None)
        super().training(**kwargs)
        for name, val in (
            ("num_replay_shards", num_replay_shards),
            ("rollout_fragment_length", rollout_fragment_length),
            ("weight_sync_period_updates", weight_sync_period_updates),
            ("train_rounds_per_iter", train_rounds_per_iter),
            ("updates_per_round", updates_per_round),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class ApexDQN(Algorithm):
    @classmethod
    def get_default_config(cls) -> ApexDQNConfig:
        return ApexDQNConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax

        cfg: ApexDQNConfig = self._algo_config
        # Re-setup (Trainable.__init__ already ran setup once) must not leak
        # the previous actor fleet's CPU reservations.
        self.cleanup()
        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        from ray_tpu.rllib.models import ModelCatalog

        self.module_spec = ModelCatalog.get_model_spec(
            probe.observation_space, probe.action_space, cfg.model_config()
        )
        assert self.module_spec.discrete, "ApexDQN requires a discrete action space"
        probe.close()
        self.learner = Learner(
            self.module_spec, dqn_loss, lr=cfg.lr, grad_clip=cfg.grad_clip, seed=cfg.seed
        )
        self.target_params = self.learner.get_weights()
        self._q_fn = jax.jit(lambda p, o: q_forward(p, o, self.module_spec))

        n_workers = max(cfg.num_rollout_workers, 1)
        worker_cls = ray_tpu.remote(num_cpus=getattr(cfg, "num_cpus_per_worker", None) or 1)(_ApexWorker)
        self.workers = [
            worker_cls.remote(
                cfg.env, dict(cfg.env_config), self.module_spec,
                i, n_workers, max(cfg.num_envs_per_worker, 1), cfg.seed,
            )
            for i in range(n_workers)
        ]
        shard_cls = ray_tpu.remote(num_cpus=0.1)(_ReplayShard)
        shard_cap = max(1, cfg.replay_buffer_capacity // max(cfg.num_replay_shards, 1))
        self.shards = [
            shard_cls.remote(shard_cap, cfg.seed + 31 * i) for i in range(cfg.num_replay_shards)
        ]
        self._shard_sizes = {i: 0 for i in range(len(self.shards))}
        weights = self.learner.get_weights()
        ray_tpu.get([w.set_weights.remote(weights) for w in self.workers], timeout=300)
        self._timesteps_total = 0
        self._updates = 0
        self._last_sync = 0
        self._add_rr = 0
        self._sample_rr = 0
        self._replay_size = 0
        self._episode_reward_window: list = []

    def training_step(self) -> dict:
        cfg: ApexDQNConfig = self._algo_config
        metrics: dict = {}
        for _ in range(cfg.train_rounds_per_iter):
            # Fan the rollout actors out; route each fragment to a shard.
            refs = [w.sample.remote(cfg.rollout_fragment_length) for w in self.workers]
            add_refs = []
            add_shards = []
            for cols, rews, count in ray_tpu.get(refs, timeout=600):
                shard_i = self._add_rr % len(self.shards)
                self._add_rr += 1
                add_refs.append(self.shards[shard_i].add.remote(cols))
                add_shards.append(shard_i)
                self._timesteps_total += count
                self._episode_reward_window += rews
            # shard.add returns the shard's new size; track the latest per
            # shard instead of a second size() fan-out every round.
            for size, shard in zip(ray_tpu.get(add_refs, timeout=300), add_shards):
                self._shard_sizes[shard] = size
            self._replay_size = sum(self._shard_sizes.values())
            self._episode_reward_window = self._episode_reward_window[-100:]
            if self._replay_size < cfg.learning_starts:
                continue
            for _ in range(cfg.updates_per_round):
                metrics = self._train_once() or metrics
            if self._updates - self._last_sync >= cfg.weight_sync_period_updates:
                self._last_sync = self._updates
                weights = self.learner.get_weights()
                ray_tpu.get(
                    [w.set_weights.remote(weights) for w in self.workers], timeout=300
                )
        metrics["replay_size"] = self._replay_size
        return metrics

    def _train_once(self):
        import jax
        import jax.numpy as jnp

        cfg: ApexDQNConfig = self._algo_config
        shard = self.shards[self._sample_rr % len(self.shards)]
        self._sample_rr += 1
        res = ray_tpu.get(shard.sample_with_idx.remote(cfg.train_batch_size), timeout=300)
        if res is None:
            return None
        batch, idx = res
        next_obs = jnp.asarray(batch[NEXT_OBS])
        target = jax.tree_util.tree_map(jnp.asarray, self.target_params)
        q_next_target = np.asarray(self._q_fn(target, next_obs))
        if cfg.double_q:
            q_next_online = np.asarray(self._q_fn(self.learner.params, next_obs))
            best = q_next_online.argmax(axis=-1)
            q_next = q_next_target[np.arange(len(best)), best]
        else:
            q_next = q_next_target.max(axis=-1)
        td_target = batch[REWARDS] + cfg.gamma * (1.0 - batch[DONES]) * q_next
        train_batch = SampleBatch({
            OBS: batch[OBS],
            ACTIONS: batch[ACTIONS],
            "td_target": td_target.astype(np.float32),
            "weights": batch["weights"],
        })
        metrics = self.learner.update(train_batch, {})
        q = np.asarray(self._q_fn(self.learner.params, jnp.asarray(batch[OBS])))
        td_err = q[np.arange(len(td_target)), batch[ACTIONS].astype(int)] - td_target
        shard.update_priorities.remote(idx, td_err)
        self._updates += 1
        if self._updates % cfg.target_network_update_freq == 0:
            self.target_params = self.learner.get_weights()
        return metrics

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["episode_reward_mean"] = (
            float(np.mean(self._episode_reward_window))
            if self._episode_reward_window
            else float("nan")
        )
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def compute_single_action(self, obs, explore: bool = False):
        """Greedy argmax-Q (evaluation / external callers); exploration is
        the rollout workers' per-worker epsilon, not reproduced here."""
        import jax.numpy as jnp

        q = np.asarray(
            self._q_fn(
                self.learner.params, jnp.asarray(np.asarray(obs, np.float32))[None]
            )
        )
        return int(q.argmax())

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "weights": self.learner.get_weights(),
            "target": self.target_params,
            "timesteps": self._timesteps_total,
            "updates": self._updates,
        })

    def load_checkpoint(self, checkpoint) -> None:
        data = checkpoint.to_dict()
        self.learner.set_weights(data["weights"])
        self.target_params = data["target"]
        self._timesteps_total = data.get("timesteps", 0)
        self._updates = data.get("updates", 0)
        weights = self.learner.get_weights()
        ray_tpu.get([w.set_weights.remote(weights) for w in self.workers], timeout=300)

    def cleanup(self) -> None:
        for w in getattr(self, "workers", []):
            try:
                ray_tpu.get(w.stop.remote(), timeout=10)
            except Exception:
                pass
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        for s in getattr(self, "shards", []):
            try:
                ray_tpu.kill(s)
            except Exception:
                pass
