from ray_tpu.rllib.algorithms.crr.crr import CRR, CRRConfig

__all__ = ["CRR", "CRRConfig"]
