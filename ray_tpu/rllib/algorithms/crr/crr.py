"""CRR — Critic Regularized Regression (offline RL; Wang et al. 2020).

Reference: rllib/algorithms/crr/ (crr.py, torch policy): purely offline
actor-critic where the actor is trained by ADVANTAGE-FILTERED behavior
cloning on dataset actions:

    L_actor = -f(A(s, a)) * log pi(a | s),   A(s,a) = Q(s,a) - E_{a'~pi} Q(s,a')

with f either ``exp`` (exp(A / beta), clipped — CRR-exp) or ``binary``
(1[A > 0] — CRR-binary/"max"). The critic is plain TD against a Polyak
target with the expectation over the CURRENT policy for the bootstrap (no
max — avoids offline overestimation). Unlike CQL there is no explicit
OOD-action penalty: staying near the data comes from the regression form
itself.

One jitted update trains critic + actor; data flows from the offline
readers (rllib/offline), never an env. Discrete spaces take exact
expectations over actions; continuous ones sample from a squashed
Gaussian (SAC's machinery).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.off_policy import OffPolicyTraining
from ray_tpu.rllib.algorithms.sac.sac import (
    _mlp_apply,
    _mlp_params,
    _squashed_sample,
)
from ray_tpu.rllib.offline import make_input_reader
from ray_tpu.rllib.policy.sample_batch import ACTIONS, DONES, NEXT_OBS, OBS, REWARDS


class CRRConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CRR)
        self.lr = 3e-4
        self.num_rollout_workers = 0
        self.train_batch_size = 256
        self.tau = 5e-3
        self.weight_type = "exp"   # "exp" | "binary" (reference: weight_type)
        self.temperature = 1.0      # beta for exp weights
        self.max_weight = 20.0      # exp-weight clip (reference: max_weight)
        self.n_action_samples = 4   # continuous: samples for E_pi[Q]
        self.updates_per_iter = 200
        self.input_: Optional[object] = None
        self.model_hiddens = (256, 256)

    def offline_data(self, *, input_=None, input_reader_kwargs=None) -> "CRRConfig":
        if input_ is not None:
            self.input_ = input_
        if input_reader_kwargs is not None:
            self.input_reader_kwargs = dict(input_reader_kwargs)
        return self

    def training(self, *, tau=None, weight_type=None, temperature=None,
                 max_weight=None, n_action_samples=None, updates_per_iter=None,
                 **kwargs) -> "CRRConfig":
        super().training(**kwargs)
        for name, val in (
            ("tau", tau), ("weight_type", weight_type), ("temperature", temperature),
            ("max_weight", max_weight), ("n_action_samples", n_action_samples),
            ("updates_per_iter", updates_per_iter),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class CRR(OffPolicyTraining, Algorithm):
    @classmethod
    def get_default_config(cls) -> CRRConfig:
        return CRRConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        cfg: CRRConfig = self._algo_config
        assert cfg.input_ is not None, "CRR needs offline data: config.offline_data(input_=...)"
        assert cfg.weight_type in ("exp", "binary")
        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        self.discrete = isinstance(probe.action_space, gym.spaces.Discrete)
        self.obs_dim = int(np.prod(probe.observation_space.shape))
        if self.discrete:
            self.action_dim = int(probe.action_space.n)
            self._act_scale = self._act_offset = None
        else:
            self.action_dim = int(np.prod(probe.action_space.shape))
            low = np.asarray(probe.action_space.low, np.float32)
            high = np.asarray(probe.action_space.high, np.float32)
            self._act_scale = (high - low) / 2.0
            self._act_offset = (high + low) / 2.0
        probe.close()
        self.reader = make_input_reader(
            cfg.input_, gamma=cfg.gamma, seed=cfg.seed,
            **cfg.input_reader_kwargs,
        )

        keys = jax.random.split(jax.random.PRNGKey(cfg.seed), 3)
        H = cfg.model_hiddens
        if self.discrete:
            self.params = {
                "actor": _mlp_params(keys[0], self.obs_dim, H, self.action_dim),
                "q": _mlp_params(keys[1], self.obs_dim, H, self.action_dim),
            }
        else:
            self.params = {
                # Squashed Gaussian head: mean + log_std.
                "actor": _mlp_params(keys[0], self.obs_dim, H, 2 * self.action_dim),
                "q": _mlp_params(keys[1], self.obs_dim + self.action_dim, H, 1),
            }
        self.target_q = jax.tree_util.tree_map(np.asarray, self.params["q"])
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self._rng = jax.random.PRNGKey(cfg.seed + 1)
        self._timesteps_total = 0
        self._build_update(cfg)

    def _build_update(self, cfg: CRRConfig):
        import jax
        import jax.numpy as jnp
        import optax

        discrete = self.discrete
        gamma, tau = cfg.gamma, cfg.tau
        beta, wmax = cfg.temperature, cfg.max_weight
        n_samples = cfg.n_action_samples
        binary = cfg.weight_type == "binary"
        tx = self.tx

        def policy_logp_and_expq(params, q_params, obs, key):
            """Returns (log-prob fn inputs, E_{a~pi} Q(s, a))."""
            if discrete:
                logits = _mlp_apply(params["actor"], obs)
                pi = jax.nn.softmax(logits)
                q_all = _mlp_apply(q_params, obs)          # [B, A]
                expq = jnp.sum(pi * q_all, axis=-1)        # [B]
                return logits, expq
            # Continuous: sample n actions from the squashed Gaussian.
            out = _mlp_apply(params["actor"], obs)
            action_dim = out.shape[-1] // 2
            mean, log_std = out[:, :action_dim], out[:, action_dim:]
            log_std = jnp.clip(log_std, -10.0, 2.0)
            qs = []
            for i in range(n_samples):
                a, _, _ = _squashed_sample(
                    params["actor"], obs, jax.random.fold_in(key, i), action_dim
                )
                qs.append(_mlp_apply(q_params, jnp.concatenate([obs, a], -1))[..., 0])
            return (mean, log_std), jnp.mean(jnp.stack(qs), axis=0)

        def update(params, target_q, opt_state, batch, key):
            obs = batch[OBS]
            acts = batch[ACTIONS]
            rew = batch[REWARDS]
            dones = batch[DONES]
            next_obs = batch[NEXT_OBS]

            def loss_fn(p):
                # ---- critic: TD with E_pi[Q_target] bootstrap (no max) ----
                _, expq_next = policy_logp_and_expq(
                    jax.lax.stop_gradient(p), target_q, next_obs, jax.random.fold_in(key, 1)
                )
                y = rew + gamma * (1.0 - dones) * expq_next
                y = jax.lax.stop_gradient(y)
                if discrete:
                    q_all = _mlp_apply(p["q"], obs)
                    q_sa = jnp.take_along_axis(q_all, acts.astype(jnp.int32)[:, None], -1)[:, 0]
                else:
                    q_sa = _mlp_apply(p["q"], jnp.concatenate([obs, acts], -1))[..., 0]
                critic_loss = jnp.mean(jnp.square(q_sa - y))

                # ---- actor: advantage-filtered regression on dataset a ----
                head, expq = policy_logp_and_expq(
                    p, jax.lax.stop_gradient(p["q"]), obs, jax.random.fold_in(key, 2)
                )
                adv = jax.lax.stop_gradient(q_sa) - expq
                adv = jax.lax.stop_gradient(adv)
                if binary:
                    w = (adv > 0).astype(jnp.float32)
                else:
                    w = jnp.minimum(jnp.exp(adv / beta), wmax)
                if discrete:
                    logits = head
                    logp = jax.nn.log_softmax(logits)
                    logp_a = jnp.take_along_axis(logp, acts.astype(jnp.int32)[:, None], -1)[:, 0]
                else:
                    mean, log_std = head
                    # Invert tanh squash for dataset actions (in [-1,1]).
                    a = jnp.clip(acts, -1 + 1e-6, 1 - 1e-6)
                    pre = jnp.arctanh(a)
                    var = jnp.exp(2 * log_std)
                    logp_a = jnp.sum(
                        -0.5 * (jnp.square(pre - mean) / var + 2 * log_std + jnp.log(2 * jnp.pi))
                        - jnp.log(1 - jnp.square(a) + 1e-6),
                        axis=-1,
                    )
                actor_loss = -jnp.mean(w * logp_a)
                return critic_loss + actor_loss, {
                    "critic_loss": critic_loss,
                    "actor_loss": actor_loss,
                    "mean_weight": w.mean(),
                    "q_mean": q_sa.mean(),
                }

            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target_q = jax.tree_util.tree_map(
                lambda t, o: (1.0 - tau) * t + tau * o, target_q, params["q"]
            )
            aux = dict(aux)
            aux["total_loss"] = loss
            return params, target_q, opt_state, aux

        self._update = jax.jit(update)

    def training_step(self) -> dict:
        import jax
        import jax.numpy as jnp

        cfg: CRRConfig = self._algo_config
        aux = {}
        for _ in range(cfg.updates_per_iter):
            batch = self.reader.next(cfg.train_batch_size)
            jb = {k: jnp.asarray(np.asarray(batch[k], np.float32)) for k in (OBS, ACTIONS, REWARDS, DONES, NEXT_OBS)}
            if not self.discrete and self._act_scale is not None:
                jb[ACTIONS] = (jb[ACTIONS] - self._act_offset) / self._act_scale
            self._rng, key = jax.random.split(self._rng)
            self.params, self.target_q, self.opt_state, aux = self._update(
                self.params, self.target_q, self.opt_state, jb, key
            )
            self._timesteps_total += cfg.train_batch_size
        return {k: float(v) for k, v in aux.items()}

    def step(self) -> dict:
        import time

        t0 = time.time()
        result = self.training_step()
        result["timesteps_total"] = self._timesteps_total
        result["time_this_iter_s"] = time.time() - t0
        return result

    def compute_single_action(self, obs, explore: bool = False):
        import jax.numpy as jnp

        obs = jnp.asarray(np.asarray(obs, np.float32).reshape(1, -1))
        if self.discrete:
            logits = np.asarray(_mlp_apply(self.params["actor"], obs))[0]
            return int(logits.argmax())
        out = np.asarray(_mlp_apply(self.params["actor"], obs))[0]
        mean = np.tanh(out[: self.action_dim])
        return mean * self._act_scale + self._act_offset

    def save_checkpoint(self):
        from ray_tpu.air.checkpoint import Checkpoint

        return Checkpoint.from_dict({
            "params": self.params,
            "target_q": self.target_q,
            "opt_state": self.opt_state,
            "timesteps": self._timesteps_total,
            # The action-sampling stream must not replay pre-save draws
            # after a restore.
            "rng": np.asarray(self._rng),
        })

    def load_checkpoint(self, checkpoint) -> None:
        import jax.numpy as jnp

        data = checkpoint.to_dict()
        self.params = data["params"]
        self.target_q = data["target_q"]
        self.opt_state = data["opt_state"]
        self._timesteps_total = data.get("timesteps", 0)
        if "rng" in data:
            self._rng = jnp.asarray(data["rng"])

    def cleanup(self) -> None:
        pass
