from ray_tpu.rllib.algorithms.marwil.marwil import MARWIL, BC, BCConfig, MARWILConfig  # noqa: F401
