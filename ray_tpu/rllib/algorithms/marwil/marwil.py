"""MARWIL (advantage-weighted behavior cloning) and BC.

Reference: rllib/algorithms/marwil/marwil.py (+ marwil_torch_policy loss) and
rllib/algorithms/bc/ (BC = MARWIL with beta=0). Offline algorithms: the
training batch comes from a JsonReader/DatasetReader instead of rollout
workers; rollout workers are kept only for evaluation.

Loss (jitted on the learner): policy term -E[exp(beta * A / c) * logp(a|s)]
with A = (return-to-go - V(s)) and c a running norm; value term regresses
V(s) on return-to-go. beta = 0 drops the value influence on the policy term
entirely (pure behavior cloning).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.policy.sample_batch import ACTIONS, OBS, VALUE_TARGETS


def marwil_loss(params, batch, spec, cfg):
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.core import rl_module

    logp, entropy, value = rl_module.action_logp_and_entropy(
        params, batch[OBS], batch[ACTIONS], spec
    )
    beta = cfg["beta"]
    targets = batch[VALUE_TARGETS]
    adv = targets - value
    # exp-weighted imitation; advantage normalized by its batch RMS
    # (reference uses a moving average — batch RMS is the jit-friendly form).
    c = jnp.sqrt(jnp.mean(adv**2) + 1e-8)
    weights = jnp.where(beta > 0, jnp.exp(beta * jax.lax.stop_gradient(adv / c)), 1.0)
    policy_loss = -jnp.mean(weights * logp)
    vf_loss = jnp.mean(adv**2)
    total = (
        policy_loss
        + cfg["vf_coeff"] * jnp.where(beta > 0, vf_loss, 0.0)
        - cfg["entropy_coeff"] * entropy.mean()
    )
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "bc_logp": logp.mean(),
        "entropy": entropy.mean(),
    }


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        self.beta = 1.0
        self.vf_coeff = 1.0
        self.entropy_coeff = 0.0
        self.grad_clip = 40.0
        self.lr = 1e-4
        self.train_batch_size = 2000
        self.input_ = None  # path / glob / list of files / Dataset
        # Offline: no training rollouts; online interaction happens only
        # when the user opts into evaluation via .evaluation(...) — the
        # base Algorithm then runs greedy episodes on a dedicated eval
        # WorkerSet (reference: offline algos default to no online eval).
        self.num_rollout_workers = 0
        self.evaluation_interval = None

    def offline_data(self, *, input_=None, input_reader_kwargs=None) -> "MARWILConfig":
        if input_ is not None:
            self.input_ = input_
        if input_reader_kwargs is not None:
            self.input_reader_kwargs = dict(input_reader_kwargs)
        return self

    def training(self, *, beta: Optional[float] = None, vf_coeff: Optional[float] = None,
                 entropy_coeff: Optional[float] = None, **kwargs) -> "MARWILConfig":
        super().training(**kwargs)
        if beta is not None:
            self.beta = beta
        if vf_coeff is not None:
            self.vf_coeff = vf_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        return self


class MARWIL(Algorithm):
    @classmethod
    def get_default_config(cls) -> MARWILConfig:
        return MARWILConfig(cls)

    def setup(self, config: dict) -> None:
        super().setup(config)
        cfg: MARWILConfig = self._algo_config
        if cfg.input_ is None:
            raise ValueError(f"{type(self).__name__} requires config.offline_data(input_=...)")
        from ray_tpu.rllib.offline import make_input_reader

        self.reader = make_input_reader(
            cfg.input_, gamma=cfg.gamma, seed=cfg.seed,
            **cfg.input_reader_kwargs,
        )

    def _build_learner_group(self, cfg: MARWILConfig) -> LearnerGroup:
        return LearnerGroup(
            self.module_spec,
            marwil_loss,
            lr=cfg.lr,
            grad_clip=cfg.grad_clip,
            seed=cfg.seed,
            num_learners=cfg.num_learners,
            num_tpus_per_learner=cfg.num_tpus_per_learner,
        )

    def training_step(self) -> dict:
        cfg: MARWILConfig = self._algo_config
        batch = self.reader.next(cfg.train_batch_size)
        self._timesteps_total += len(batch)
        loss_cfg = {
            "beta": cfg.beta,
            "vf_coeff": cfg.vf_coeff,
            "entropy_coeff": cfg.entropy_coeff,
        }
        metrics = self.learner_group.update(batch, loss_cfg)
        # Evaluation rollouts (the only online interaction) ride the base
        # Algorithm.evaluate() machinery: train() runs greedy episodes on a
        # dedicated eval WorkerSet every evaluation_interval iterations.
        return dict(metrics)


class BCConfig(MARWILConfig):
    """BC = MARWIL with beta=0 (reference: rllib/algorithms/bc/bc.py)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)
        self.beta = 0.0
        self.vf_coeff = 0.0


class BC(MARWIL):
    @classmethod
    def get_default_config(cls) -> BCConfig:
        return BCConfig(cls)
