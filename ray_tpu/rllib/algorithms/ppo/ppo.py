"""PPO — proximal policy optimization.

Reference: rllib/algorithms/ppo/ppo.py:394 (PPO, training_step :420) and
ppo_learner/ppo_torch_learner loss. The loss here is a pure-JAX function
jitted inside the Learner: clipped surrogate + value loss + entropy bonus,
minibatch SGD over each synchronous sample round, then weight broadcast to
the rollout workers through the object store (§3.6).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    ADVANTAGES,
    LOGPS,
    OBS,
    VALUE_TARGETS,
    VF_PREDS,
    SampleBatch,
)


def ppo_loss(params, batch, spec, cfg):
    """Clipped-surrogate PPO loss (reference: ppo_torch_learner.py loss)."""
    import jax.numpy as jnp

    from ray_tpu.rllib.core import rl_module

    logp, entropy, value = rl_module.action_logp_and_entropy(params, batch[OBS], batch[ACTIONS], spec)
    ratio = jnp.exp(logp - batch[LOGPS])
    adv = batch[ADVANTAGES]
    adv = (adv - adv.mean()) / (adv.std() + 1e-8)
    clip = cfg["clip_param"]
    surrogate = jnp.minimum(ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
    # Clipped value loss (reference vf_clip_param).
    vf_err = (value - batch[VALUE_TARGETS]) ** 2
    vf_clipped = batch[VF_PREDS] + jnp.clip(value - batch[VF_PREDS], -cfg["vf_clip_param"], cfg["vf_clip_param"])
    vf_err2 = (vf_clipped - batch[VALUE_TARGETS]) ** 2
    vf_loss = jnp.maximum(vf_err, vf_err2)
    policy_loss = -surrogate.mean()
    value_loss = vf_loss.mean()
    entropy_mean = entropy.mean()
    total = policy_loss + cfg["vf_loss_coeff"] * value_loss - cfg["entropy_coeff"] * entropy_mean
    kl = (batch[LOGPS] - logp).mean()
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": value_loss,
        "entropy": entropy_mean,
        "kl": kl,
    }


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.lr = 3e-4
        self.train_batch_size = 2000
        self.sgd_minibatch_size = 128
        self.num_sgd_iter = 8
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.grad_clip = 0.5

    def training(self, *, sgd_minibatch_size: Optional[int] = None, num_sgd_iter: Optional[int] = None,
                 clip_param: Optional[float] = None, vf_clip_param: Optional[float] = None,
                 vf_loss_coeff: Optional[float] = None, entropy_coeff: Optional[float] = None, **kwargs) -> "PPOConfig":
        super().training(**kwargs)
        if sgd_minibatch_size is not None:
            self.sgd_minibatch_size = sgd_minibatch_size
        if num_sgd_iter is not None:
            self.num_sgd_iter = num_sgd_iter
        if clip_param is not None:
            self.clip_param = clip_param
        if vf_clip_param is not None:
            self.vf_clip_param = vf_clip_param
        if vf_loss_coeff is not None:
            self.vf_loss_coeff = vf_loss_coeff
        if entropy_coeff is not None:
            self.entropy_coeff = entropy_coeff
        return self


class PPO(Algorithm):
    @classmethod
    def get_default_config(cls) -> PPOConfig:
        return PPOConfig(cls)

    def _build_learner_group(self, cfg: PPOConfig) -> LearnerGroup:
        return LearnerGroup(
            self.module_spec,
            ppo_loss,
            lr=cfg.lr,
            grad_clip=cfg.grad_clip,
            seed=cfg.seed,
            num_learners=cfg.num_learners,
            num_tpus_per_learner=cfg.num_tpus_per_learner,
        )

    def training_step(self) -> dict:
        cfg: PPOConfig = self._algo_config
        # 1. Synchronous parallel sampling (reference: rollout_ops.py:21).
        per_worker = max(1, cfg.train_batch_size // max(self.workers.num_workers, 1) // cfg.num_envs_per_worker)
        batches = self.workers.sample(per_worker)
        batch = SampleBatch.concat_samples(batches)
        self._timesteps_total += batch.count
        # 2. Minibatch SGD epochs on the learner group.
        loss_cfg = {
            "clip_param": cfg.clip_param,
            "vf_clip_param": cfg.vf_clip_param,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
        }
        metrics: dict = {}
        seed = np.random.randint(1 << 31)
        for epoch in range(cfg.num_sgd_iter):
            for mb in batch.minibatches(min(cfg.sgd_minibatch_size, batch.count), seed=seed + epoch):
                metrics = self.learner_group.update(mb, loss_cfg)
        # 3. Broadcast fresh weights to rollout workers.
        self.workers.sync_weights(self.learner_group.get_weights())
        metrics["num_env_steps_sampled_this_iter"] = batch.count
        return metrics
