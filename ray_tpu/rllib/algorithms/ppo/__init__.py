from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig, ppo_loss  # noqa: F401
