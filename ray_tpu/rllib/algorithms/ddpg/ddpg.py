"""DDPG / TD3 — deterministic policy gradient for continuous control.

Reference: rllib/algorithms/ddpg/ (ddpg.py, ddpg_torch_policy.py) and
rllib/algorithms/td3/td3.py (TD3 = DDPG with twin critics, delayed policy
updates, and target-policy smoothing). One jitted step updates critics and
(on delayed steps) the actor, plus Polyak-averaged targets — the TD3 switches
are static jit arguments so each variant compiles to its own XLA program.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.off_policy import OffPolicyTraining, floats
from ray_tpu.rllib.algorithms.sac.sac import _mlp_apply, _mlp_params, _true_transition
from ray_tpu.rllib.env.vector_env import VectorEnv
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    NEXT_OBS,
    OBS,
    REWARDS,
    SampleBatch,
)
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


def init_ddpg_params(rng, obs_dim, action_dim, hiddens, twin_q):
    import jax

    ka, k1, k2 = jax.random.split(rng, 3)
    params = {
        "actor": _mlp_params(ka, obs_dim, hiddens, action_dim),
        "q1": _mlp_params(k1, obs_dim + action_dim, hiddens, 1),
    }
    if twin_q:
        params["q2"] = _mlp_params(k2, obs_dim + action_dim, hiddens, 1)
    return params


class DDPGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPG)
        self.lr = 1e-3
        self.num_rollout_workers = 0
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.learning_starts = 1500
        self.tau = 5e-3
        self.rollout_steps_per_iter = 1000
        self.train_intensity = 1
        self.exploration_noise = 0.1  # gaussian action noise (in [-1,1] units)
        self.model_hiddens = (256, 256)
        # TD3 switches (reference: td3.py flips these on DDPGConfig):
        self.twin_q = False
        self.policy_delay = 1
        self.smooth_target_policy = False
        self.target_noise = 0.2
        self.target_noise_clip = 0.5

    def training(self, *, replay_buffer_capacity=None, learning_starts=None, tau=None,
                 rollout_steps_per_iter=None, train_intensity=None, exploration_noise=None,
                 twin_q=None, policy_delay=None, smooth_target_policy=None,
                 target_noise=None, target_noise_clip=None, **kwargs) -> "DDPGConfig":
        super().training(**kwargs)
        for name, val in (
            ("replay_buffer_capacity", replay_buffer_capacity),
            ("learning_starts", learning_starts),
            ("tau", tau),
            ("rollout_steps_per_iter", rollout_steps_per_iter),
            ("train_intensity", train_intensity),
            ("exploration_noise", exploration_noise),
            ("twin_q", twin_q),
            ("policy_delay", policy_delay),
            ("smooth_target_policy", smooth_target_policy),
            ("target_noise", target_noise),
            ("target_noise_clip", target_noise_clip),
        ):
            if val is not None:
                setattr(self, name, val)
        return self


class TD3Config(DDPGConfig):
    """TD3 defaults (reference: td3.py — twin critics, delayed actor,
    smoothed targets)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or TD3)
        self.twin_q = True
        self.policy_delay = 2
        self.smooth_target_policy = True


class DDPG(OffPolicyTraining, Algorithm):
    @classmethod
    def get_default_config(cls) -> DDPGConfig:
        return DDPGConfig(cls)

    def setup(self, config: dict) -> None:
        import gymnasium as gym
        import jax
        import optax

        self.cleanup()  # re-setup: close any previous env
        cfg: DDPGConfig = self._algo_config
        probe = gym.make(cfg.env) if isinstance(cfg.env, str) else cfg.env(dict(cfg.env_config))
        assert not isinstance(probe.action_space, gym.spaces.Discrete), "DDPG/TD3 need continuous actions"
        self.obs_dim = int(np.prod(probe.observation_space.shape))
        self.action_dim = int(np.prod(probe.action_space.shape))
        low = np.asarray(probe.action_space.low, np.float32)
        high = np.asarray(probe.action_space.high, np.float32)
        self._act_scale = (high - low) / 2.0
        self._act_offset = (high + low) / 2.0
        probe.close()
        self.env = VectorEnv(cfg.env, max(cfg.num_envs_per_worker, 1), cfg.env_config, 0, seed=cfg.seed)
        self.params = init_ddpg_params(
            jax.random.PRNGKey(cfg.seed), self.obs_dim, self.action_dim, cfg.model_hiddens, cfg.twin_q
        )
        self.target = jax.tree_util.tree_map(lambda x: x, self.params)
        self._critic_keys = tuple(k for k in ("q1", "q2") if k in self.params)
        # Separate optimizers: the delayed (TD3) actor update must skip BOTH
        # the gradient and the Adam moment update — a zeroed gradient through
        # a shared optimizer would still move the actor via momentum.
        self.actor_tx = optax.adam(cfg.lr)
        self.critic_tx = optax.adam(cfg.lr)
        self.opt_state = {
            "actor": self.actor_tx.init(self.params["actor"]),
            "critic": self.critic_tx.init({k: self.params[k] for k in self._critic_keys}),
        }
        self.buffer = ReplayBuffer(cfg.replay_buffer_capacity, seed=cfg.seed)
        self._rng = jax.random.PRNGKey(cfg.seed + 1)
        self._np_rng = np.random.default_rng(cfg.seed)
        self._timesteps_total = 0
        self._updates = 0
        self._episode_reward_window: list = []
        self._build_fns(cfg)

    def _build_fns(self, cfg: DDPGConfig):
        import jax
        import jax.numpy as jnp

        gamma, tau = cfg.gamma, cfg.tau
        twin_q, smooth = cfg.twin_q, cfg.smooth_target_policy
        noise, noise_clip = cfg.target_noise, cfg.target_noise_clip
        critic_keys = self._critic_keys
        actor_tx, critic_tx = self.actor_tx, self.critic_tx

        def q_val(q, obs, a):
            return _mlp_apply(q, jnp.concatenate([obs, a], -1))[:, 0]

        def critic_loss_fn(critic, target, batch, key):
            obs, next_obs = batch[OBS], batch[NEXT_OBS]
            next_a = jnp.tanh(_mlp_apply(target["actor"], next_obs))
            if smooth:
                eps = jnp.clip(jax.random.normal(key, next_a.shape) * noise, -noise_clip, noise_clip)
                next_a = jnp.clip(next_a + eps, -1.0, 1.0)
            tq = q_val(target["q1"], next_obs, next_a)
            if twin_q:
                tq = jnp.minimum(tq, q_val(target["q2"], next_obs, next_a))
            td_target = jax.lax.stop_gradient(
                batch[REWARDS] + gamma * (1 - batch[DONES]) * tq
            )
            q1 = q_val(critic["q1"], obs, batch[ACTIONS])
            loss = jnp.mean((q1 - td_target) ** 2)
            if twin_q:
                q2 = q_val(critic["q2"], obs, batch[ACTIONS])
                loss = loss + jnp.mean((q2 - td_target) ** 2)
            return loss, q1.mean()

        def actor_loss_fn(actor, critic, batch):
            obs = batch[OBS]
            a_pi = jnp.tanh(_mlp_apply(actor, obs))
            return -jnp.mean(q_val(critic["q1"], obs, a_pi))

        def train_step(params, target, opt_state, batch, key, update_actor):
            critic = {k: params[k] for k in critic_keys}
            (closs, mean_q), cgrads = jax.value_and_grad(critic_loss_fn, has_aux=True)(
                critic, target, batch, key
            )
            cupd, c_opt = critic_tx.update(cgrads, opt_state["critic"], critic)
            critic = jax.tree_util.tree_map(lambda p, u: p + u, critic, cupd)

            # Delayed policy + target updates (TD3): the skipped branch
            # leaves actor params, actor Adam moments, AND targets untouched.
            def do_actor(op):
                actor, a_opt, tgt = op
                aloss, agrads = jax.value_and_grad(actor_loss_fn)(actor, critic, batch)
                aupd, a_opt = actor_tx.update(agrads, a_opt, actor)
                actor = jax.tree_util.tree_map(lambda p, u: p + u, actor, aupd)
                new_params = {**critic, "actor": actor}
                tgt = jax.tree_util.tree_map(
                    lambda t, p: (1 - tau) * t + tau * p, tgt, new_params
                )
                return actor, a_opt, tgt, aloss

            def skip_actor(op):
                actor, a_opt, tgt = op
                return actor, a_opt, tgt, jnp.zeros(())

            actor, a_opt, target, aloss = jax.lax.cond(
                update_actor > 0, do_actor, skip_actor,
                (params["actor"], opt_state["actor"], target),
            )
            params = {**critic, "actor": actor}
            opt_state = {"actor": a_opt, "critic": c_opt}
            metrics = {"critic_loss": closs, "actor_loss": aloss, "mean_q": mean_q}
            return params, target, opt_state, metrics

        self._train_step = jax.jit(train_step)
        self._policy = jax.jit(lambda p, o: jnp.tanh(_mlp_apply(p["actor"], o)))

    def _env_action(self, a):
        return np.asarray(a) * self._act_scale + self._act_offset

    def training_step(self) -> dict:
        import jax
        import jax.numpy as jnp

        cfg: DDPGConfig = self._algo_config
        last_m = None
        for _ in range(cfg.rollout_steps_per_iter):
            obs = self.env.current_obs().astype(np.float32).reshape(self.env.num_envs, -1)
            if self._timesteps_total < cfg.learning_starts:
                a = self._np_rng.uniform(-1, 1, (self.env.num_envs, self.action_dim)).astype(np.float32)
            else:
                a = np.asarray(self._policy(self.params, jnp.asarray(obs)))
                a = np.clip(a + self._np_rng.normal(0, cfg.exploration_noise, a.shape), -1, 1).astype(np.float32)
            _, rewards, dones, infos = self.env.step(self._env_action(a))
            next_obs, terminateds = _true_transition(self.env, dones, infos)
            self.buffer.add(SampleBatch({
                OBS: obs, ACTIONS: a, REWARDS: rewards,
                DONES: terminateds, NEXT_OBS: next_obs,
            }))
            self._timesteps_total += self.env.num_envs
            if self._timesteps_total >= cfg.learning_starts:
                for _ in range(cfg.train_intensity):
                    batch = self.buffer.sample(cfg.train_batch_size)
                    jb = {k: jnp.asarray(v) for k, v in batch.items()}
                    self._rng, key = jax.random.split(self._rng)
                    self._updates += 1
                    update_actor = jnp.asarray(
                        1.0 if self._updates % max(cfg.policy_delay, 1) == 0 else 0.0, jnp.float32
                    )
                    self.params, self.target, self.opt_state, last_m = self._train_step(
                        self.params, self.target, self.opt_state, jb, key, update_actor
                    )
        stats_r, _ = self.env.pop_episode_stats()
        self._episode_reward_window += stats_r
        self._episode_reward_window = self._episode_reward_window[-100:]
        return floats(last_m) if last_m is not None else {}

    def compute_single_action(self, obs, explore: bool = False):
        import jax.numpy as jnp

        obs = np.asarray(obs, np.float32).reshape(1, -1)
        a = np.asarray(self._policy(self.params, jnp.asarray(obs)))[0]
        if explore:
            a = np.clip(a + self._np_rng.normal(0, self._algo_config.exploration_noise, a.shape), -1, 1)
        return self._env_action(a)


class TD3(DDPG):
    @classmethod
    def get_default_config(cls) -> TD3Config:
        return TD3Config(cls)
