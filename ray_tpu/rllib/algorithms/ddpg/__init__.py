from ray_tpu.rllib.algorithms.ddpg.ddpg import DDPG, TD3, DDPGConfig, TD3Config  # noqa: F401
