"""IMPALA — importance-weighted actor-learner with V-trace.

Reference: rllib/algorithms/impala/ (+ vtrace_tf/torch). Architecturally the
TPU shape differs from the reference's async queues: rollout workers sample
with whatever weights they last received (behavior policy), the learner
corrects the off-policyness with V-trace importance weights inside one jitted
loss, and weight broadcast happens once per iteration — decoupled
actors/learner without a Python-side queue, matching how an XLA-friendly
learner wants its input: one big batch, one compiled step.

V-trace (Espeholt et al. 2018):
    rho_t = min(rho_bar, pi(a|s)/mu(a|s));  c_t = min(c_bar, rho_t)
    delta_t = rho_t (r_t + gamma V(s_{t+1}) - V(s_t))
    vs_t = V(s_t) + delta_t + gamma c_t (vs_{t+1} - V(s_{t+1}))
    pg_adv_t = rho_t (r_t + gamma vs_{t+1} - V(s_t))
computed with a reverse lax.scan; episode ends reset the recursion via the
dones mask. Bootstrap values ride in the batch (NEXT_VF_PREDS).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    FRAG_CUT,
    LOGPS,
    NEXT_VF_PREDS,
    OBS,
    REWARDS,
    SampleBatch,
)


def impala_loss(params, batch, spec, cfg):
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.core import rl_module
    from ray_tpu.rllib.utils.vtrace import vtrace

    logp, entropy, values = rl_module.action_logp_and_entropy(
        params, batch[OBS], batch[ACTIONS], spec
    )
    nonterminal = 1.0 - batch[DONES].astype(values.dtype)
    # Fragment cuts: the batch is a concatenation of per-env rollout
    # fragments; the recursion must reset at each fragment's last row (the
    # bootstrap value there already carries the tail's contribution).
    cuts = batch[FRAG_CUT].astype(values.dtype)
    vs, pg_adv, rho = vtrace(
        jax.lax.stop_gradient(values), batch[NEXT_VF_PREDS], logp, batch[LOGPS],
        batch[REWARDS], nonterminal, cuts, cfg["gamma"], cfg["rho_bar"], cfg["c_bar"],
    )
    policy_loss = -jnp.mean(logp * pg_adv)
    vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
    entropy_mean = entropy.mean()
    total = policy_loss + cfg["vf_loss_coeff"] * vf_loss - cfg["entropy_coeff"] * entropy_mean
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy_mean,
        "mean_rho": rho.mean(),
    }


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self.lr = 5e-4
        self.train_batch_size = 2000
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0
        self.rho_bar = 1.0
        self.c_bar = 1.0
        self.minibatch_size = 512
        self.num_sgd_iter = 1
        # Broadcast weights every N iterations (staleness is what V-trace
        # corrects; >1 models the reference's async actors).
        self.broadcast_interval = 1
        # True async actors (reference: AsyncSampler/EnvRunnerV2): workers
        # keep stepping in a background thread while the learner updates;
        # the learner drains whatever fragments are ready. V-trace absorbs
        # the extra staleness this introduces.
        self.async_sampling = False

    def training(self, *, vf_loss_coeff: Optional[float] = None,
                 entropy_coeff: Optional[float] = None, rho_bar: Optional[float] = None,
                 c_bar: Optional[float] = None, minibatch_size: Optional[int] = None,
                 num_sgd_iter: Optional[int] = None, broadcast_interval: Optional[int] = None,
                 async_sampling: Optional[bool] = None,
                 **kwargs) -> "IMPALAConfig":
        super().training(**kwargs)
        for name, value in (
            ("vf_loss_coeff", vf_loss_coeff),
            ("entropy_coeff", entropy_coeff),
            ("rho_bar", rho_bar),
            ("c_bar", c_bar),
            ("minibatch_size", minibatch_size),
            ("num_sgd_iter", num_sgd_iter),
            ("broadcast_interval", broadcast_interval),
            ("async_sampling", async_sampling),
        ):
            if value is not None:
                setattr(self, name, value)
        return self


class IMPALA(Algorithm):
    @classmethod
    def get_default_config(cls) -> IMPALAConfig:
        return IMPALAConfig(cls)

    def _build_learner_group(self, cfg: IMPALAConfig) -> LearnerGroup:
        return LearnerGroup(
            self.module_spec,
            impala_loss,
            lr=cfg.lr,
            grad_clip=cfg.grad_clip,
            seed=cfg.seed,
            num_learners=cfg.num_learners,
            num_tpus_per_learner=cfg.num_tpus_per_learner,
            use_mesh=getattr(cfg, "learner_mesh", False),
            grad_sync=getattr(cfg, "grad_sync", "host"),
        )

    def training_step(self) -> dict:
        cfg: IMPALAConfig = self._algo_config
        batches = self._gather_rollouts(cfg.train_batch_size, cfg.async_sampling)
        if not batches:
            return {"async_waiting": 1.0}
        batch = SampleBatch.concat_samples(batches)
        self._timesteps_total += batch.count
        loss_cfg = {
            "gamma": cfg.gamma,
            "rho_bar": cfg.rho_bar,
            "c_bar": cfg.c_bar,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
        }
        # V-trace needs contiguous time order — update on the WHOLE batch
        # (no shuffled minibatches like PPO).
        metrics = {}
        for _ in range(cfg.num_sgd_iter):
            metrics = self.learner_group.update(batch, loss_cfg)
        if self.iteration % max(cfg.broadcast_interval, 1) == 0:
            # Podracer seam: one device-object group broadcast when the
            # config picked weight_sync="device_broadcast", per-worker host
            # pytree sync otherwise.
            self.sync_worker_weights()
        return dict(metrics)
