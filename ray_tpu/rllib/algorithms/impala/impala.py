"""IMPALA — importance-weighted actor-learner with V-trace.

Reference: rllib/algorithms/impala/ (+ vtrace_tf/torch). Architecturally the
TPU shape differs from the reference's async queues: rollout workers sample
with whatever weights they last received (behavior policy), the learner
corrects the off-policyness with V-trace importance weights inside one jitted
loss, and weight broadcast happens once per iteration — decoupled
actors/learner without a Python-side queue, matching how an XLA-friendly
learner wants its input: one big batch, one compiled step.

V-trace (Espeholt et al. 2018):
    rho_t = min(rho_bar, pi(a|s)/mu(a|s));  c_t = min(c_bar, rho_t)
    delta_t = rho_t (r_t + gamma V(s_{t+1}) - V(s_t))
    vs_t = V(s_t) + delta_t + gamma c_t (vs_{t+1} - V(s_{t+1}))
    pg_adv_t = rho_t (r_t + gamma vs_{t+1} - V(s_t))
computed with a reverse lax.scan; episode ends reset the recursion via the
dones mask. Bootstrap values ride in the batch (NEXT_VF_PREDS).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import LearnerGroup
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    FRAG_CUT,
    LOGPS,
    NEXT_VF_PREDS,
    OBS,
    REWARDS,
    SampleBatch,
)


def impala_loss(params, batch, spec, cfg):
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.core import rl_module

    logp, entropy, values = rl_module.action_logp_and_entropy(
        params, batch[OBS], batch[ACTIONS], spec
    )
    gamma = cfg["gamma"]
    rewards = batch[REWARDS]
    nonterminal = 1.0 - batch[DONES].astype(values.dtype)
    # Fragment cuts: the batch is a concatenation of per-env rollout
    # fragments; the recursion must reset at each fragment's last row (the
    # bootstrap value there already carries the tail's contribution).
    cuts = batch[FRAG_CUT].astype(values.dtype)
    carry_mask = nonterminal * (1.0 - cuts)
    # Behavior values for the recursion's V(s_{t+1}) (stop-grad bootstrap).
    next_values = batch[NEXT_VF_PREDS]
    rho = jnp.minimum(cfg["rho_bar"], jnp.exp(logp - batch[LOGPS]))
    rho = jax.lax.stop_gradient(rho)
    c = jnp.minimum(cfg["c_bar"], rho)
    v_sg = jax.lax.stop_gradient(values)
    deltas = rho * (rewards + gamma * next_values - v_sg)

    # Reverse scan for vs_t - V(s_t); episode ends / fragment cuts reset it.
    def back(carry, inp):
        delta_t, c_t, mask = inp
        acc = delta_t + gamma * c_t * mask * carry
        return acc, acc

    _, vs_minus_v_rev = jax.lax.scan(
        back,
        jnp.zeros((), values.dtype),
        (deltas[::-1], c[::-1], carry_mask[::-1]),
    )
    vs_minus_v = vs_minus_v_rev[::-1]
    vs = v_sg + vs_minus_v
    # vs_{t+1}: next row's vs inside a fragment; the bootstrap value at a
    # fragment cut; 0 past a terminal.
    vs_shift = jnp.concatenate([vs[1:], vs[-1:]])
    vs_next = jnp.where(cuts > 0, next_values, vs_shift) * nonterminal
    pg_adv = rho * (rewards + gamma * vs_next - v_sg)
    policy_loss = -jnp.mean(logp * pg_adv)
    vf_loss = 0.5 * jnp.mean((vs - values) ** 2)
    entropy_mean = entropy.mean()
    total = policy_loss + cfg["vf_loss_coeff"] * vf_loss - cfg["entropy_coeff"] * entropy_mean
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy_mean,
        "mean_rho": rho.mean(),
    }


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self.lr = 5e-4
        self.train_batch_size = 2000
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0
        self.rho_bar = 1.0
        self.c_bar = 1.0
        self.minibatch_size = 512
        self.num_sgd_iter = 1
        # Broadcast weights every N iterations (staleness is what V-trace
        # corrects; >1 models the reference's async actors).
        self.broadcast_interval = 1

    def training(self, *, vf_loss_coeff: Optional[float] = None,
                 entropy_coeff: Optional[float] = None, rho_bar: Optional[float] = None,
                 c_bar: Optional[float] = None, minibatch_size: Optional[int] = None,
                 num_sgd_iter: Optional[int] = None, broadcast_interval: Optional[int] = None,
                 **kwargs) -> "IMPALAConfig":
        super().training(**kwargs)
        for name, value in (
            ("vf_loss_coeff", vf_loss_coeff),
            ("entropy_coeff", entropy_coeff),
            ("rho_bar", rho_bar),
            ("c_bar", c_bar),
            ("minibatch_size", minibatch_size),
            ("num_sgd_iter", num_sgd_iter),
            ("broadcast_interval", broadcast_interval),
        ):
            if value is not None:
                setattr(self, name, value)
        return self


class IMPALA(Algorithm):
    @classmethod
    def get_default_config(cls) -> IMPALAConfig:
        return IMPALAConfig(cls)

    def _build_learner_group(self, cfg: IMPALAConfig) -> LearnerGroup:
        return LearnerGroup(
            self.module_spec,
            impala_loss,
            lr=cfg.lr,
            grad_clip=cfg.grad_clip,
            seed=cfg.seed,
            num_learners=cfg.num_learners,
            num_tpus_per_learner=cfg.num_tpus_per_learner,
        )

    def training_step(self) -> dict:
        cfg: IMPALAConfig = self._algo_config
        per_worker = max(
            1, cfg.train_batch_size // max(self.workers.num_workers, 1) // cfg.num_envs_per_worker
        )
        batches = self.workers.sample(per_worker)
        batch = SampleBatch.concat_samples(batches)
        self._timesteps_total += batch.count
        loss_cfg = {
            "gamma": cfg.gamma,
            "rho_bar": cfg.rho_bar,
            "c_bar": cfg.c_bar,
            "vf_loss_coeff": cfg.vf_loss_coeff,
            "entropy_coeff": cfg.entropy_coeff,
        }
        # V-trace needs contiguous time order — update on the WHOLE batch
        # (no shuffled minibatches like PPO).
        metrics = {}
        for _ in range(cfg.num_sgd_iter):
            metrics = self.learner_group.update(batch, loss_cfg)
        if self.iteration % max(cfg.broadcast_interval, 1) == 0:
            self.workers.sync_weights(self.learner_group.get_weights())
        return dict(metrics)
