from ray_tpu.rllib.algorithms.impala.impala import IMPALA, IMPALAConfig  # noqa: F401
