"""ray_tpu.rllib — reinforcement learning on the ray_tpu runtime.

Analog of the reference's RLlib (rllib/): CPU rollout-worker actors step
vectorized gymnasium envs; a pure-JAX Learner (single-process or an actor
gang with gradient allreduce over the collective plane) runs jitted SGD;
Algorithm extends the Tune Trainable so algorithms drop into tune.Tuner.
"""

from ray_tpu.rllib.algorithms.a2c import A2C, A2CConfig  # noqa: F401
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.bandits import BanditConfig, BanditLinTS, BanditLinUCB  # noqa: F401
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig  # noqa: F401
from ray_tpu.rllib.algorithms.ddpg import DDPG, TD3, DDPGConfig, TD3Config  # noqa: F401
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.algorithms.es import ES, ESConfig  # noqa: F401
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig  # noqa: F401
from ray_tpu.rllib.algorithms.marwil import MARWIL, BC, BCConfig, MARWILConfig  # noqa: F401
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch, SampleBatch  # noqa: F401
from ray_tpu.rllib.algorithms.apex_dqn import ApexDQN, ApexDQNConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.qmix import QMIX, QMIXConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.pg import PG, PGConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.dt import DT, DTConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.r2d2 import R2D2, R2D2Config  # noqa: F401,E402
from ray_tpu.rllib.algorithms.maddpg import MADDPG, MADDPGConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.ars import ARS, ARSConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.crr import CRR, CRRConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.slateq import SlateQ, SlateQConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.alpha_zero import AlphaZero, AlphaZeroConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3, DreamerV3Config  # noqa: F401,E402
from ray_tpu.rllib.algorithms.simple_q import SimpleQ, SimpleQConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.a3c import A3C, A3CConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.ddppo import DDPPO, DDPPOConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.apex_ddpg import ApexDDPG, ApexDDPGConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.maml import MAML, MAMLConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.mbmpo import MBMPO, MBMPOConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.alpha_star import AlphaStar, AlphaStarConfig  # noqa: F401,E402
from ray_tpu.rllib.algorithms.leela_chess_zero import LeelaChessZero, LeelaChessZeroConfig  # noqa: F401,E402
from ray_tpu.rllib.callbacks import DefaultCallbacks  # noqa: F401,E402
from ray_tpu.rllib.env.external_env import ExternalEnv, ExternalEnvRunner  # noqa: F401,E402
