"""`rllib train` CLI (reference: rllib/train.py + rllib/scripts.py).

    python -m ray_tpu.rllib train --run PPO --env CartPole-v1 \
        --stop-reward 150 --stop-iters 50 --config '{"lr": 3e-4}'
    python -m ray_tpu.rllib evaluate --run PPO --env CartPole-v1 \
        --checkpoint /path/to/ckpt --episodes 5
"""

from __future__ import annotations

import argparse
import json
import sys


# Names that don't round-trip through .upper() (hyphens normalize to _).
_ALGO_ALIASES = {"APEXDQN": "ApexDQN", "APEX_DQN": "ApexDQN"}


def _algo_class(name: str):
    import ray_tpu.rllib as rllib

    canonical = _ALGO_ALIASES.get(name.upper().replace("-", "_"), None)
    cls = (
        (getattr(rllib, canonical, None) if canonical else None)
        or getattr(rllib, name.upper(), None)
        or getattr(rllib, name, None)
    )
    if cls is None:
        raise SystemExit(f"unknown algorithm {name!r}; available: "
                         "PPO, APPO, IMPALA, A2C, DQN, ApexDQN, SAC, DDPG, TD3, "
                         "ES, PG, BC, MARWIL, CQL, QMIX, DT")
    return cls


def _build(args) -> tuple:
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init()
    cls = _algo_class(args.run)
    cfg = cls.get_default_config().environment(args.env)
    cfg.update_from_dict(json.loads(args.config) if args.config else {})
    algo = cfg.build()  # Trainable.__init__ runs setup()
    return algo, cfg


def run_tuned_example(path: str, max_iters_override: int | None = None) -> dict:
    """Run experiments from a tuned-example YAML (reference:
    rllib/tuned_examples/*.yaml driven by `rllib train file`). Returns
    {experiment_name: last_result}; raises if a stop criterion names a
    metric the algorithm never reports."""
    import yaml

    import ray_tpu

    with open(path) as f:
        experiments = yaml.safe_load(f)
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    out = {}
    for name, exp in experiments.items():
        cls = _algo_class(exp["run"])
        cfg = cls.get_default_config().environment(exp["env"])
        cfg.update_from_dict(exp.get("config") or {})
        stop = exp.get("stop") or {}
        max_iters = (
            max_iters_override
            if max_iters_override is not None
            else int(stop.get("training_iteration", 100))
        )
        algo = cfg.build()
        result: dict = {}
        try:
            for i in range(max_iters):
                result = algo.step()
                result["training_iteration"] = i + 1
                if i == 0:
                    # Typo'd stop keys would otherwise silently burn the full
                    # iteration budget.
                    missing = [k for k in stop if k not in result]
                    if missing:
                        raise ValueError(
                            f"experiment {name!r}: stop criteria {missing} name "
                            f"metrics the algorithm never reports "
                            f"(reported: {sorted(result)})"
                        )
                reward = result.get("episode_reward_mean", float("nan"))
                print(f"[{name}] iter {i + 1}: reward={reward:.2f}")
                if _stop_met(stop, result):
                    break
        finally:
            algo.cleanup()
        out[name] = result
    return out


def _stop_met(stop: dict, result: dict) -> bool:
    for key, bound in stop.items():
        v = result.get(key)
        if v is not None and v == v and v >= bound:  # v==v filters NaN
            return True
    return False


def cmd_train(args) -> int:
    if args.file:
        # Explicit --stop-iters bounds the YAML's own budget too.
        run_tuned_example(args.file, max_iters_override=args.stop_iters)
        return 0
    if not (args.run and args.env):
        raise SystemExit("train needs either -f <tuned.yaml> or --run + --env")
    algo, _ = _build(args)
    try:
        for i in range(100 if args.stop_iters is None else args.stop_iters):
            result = algo.step()
            reward = result.get("episode_reward_mean", float("nan"))
            print(f"iter {i + 1}: reward={reward:.2f} "
                  f"timesteps={result.get('timesteps_total', 0)}")
            if args.stop_reward is not None and reward >= args.stop_reward:
                print(f"stop-reward {args.stop_reward} reached")
                break
            if args.stop_timesteps and result.get("timesteps_total", 0) >= args.stop_timesteps:
                break
        if args.checkpoint_out:
            ckpt = algo.save_checkpoint()
            ckpt.to_directory(args.checkpoint_out)
            print(f"checkpoint written to {args.checkpoint_out}")
    finally:
        algo.cleanup()
    return 0


def cmd_evaluate(args) -> int:
    import gymnasium as gym
    import numpy as np

    algo, _ = _build(args)
    try:
        if args.checkpoint:
            from ray_tpu.air.checkpoint import Checkpoint

            algo.load_checkpoint(Checkpoint.from_directory(args.checkpoint))
        env = gym.make(args.env)
        rewards = []
        for ep in range(args.episodes):
            obs, _ = env.reset(seed=ep)
            total, done = 0.0, False
            while not done:
                action = algo.compute_single_action(obs, explore=False)
                obs, r, term, trunc, _ = env.step(action)
                total += float(r)
                done = term or trunc
            rewards.append(total)
            print(f"episode {ep + 1}: reward={total:.2f}")
        print(f"mean reward over {len(rewards)} episodes: {np.mean(rewards):.2f}")
        env.close()
    finally:
        algo.cleanup()
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="rllib", description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)
    for name in ("train", "evaluate"):
        p = sub.add_parser(name)
        p.add_argument("--run", required=(name == "evaluate"), default=None,
                       help="algorithm name, e.g. PPO")
        p.add_argument("--env", required=(name == "evaluate"), default=None,
                       help="gym env id or registered env")
        p.add_argument("--config", default=None, help="JSON config overrides")
    t = sub.choices["train"]
    t.add_argument("-f", "--file", default=None,
                   help="tuned-example YAML (rllib/tuned_examples/*.yaml)")
    t.add_argument("--stop-iters", type=int, default=None,
                   help="iteration cap (default: YAML stop / 100)")
    t.add_argument("--stop-reward", type=float, default=None)
    t.add_argument("--stop-timesteps", type=int, default=None)
    t.add_argument("--checkpoint-out", default=None)
    e = sub.choices["evaluate"]
    e.add_argument("--checkpoint", default=None)
    e.add_argument("--episodes", type=int, default=5)
    args = parser.parse_args(argv)
    return cmd_train(args) if args.command == "train" else cmd_evaluate(args)


if __name__ == "__main__":
    sys.exit(main())
