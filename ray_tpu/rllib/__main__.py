from ray_tpu.rllib.train import main

raise SystemExit(main())
