from ray_tpu.rllib.core.learner import Learner, LearnerGroup  # noqa: F401
from ray_tpu.rllib.core.rl_module import RLModuleSpec  # noqa: F401
