"""RLModule — the neural policy/value model, pure-JAX.

Reference: rllib/core/rl_module/rl_module.py (new-stack RLModule with
forward_exploration / forward_train). TPU-native design: params are a pytree,
forwards are pure functions jitted once; discrete policies use categorical
logits, continuous use tanh-squashed diagonal gaussians. The same module
serves rollout actors (CPU forward) and learners (accelerator update) — only
the params move.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class RLModuleSpec:
    obs_dim: int
    action_dim: int
    discrete: bool
    hiddens: Tuple[int, ...] = (64, 64)
    activation: str = "tanh"
    free_log_std: bool = True  # continuous: state-independent log_std
    # Image observations: HWC shape + conv torso spec [(out_ch, kernel,
    # stride), ...] (reference: ModelCatalog VisionNet filters,
    # rllib/models/catalog.py). Empty = flat MLP.
    obs_shape: Tuple[int, ...] = ()
    conv_filters: Tuple[Tuple[int, int, int], ...] = ()

    @staticmethod
    def from_spaces(observation_space, action_space, hiddens=(64, 64),
                    conv_filters=None) -> "RLModuleSpec":
        import gymnasium as gym

        obs_dim = int(np.prod(observation_space.shape))
        shape = tuple(observation_space.shape)
        convs: Tuple = ()
        if len(shape) == 3:
            convs = tuple(conv_filters) if conv_filters else default_conv_filters(shape)
        elif conv_filters:
            raise ValueError("conv_filters requires a 3D (H, W, C) observation space")
        if isinstance(action_space, gym.spaces.Discrete):
            return RLModuleSpec(obs_dim, int(action_space.n), True, tuple(hiddens),
                                obs_shape=shape if convs else (), conv_filters=convs)
        return RLModuleSpec(obs_dim, int(np.prod(action_space.shape)), False, tuple(hiddens),
                            obs_shape=shape if convs else (), conv_filters=convs)


def default_conv_filters(shape: Tuple[int, ...]) -> Tuple[Tuple[int, int, int], ...]:
    """Default conv stacks by input size (reference: catalog.py
    _get_filter_config — 84x84 Atari stack, smaller stacks otherwise).
    Tiny spatial dims get NO convs (flat MLP) rather than a stack that
    collapses to zero — a (4,4,1) gridworld must keep training."""
    h = min(shape[0], shape[1])
    if h >= 84:
        return ((16, 8, 4), (32, 4, 2), (64, 3, 1))
    if h >= 42:
        return ((16, 4, 2), (32, 4, 2), (64, 3, 1))
    if h >= 7:
        return ((16, 3, 2), (32, 3, 2))
    if h >= 3:
        return ((16, 3, 1),)
    return ()


def _act(name: str):
    import jax.numpy as jnp
    import jax

    return {"tanh": jnp.tanh, "relu": jax.nn.relu, "swish": jax.nn.swish}[name]


def _conv_out_dim(spec: RLModuleSpec) -> int:
    h, w, _ = spec.obs_shape
    c = spec.obs_shape[2]
    for out_ch, k, s in spec.conv_filters:
        h = (h - k) // s + 1
        w = (w - k) // s + 1
        c = out_ch
    if h <= 0 or w <= 0:
        raise ValueError(
            f"conv_filters {spec.conv_filters} collapse a {spec.obs_shape} input"
        )
    return h * w * c


def init_params(rng, spec: RLModuleSpec):
    """Orthogonal-init torso (conv stack for image obs, reference VisionNet;
    MLP otherwise, reference FCNet) + policy and value heads, functional."""
    import jax
    import jax.numpy as jnp

    def dense(key, din, dout, scale):
        w = jax.nn.initializers.orthogonal(scale)(key, (din, dout), jnp.float32)
        return {"w": w, "b": jnp.zeros((dout,), jnp.float32)}

    def conv(key, cin, cout, k):
        w = jax.nn.initializers.orthogonal(np.sqrt(2))(key, (k, k, cin, cout), jnp.float32)
        return {"w": w, "b": jnp.zeros((cout,), jnp.float32)}

    n_conv = len(spec.conv_filters)
    keys = jax.random.split(rng, (len(spec.hiddens) + n_conv) * 2 + 3)
    params = {"pi": [], "vf": []}
    if n_conv:
        params["pi_conv"], params["vf_conv"] = [], []
        cin = spec.obs_shape[2]
        for i, (cout, k, _s) in enumerate(spec.conv_filters):
            params["pi_conv"].append(conv(keys[2 * (len(spec.hiddens) + i)], cin, cout, k))
            params["vf_conv"].append(conv(keys[2 * (len(spec.hiddens) + i) + 1], cin, cout, k))
            cin = cout
        din = _conv_out_dim(spec)
    else:
        din = spec.obs_dim
    for i, h in enumerate(spec.hiddens):
        params["pi"].append(dense(keys[2 * i], din, h, np.sqrt(2)))
        params["vf"].append(dense(keys[2 * i + 1], din, h, np.sqrt(2)))
        din = h
    params["pi_out"] = dense(keys[-3], din, spec.action_dim, 0.01)
    params["vf_out"] = dense(keys[-2], din, 1, 1.0)
    if not spec.discrete and spec.free_log_std:
        params["log_std"] = jnp.zeros((spec.action_dim,), jnp.float32)
    return params


def _mlp(layers, x, act):
    import jax.numpy as jnp

    for layer in layers:
        x = act(x @ layer["w"] + layer["b"])
    return x


def _conv_torso(layers, x, spec: RLModuleSpec, act):
    """NHWC conv stack -> flat features (VALID padding, per-filter stride)."""
    import jax

    x = x.reshape((x.shape[0],) + spec.obs_shape)
    for layer, (_cout, _k, s) in zip(layers, spec.conv_filters):
        x = jax.lax.conv_general_dilated(
            x, layer["w"], window_strides=(s, s), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        x = act(x + layer["b"])
    return x.reshape(x.shape[0], -1)


def forward(params, obs, spec: RLModuleSpec):
    """Returns (pi_out, value). pi_out: logits (discrete) or mean (cont)."""
    import jax.numpy as jnp

    act = _act(spec.activation)
    if spec.conv_filters:
        hpi = _conv_torso(params["pi_conv"], obs, spec, act)
        hvf = _conv_torso(params["vf_conv"], obs, spec, act)
    else:
        hpi = hvf = obs.reshape(obs.shape[0], -1)
    hpi = _mlp(params["pi"], hpi, act)
    hvf = _mlp(params["vf"], hvf, act)
    pi_out = hpi @ params["pi_out"]["w"] + params["pi_out"]["b"]
    value = (hvf @ params["vf_out"]["w"] + params["vf_out"]["b"])[:, 0]
    return pi_out, value


def sample_actions(params, obs, rng, spec: RLModuleSpec, explore: bool = True):
    """Sample actions + logp + value in one jittable forward."""
    import jax
    import jax.numpy as jnp

    pi_out, value = forward(params, obs, spec)
    if spec.discrete:
        if explore:
            actions = jax.random.categorical(rng, pi_out, axis=-1)
        else:
            actions = jnp.argmax(pi_out, axis=-1)
        logp = jax.nn.log_softmax(pi_out)[jnp.arange(pi_out.shape[0]), actions]
        return actions, logp, value
    log_std = params.get("log_std", jnp.zeros(pi_out.shape[-1]))
    if explore:
        noise = jax.random.normal(rng, pi_out.shape)
        actions = pi_out + noise * jnp.exp(log_std)
    else:
        actions = pi_out
    logp = gaussian_logp(actions, pi_out, log_std)
    return actions, logp, value


def gaussian_logp(x, mean, log_std):
    import jax.numpy as jnp

    return -0.5 * jnp.sum(
        ((x - mean) / jnp.exp(log_std)) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi), axis=-1
    )


def action_logp_and_entropy(params, obs, actions, spec: RLModuleSpec):
    """Recompute logp/entropy/value for stored actions (training pass)."""
    import jax
    import jax.numpy as jnp

    pi_out, value = forward(params, obs, spec)
    if spec.discrete:
        logits = jax.nn.log_softmax(pi_out)
        logp = logits[jnp.arange(pi_out.shape[0]), actions.astype(jnp.int32)]
        entropy = -jnp.sum(jnp.exp(logits) * logits, axis=-1)
        return logp, entropy, value
    log_std = params.get("log_std", jnp.zeros(pi_out.shape[-1]))
    logp = gaussian_logp(actions, pi_out, log_std)
    entropy = jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1) * jnp.ones(pi_out.shape[0])
    return logp, entropy, value
