"""Learner + LearnerGroup — jitted SGD on rollout batches.

Reference: rllib/core/learner/learner.py (Learner, compute_loss :900) and
learner_group.py:61 (LearnerGroup of remote learner actors, DDP-wrapped in
torch). TPU-native redesign: the loss is a pure function; the update is one
jitted step (grad + optax apply). Data parallelism over learners is an
allreduce of gradients through the collective plane (XLA psum over ICI when
the group backend is "tpu"), not parameter-server averaging.

Podracer weight sync (arXiv:2104.06272, wired by Algorithm when
``weight_sync="device_broadcast"``): the learner packs its params pytree
into ONE flat device vector (:func:`pack_weights`), keeps it device-resident
as a device object, and ``device_object.broadcast`` fans it to the sampler
fleet with one group operation — samplers rebuild the pytree against their
own canonical template (:func:`unpack_weights`), so only leaf VALUES cross
the wire, never tree structure.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.policy.sample_batch import SampleBatch

logger = logging.getLogger(__name__)


def pack_weights(params):
    """Flatten a params pytree into ONE contiguous float32 vector (canonical
    jax tree-flatten order). The single-array form is what lets a whole
    model ride the device-object plane as ONE descriptor + ONE group
    broadcast per sync."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(params)
    if not leaves:
        return jnp.zeros((0,), jnp.float32)
    return jnp.concatenate([jnp.ravel(leaf).astype(jnp.float32) for leaf in leaves])


def unpack_weights(flat, template):
    """Rebuild a params pytree from :func:`pack_weights` output. ``template``
    supplies structure, shapes, and dtypes — both sides derive it from the
    SAME module spec (rl_module.init_params is deterministic in structure),
    so no treedef ever crosses the wire."""
    import jax
    import jax.numpy as jnp

    flat = jnp.asarray(flat)
    leaves, treedef = jax.tree_util.tree_flatten(template)
    sizes = [int(np.prod(leaf.shape)) if leaf.shape else 1 for leaf in leaves]
    if sum(sizes) != flat.shape[0]:
        raise ValueError(
            f"packed weight vector has {flat.shape[0]} elements, template "
            f"expects {sum(sizes)} — learner and sampler disagree on the module spec"
        )
    out = []
    offset = 0
    for leaf, n in zip(leaves, sizes):
        out.append(flat[offset : offset + n].reshape(leaf.shape).astype(leaf.dtype))
        offset += n
    return jax.tree_util.tree_unflatten(treedef, out)


class Learner:
    """Single-process learner: params + optimizer + jitted update.

    ``use_mesh=True`` builds the Podracer learner mesh: a 1-axis
    ``jax.sharding.Mesh`` over every local device with params REPLICATED
    and the batch sharded along its leading (time/row) axis — the pjit
    data-parallel shape (arXiv:2104.06272's Anakin cell on one host). On a
    single-device process the mesh degenerates to trivial sharding, so the
    same code path is exercised everywhere and the multi-chip layout is a
    deployment detail, not a code change."""

    def __init__(self, spec, loss_fn: Callable, lr: float = 5e-5, grad_clip: Optional[float] = None, seed: int = 0, optimizer: str = "adam", use_mesh: bool = False):
        import jax
        import optax

        from ray_tpu.rllib.core import rl_module

        self.spec = spec
        self.loss_fn = loss_fn
        self.params = rl_module.init_params(jax.random.PRNGKey(seed), spec)
        self.mesh = None
        if use_mesh:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            self.mesh = Mesh(np.array(jax.local_devices()), ("data",))
            # Params live replicated on the mesh so every data shard reads
            # them locally during the sharded forward/backward.
            replicated = NamedSharding(self.mesh, P())
            self.params = jax.device_put(self.params, replicated)
        chain = []
        if grad_clip:
            chain.append(optax.clip_by_global_norm(grad_clip))
        chain.append(optax.adam(lr) if optimizer == "adam" else optax.sgd(lr))
        self.tx = optax.chain(*chain)
        self.opt_state = self.tx.init(self.params)
        self._update = None

    def _build_update(self):
        import jax
        import optax

        loss_fn = self.loss_fn
        spec = self.spec
        tx = self.tx
        mesh = self.mesh

        def update(params, opt_state, batch, loss_cfg):
            if mesh is not None and mesh.size > 1:
                from jax.sharding import NamedSharding, PartitionSpec as P

                # Constrain the batch onto the data axis (rows divisible by
                # the mesh stay sharded; ragged tails fall back to
                # replication rather than a compile error).
                batch = {
                    k: (
                        jax.lax.with_sharding_constraint(
                            v, NamedSharding(mesh, P("data"))
                        )
                        if getattr(v, "ndim", 0) >= 1 and v.shape[0] % mesh.size == 0
                        else v
                    )
                    for k, v in batch.items()
                }
            (loss, metrics), grads = jax.value_and_grad(
                lambda p: loss_fn(p, batch, spec, loss_cfg), has_aux=True
            )(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            metrics = dict(metrics)
            metrics["total_loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, metrics

        self._update = jax.jit(update, static_argnames=())

    def update(self, batch: SampleBatch, loss_cfg: dict) -> dict:
        import jax.numpy as jnp

        if self._update is None:
            self._build_update()
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, metrics = self._update(self.params, self.opt_state, jb, loss_cfg)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        import jax

        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights):
        import jax.numpy as jnp
        import jax

        self.params = jax.tree_util.tree_map(jnp.asarray, weights)


class _RemoteLearner:
    """Learner living in its own actor; grads allreduced through the
    collective plane before the optimizer step (reference: DDP learners)."""

    def __init__(self, spec, loss_fn, lr, grad_clip, seed, rank, world_size, group_name, use_mesh=False, grad_sync="host"):
        self.rank = rank
        self.world_size = world_size
        self.group_name = group_name
        self.grad_sync = grad_sync
        self._grad_step = 0
        self.learner = Learner(spec, loss_fn, lr, grad_clip, seed, use_mesh=use_mesh)

    def init_collective(self, world, backend):
        from ray_tpu.util import collective

        collective.init_collective_group(
            world_size=self.world_size, rank=self.rank, backend=backend, group_name=self.group_name
        )
        return True

    def init_weight_collective(self, world_size, rank, backend, group_name):
        """Join the learner↔sampler WEIGHT group (distinct from the grad
        allreduce group above): this actor is the holder rank the device-
        object broadcast fans out from."""
        from ray_tpu.util import collective

        collective.init_collective_group(
            world_size=world_size, rank=rank, backend=backend, group_name=group_name
        )
        return True

    def group_roster(self, group_name):
        """Roster snapshot of a group this actor belongs to (elastic
        membership introspection)."""
        from ray_tpu.util import collective

        return collective.roster(group_name)

    def pack_weights(self):
        """One flat device vector of the current params. On a
        tensor_transport actor this returns as a DEVICE OBJECT: the vector
        stays resident here (this learner is the holder) and only the
        descriptor travels."""
        return pack_weights(self.learner.params)

    def update(self, batch: SampleBatch, loss_cfg: dict) -> dict:
        import jax

        if self.world_size > 1:
            # Data-parallel grad sync: compute grads, allreduce, then step.
            from ray_tpu.util import collective

            loss_fn, spec = self.learner.loss_fn, self.learner.spec

            def total_loss(p, jb):
                return loss_fn(p, jb, spec, loss_cfg)

            import jax.numpy as jnp

            jb = {k: jnp.asarray(v) for k, v in batch.items()}
            (loss, metrics), grads = jax.value_and_grad(total_loss, has_aux=True)(self.learner.params, jb)
            grad_allreduce_tree = 0.0
            if self.grad_sync == "device_allreduce":
                # Relay-tree path: the whole grad pytree rides as ONE flat
                # vector through the tree allreduce (reduce up the binomial
                # tree with chunk-wise combine at relay hops, broadcast back
                # down) instead of a per-leaf ring round-trip.
                from ray_tpu.util.collective.p2p import COLL

                group = collective.get_group(self.group_name)
                self._grad_step += 1
                before = COLL.reduce_sends
                packed = pack_weights(grads) / self.world_size
                avg = group.allreduce_payload(packed, tag=f"grad{self._grad_step}")
                grads = unpack_weights(avg, grads)
                grad_allreduce_tree = float(COLL.reduce_sends - before)
            else:
                flat, treedef = jax.tree_util.tree_flatten(grads)
                reduced = [collective.allreduce(np.asarray(g) / self.world_size, group_name=self.group_name) for g in flat]
                grads = jax.tree_util.tree_unflatten(treedef, [jnp.asarray(g) for g in reduced])
            updates, self.learner.opt_state = self.learner.tx.update(grads, self.learner.opt_state, self.learner.params)
            self.learner.params = jax.tree_util.tree_map(lambda p, u: p + u, self.learner.params, updates)
            out = {k: float(v) for k, v in dict(metrics).items()}
            out["total_loss"] = float(loss)
            if self.grad_sync == "device_allreduce":
                out["grad_allreduce_tree"] = grad_allreduce_tree
            return out
        return self.learner.update(batch, loss_cfg)

    def get_weights(self):
        return self.learner.get_weights()

    def set_weights(self, weights):
        self.learner.set_weights(weights)
        return True


class LearnerGroup:
    """Local learner or a gang of learner actors (reference:
    learner_group.py:61). num_learners=0 -> in-process (the common
    single-host case); >0 -> remote actors with grad allreduce."""

    def __init__(self, spec, loss_fn, *, lr=5e-5, grad_clip=None, seed=0,
                 num_learners: int = 0, num_tpus_per_learner: float = 0,
                 collective_backend: str = "cpu", group_name: str = "rllib_learners",
                 use_mesh: bool = False, grad_sync: str = "host"):
        self._local: Optional[Learner] = None
        self._actors: list = []
        if num_learners <= 0:
            self._local = Learner(spec, loss_fn, lr, grad_clip, seed, use_mesh=use_mesh)
        else:
            # tensor_transport: a pack_weights() return stays device-resident
            # on the learner actor (the holder) — the Podracer weight-sync
            # path broadcasts its descriptor instead of shipping the vector
            # through the host store.
            cls = ray_tpu.remote(
                num_cpus=1, num_tpus=num_tpus_per_learner or None,
                tensor_transport="collective",
            )(_RemoteLearner)
            self._actors = [
                cls.remote(spec, loss_fn, lr, grad_clip, seed, rank, num_learners, group_name, use_mesh, grad_sync)
                for rank in range(num_learners)
            ]
            if num_learners > 1:
                ray_tpu.get([a.init_collective.remote(num_learners, collective_backend) for a in self._actors])

    def update(self, batch: SampleBatch, loss_cfg: dict) -> dict:
        if self._local is not None:
            return self._local.update(batch, loss_cfg)
        n = len(self._actors)
        bounds = self._shard_bounds(batch, n)
        refs = [
            a.update.remote(batch.slice(lo, hi), loss_cfg)
            for a, (lo, hi) in zip(self._actors, bounds)
        ]
        all_metrics = ray_tpu.get(refs)
        return {k: float(np.mean([m[k] for m in all_metrics])) for k in all_metrics[0]}

    @staticmethod
    def _shard_bounds(batch: SampleBatch, n: int) -> list:
        """Split points for n shards. Sequence-structured batches (FRAG_CUT
        present, e.g. IMPALA's V-trace input) must split only at fragment
        boundaries, or time recursions would leak across shards."""
        from ray_tpu.rllib.policy.sample_batch import FRAG_CUT

        total = batch.count
        if total < n:
            # Fewer rows than learners: every rank gets the whole batch —
            # identical grads allreduce to themselves, and every rank MUST
            # participate (an empty shard would NaN, a missing one would
            # hang the collective).
            return [(0, total)] * n
        if FRAG_CUT not in batch:
            return [(i * total // n, (i + 1) * total // n) for i in range(n)]
        cut_ends = [i + 1 for i, c in enumerate(np.asarray(batch[FRAG_CUT])) if c]
        if not cut_ends or cut_ends[-1] != total:
            cut_ends.append(total)
        bounds = []
        lo = 0
        for i in range(n):
            if i == n - 1:
                bounds.append((lo, total))
                break
            target = (i + 1) * total // n
            # Nearest fragment boundary at or after the even split point.
            hi = next((c for c in cut_ends if c >= max(target, lo + 1)), total)
            bounds.append((lo, hi))
            lo = hi
        if any(hi <= lo for lo, hi in bounds):
            # Fewer fragments than learners (or shuffled minibatches whose
            # cut rows landed badly): empty shards would feed NaN-producing
            # zero-length updates — fall back to a balanced row split.
            return [(i * total // n, (i + 1) * total // n) for i in range(n)]
        return bounds

    def stop(self):
        """Kill remote learner actors (they hold TPU/CPU reservations)."""
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors = []

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._actors[0].get_weights.remote())

    def set_weights(self, weights):
        if self._local is not None:
            self._local.set_weights(weights)
        else:
            ray_tpu.get([a.set_weights.remote(weights) for a in self._actors])

    # ---- Podracer weight sync (device-object broadcast path) ----

    def init_weight_collective(self, world_size: int, rank: int, backend: str, group_name: str):
        """Join the learner↔sampler weight group as the HOLDER rank. Local
        mode: the driver process itself is the holder (it owns the params),
        so the group is initialized right here. The join lands this rank in
        the group's GCS roster; `world_size` is only the INITIAL gang size
        — every later broadcast snapshots the roster, so the sampler fleet
        can grow, shrink, or churn under the holder without re-forming the
        group."""
        if self._local is not None:
            from ray_tpu.util import collective

            collective.init_collective_group(
                world_size=world_size, rank=rank, backend=backend, group_name=group_name
            )
            return True
        return ray_tpu.get(
            self._actors[0].init_weight_collective.remote(world_size, rank, backend, group_name)
        )

    def weight_group_roster(self, group_name: str):
        """Membership snapshot of the weight group as the holder would see
        it at the next broadcast: ``{"epoch", "ranks", "world_size"}``, or
        None before the first roster publish. Drives the resize oracle —
        after a grow/shrink the roster must list exactly the live ranks."""
        from ray_tpu.util import collective

        if self._local is not None:
            return collective.roster(group_name)
        return ray_tpu.get(self._actors[0].group_roster.remote(group_name))

    def pack_weight_ref(self):
        """ObjectRef of the packed flat weight vector as a DEVICE OBJECT —
        the one descriptor a sync broadcasts. Local mode puts from the
        driver (the driver is the holder); remote mode returns the learner
        actor's device-resident result."""
        if self._local is not None:
            return ray_tpu.put(pack_weights(self._local.params), tensor_transport="collective")
        return self._actors[0].pack_weights.remote()
