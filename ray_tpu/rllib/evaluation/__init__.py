from ray_tpu.rllib.evaluation.rollout_worker import RolloutWorker, WorkerSet  # noqa: F401
