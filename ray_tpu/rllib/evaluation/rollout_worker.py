"""RolloutWorker + WorkerSet — CPU actors stepping vectorized envs.

Reference: rllib/evaluation/rollout_worker.py:166 (RolloutWorker, sample
:666), worker_set.py:80 (WorkerSet), utils/actor_manager.py:189
(FaultTolerantActorManager — lost workers are respawned and the round
continues with the survivors). The async mode (start_async/get_async) is
the analog of AsyncSampler/EnvRunnerV2 (rllib/evaluation/sampler.py:309,
env_runner_v2.py:199): a background thread keeps stepping the vector env
into a bounded fragment queue while the learner consumes and updates —
V-trace/IS corrections in IMPALA/APPO absorb the policy staleness this
introduces.
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
from typing import Callable, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core import rl_module
from ray_tpu.rllib.env.vector_env import make_vector_env
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    EPS_ID,
    LOGPS,
    NEXT_OBS,
    OBS,
    REWARDS,
    VF_PREDS,
    SampleBatch,
    compute_gae,
)

logger = logging.getLogger(__name__)


class RolloutWorker:
    """One actor: vector env + policy forward, producing GAE-postprocessed
    SampleBatches."""

    def __init__(self, env_spec, spec, worker_index: int = 0, num_envs: int = 1,
                 env_config: Optional[dict] = None, gamma: float = 0.99,
                 lambda_: float = 0.95, seed: int = 0, observation_filter: Optional[str] = None,
                 agent_connectors=None, clip_actions: bool = True,
                 action_connectors=None):
        import jax

        jax.config.update("jax_platforms", "cpu")  # rollouts stay off-chip
        # make_vector_env flattens MultiAgentEnvs into per-agent slots
        # (shared-policy training, reference's default policy mapping).
        self.env = make_vector_env(env_spec, num_envs, env_config, worker_index, seed=seed + worker_index * 1000)
        # Connector pipelines (reference: rllib/connectors/connector.py:320 +
        # agent/pipeline.py:21): agent connectors shape observations before
        # the policy forward; action connectors shape sampled actions before
        # env.step. The stateful observation filter is a PIPELINE STAGE (not
        # ad hoc worker code): it runs first, user stages after. Box spaces
        # get automatic action clipping appended (the policy's gaussian
        # sample is unbounded).
        from ray_tpu.rllib.connectors import (
            ActionConnectorPipeline,
            AgentConnectorPipeline,
            ClipActions,
            MeanStdFilter,
        )

        self._filter_stage = None
        self._filter_delta = None
        agent_stages = list(agent_connectors or [])
        if observation_filter in ("MeanStdFilter", "mean_std"):
            self._filter_stage = MeanStdFilter()
            # Local-only accumulation since the last sync; the driver merges
            # DELTAS (reference: FilterManager flushes buffers), because
            # re-merging full states would double-count shared history.
            self._filter_delta = MeanStdFilter()
            agent_stages.insert(0, self._filter_stage)
        self.agent_connectors = AgentConnectorPipeline(agent_stages)
        action_stages = list(action_connectors or [])
        space = getattr(self.env, "action_space", None)
        if clip_actions and space is not None and hasattr(space, "low"):
            action_stages.append(ClipActions(space.low, space.high))
        self.action_connectors = ActionConnectorPipeline(action_stages)
        # Async env-runner state (started on demand by start_async).
        self._async_thread: Optional[threading.Thread] = None
        self._async_stop: Optional[threading.Event] = None
        self._async_q: Optional[_queue.Queue] = None
        # Guards the stateful obs filter: in async mode the runner thread
        # updates it mid-sample while filter-sync RPCs (pop_filter_delta /
        # set_filter_state) run on the actor main thread.
        self._filter_lock = threading.Lock()
        # Slot multiplier (n_agents for multi-agent envs): sample() divides
        # requested steps by it so the row count an algorithm asked for via
        # train_batch_size stays agent-count-invariant.
        self._rows_per_step = max(1, self.env.num_envs // max(num_envs, 1))
        self.spec = spec
        self.gamma = gamma
        self.lambda_ = lambda_
        self._rng = jax.random.PRNGKey(seed + worker_index)
        self._params = None
        self._sample_fn = jax.jit(
            lambda p, o, r, explore: rl_module.sample_actions(p, o, r, self.spec, explore),
            static_argnames=("explore",),
        )

    def set_weights(self, weights) -> bool:
        import jax.numpy as jnp
        import jax

        self._params = jax.tree_util.tree_map(jnp.asarray, weights)
        return True

    # ---- Podracer weight sync (device-object broadcast path) ----

    def init_collective(self, world_size: int, rank: int, backend: str = "cpu",
                        group_name: str = "rllib_weights") -> bool:
        """Join the learner↔sampler weight group: a device-object broadcast
        from the learner then lands in this process's direct mailbox and
        set_packed_weights' arg resolution takes it with zero pull RPCs."""
        from ray_tpu.util import collective

        collective.init_collective_group(
            world_size=world_size, rank=rank, backend=backend, group_name=group_name
        )
        return True

    def set_packed_weights(self, packed) -> bool:
        """Weight sync from ONE flat vector (learner.pack_weights). The
        descriptor arg resolves before this runs — group members take the
        broadcast payload from their inbox. A respawned replacement is
        re-registered into the group by WorkerSet._replace_worker (roster
        epoch bump), so at most its FIRST post-respawn sync rides the pull
        path; every later one is back on the broadcast plane. The pytree is
        rebuilt against this worker's own canonical template, so only
        values crossed the wire."""
        import jax

        from ray_tpu.rllib.core import rl_module
        from ray_tpu.rllib.core.learner import unpack_weights

        template = self._params
        if template is None:
            template = rl_module.init_params(jax.random.PRNGKey(0), self.spec)
        self._params = unpack_weights(packed, template)
        return True

    def _shape_obs(self, obs: np.ndarray, explore: bool, peek: bool = False) -> np.ndarray:
        """One pipeline call: while exploring, stateful stages update
        (__call__); otherwise transform-only, so learned statistics never
        absorb eval observations (temporal stages like FrameStack advance
        either way — see AgentConnector.transform). ``peek=True`` freezes
        ALL state, temporal buffers included — for bootstrap forwards over
        an obs the stepping loop will shape again (a transform there would
        double-push the fragment-boundary frame)."""
        if not self.agent_connectors.connectors:
            return obs
        with self._filter_lock:
            if peek:
                return self.agent_connectors.peek(obs)
            if self._filter_stage is not None and explore:
                self._filter_delta(obs)  # delta stats only; result unused
            return (
                self.agent_connectors(obs)
                if explore
                else self.agent_connectors.transform(obs)
            )

    def sample(self, num_steps: int, explore: bool = True) -> SampleBatch:
        """Collect `num_steps` per sub-env; GAE over each env's fragment."""
        import jax

        assert self._params is not None, "set_weights before sample"
        num_steps = max(1, num_steps // self._rows_per_step)
        n_envs = self.env.num_envs
        cols: dict = {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, LOGPS, VF_PREDS, EPS_ID)}
        for _ in range(num_steps):
            obs = self._shape_obs(self.env.current_obs().astype(np.float32), explore)
            self._rng, key = jax.random.split(self._rng)
            actions, logp, value = self._sample_fn(self._params, obs, key, explore)
            actions_np = np.asarray(actions)
            env_actions = (
                self.action_connectors(actions_np)
                if self.action_connectors.connectors
                else actions_np
            )
            cols[OBS].append(obs)
            cols[EPS_ID].append(self.env.eps_ids())
            _, rewards, dones, _ = self.env.step(env_actions)
            # Episode boundaries reach temporal connectors (frame stacks
            # re-seed finished slots before the next episode's first obs).
            # Under the filter lock: on_episode_done mutates connector state
            # (temporal buffers), and in async mode set_connector_state /
            # set_filter_state swap that state from the actor main thread
            # mid-sample — ALL pipeline mutation serializes on one lock.
            if np.any(dones):
                with self._filter_lock:
                    self.agent_connectors.on_episode_done(dones)
            # The TRAINING batch keeps the raw sampled action: logp was
            # computed for it, and training on the clipped action would
            # bias the policy gradient at the clip boundary (reference
            # clips only on the env side for the same reason).
            cols[ACTIONS].append(actions_np)
            cols[REWARDS].append(rewards)
            cols[DONES].append(dones)
            cols[LOGPS].append(np.asarray(logp))
            cols[VF_PREDS].append(np.asarray(value))
        # Bootstrap value for the final obs of each env (peek: the next
        # fragment shapes this same obs as its first step).
        self._rng, key = jax.random.split(self._rng)
        final_obs = self._shape_obs(self.env.current_obs().astype(np.float32), False, peek=True)
        _, _, last_values = self._sample_fn(self._params, final_obs, key, False)
        last_values = np.asarray(last_values)
        # [T, N, ...] -> per-env fragments -> GAE -> concat.
        frags = []
        for e in range(n_envs):
            frag = SampleBatch({k: np.stack([step[e] for step in v]) for k, v in cols.items()})
            frag = compute_gae(frag, last_values[e], self.gamma, self.lambda_)
            frags.append(frag)
        batch = SampleBatch.concat_samples(frags)
        return batch

    # -- async env-runner (reference: AsyncSampler sampler.py:309 /
    # EnvRunnerV2 env_runner_v2.py:199) ---------------------------------
    def start_async(self, fragment_len: int, queue_size: int = 4) -> bool:
        """Launch the background fragment producer: steps the vector env
        continuously with the latest weights, queueing GAE-postprocessed
        fragments. The bounded queue gives backpressure — when the learner
        lags, the producer blocks instead of growing stale sample memory."""
        if self._async_thread is not None:
            if self._async_thread.is_alive():
                return True
            # Previous runner finished dying after a timed-out stop_async;
            # safe to replace it now.
            self._async_thread = None
        q = _queue.Queue(maxsize=queue_size)
        stop = threading.Event()
        self._async_q = q
        self._async_stop = stop
        self._async_thread = threading.Thread(
            target=self._async_loop, args=(fragment_len, q, stop), daemon=True,
            name="env-runner",
        )
        self._async_thread.start()
        return True

    def _async_loop(self, fragment_len: int, q: "_queue.Queue", stop: threading.Event):
        # q/stop are captured locals: stop_async may null the instance
        # attributes while this thread is mid-fragment.
        import time as _time

        while not stop.is_set():
            if self._params is None:
                _time.sleep(0.02)
                continue
            try:
                batch = self.sample(fragment_len, explore=True)
            except Exception:
                logger.exception("async env-runner sampling failed")
                _time.sleep(0.5)
                continue
            rewards, lens = self.env.pop_episode_stats()
            item = {"batch": batch, "episode_rewards": rewards, "episode_lens": lens}
            # Blocking put = backpressure; wake periodically to honor stop.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.25)
                    break
                except _queue.Full:
                    continue

    def get_async(self, max_items: int = 8, timeout: float = 10.0) -> list:
        """Drain ready fragments (blocking for at least one, up to timeout).
        Returns [] when the runner isn't started or nothing arrived."""
        if self._async_q is None:
            return []
        items = []
        try:
            items.append(self._async_q.get(timeout=timeout))
        except _queue.Empty:
            return []
        while len(items) < max_items:
            try:
                items.append(self._async_q.get_nowait())
            except _queue.Empty:
                break
        return items

    def async_queue_depth(self) -> int:
        return -1 if self._async_q is None else self._async_q.qsize()

    def stop_async(self) -> bool:
        if self._async_thread is None:
            return False
        self._async_stop.set()
        # Unblock a producer stuck on a full queue.
        try:
            while True:
                self._async_q.get_nowait()
        except _queue.Empty:
            pass
        self._async_thread.join(timeout=10)
        if self._async_thread.is_alive():
            # Mid-fragment on a slow env: leave the fields in place so
            # start_async won't spawn a SECOND runner over the same env —
            # the stop event is set, so this one exits after its fragment.
            logger.warning("async env-runner still draining; restart deferred")
            return False
        self._async_thread = None
        self._async_q = None
        return True

    def episode_stats(self) -> dict:
        rewards, lens = self.env.pop_episode_stats()
        return {"episode_rewards": rewards, "episode_lens": lens}

    def get_filter_state(self):
        return self._filter_stage.get_state() if self._filter_stage is not None else None

    def pop_filter_delta(self):
        """Return accumulation since the last sync and reset it."""
        if self._filter_delta is None:
            return None
        from ray_tpu.rllib.connectors import MeanStdFilter

        with self._filter_lock:
            state = self._filter_delta.get_state()
            self._filter_delta = MeanStdFilter()
        return state

    def set_filter_state(self, state) -> bool:
        if self._filter_stage is not None and state is not None:
            with self._filter_lock:
                self._filter_stage.set_state(state)
        return True

    def get_connector_state(self) -> dict:
        """Serialized agent+action pipelines (structure AND state) — what a
        checkpoint carries so a restored worker resumes filters/stacks."""
        with self._filter_lock:
            return {
                "agent": self.agent_connectors.serialize(),
                "action": self.action_connectors.serialize(),
            }

    def set_connector_state(self, blobs: dict) -> bool:
        from ray_tpu.rllib.connectors import ConnectorPipeline, MeanStdFilter

        with self._filter_lock:
            self.agent_connectors = ConnectorPipeline.deserialize(blobs["agent"])
            self.action_connectors = ConnectorPipeline.deserialize(blobs["action"])
            self._filter_stage = next(
                (c for c in self.agent_connectors.connectors if isinstance(c, MeanStdFilter)),
                None,
            )
            # Keep the delta accumulator consistent with the restored
            # pipeline: a worker built filterless gains one, a worker whose
            # restored pipeline dropped the filter must stop accumulating.
            self._filter_delta = MeanStdFilter() if self._filter_stage is not None else None
        return True

    def rejoin_collective(self, group_name: str = "rllib_weights") -> bool:
        """Live-member rejoin: re-assert this worker's roster membership in
        a group it already initialized (a transient stall can get a live
        member evicted by a broadcast that timed out on it). False when the
        group is unknown here — the caller must init, not rejoin."""
        from ray_tpu.util import collective as col

        return col.rejoin_group(group_name) is not None

    def get_coll_stats(self) -> dict:
        """This process's collective counters (p2p.COLL). Lets the driver
        and tests assert a sampler stayed on the broadcast plane —
        bcast_recvs climbing while host_sync_fallbacks stays flat."""
        from ray_tpu.util.collective.p2p import COLL

        return {k: getattr(COLL, k) for k in COLL.__slots__}

    def ping(self) -> bool:
        return True

    def stop(self):
        self.env.close()
        return True


class WorkerSet:
    """Fault-tolerant gang of rollout workers (reference: worker_set.py:80 +
    FaultTolerantActorManager)."""

    def __init__(self, env_spec, spec, *, num_workers: int, num_envs_per_worker: int = 1,
                 env_config: Optional[dict] = None, gamma: float = 0.99, lambda_: float = 0.95,
                 seed: int = 0, num_cpus_per_worker: float = 1,
                 observation_filter: Optional[str] = None, agent_connectors=None,
                 clip_actions: bool = True, recreate_failed_workers: bool = True,
                 max_worker_restarts: int = 100, action_connectors=None):
        self.observation_filter = observation_filter
        # Failure policy (reference: AlgorithmConfig.fault_tolerance()):
        # respawn dead workers while the restart budget lasts; afterwards
        # (or with recreate_failed_workers=False) degrade to the survivors.
        self.recreate_failed_workers = recreate_failed_workers
        self.max_worker_restarts = max_worker_restarts
        self._restarts = 0
        self._filter_base = None  # merged filter history (driver-side)
        self._make_worker = lambda idx: ray_tpu.remote(num_cpus=num_cpus_per_worker)(RolloutWorker).remote(
            env_spec, spec, idx, num_envs_per_worker, env_config, gamma, lambda_, seed,
            observation_filter, agent_connectors, clip_actions, action_connectors
        )
        self._workers = [self._make_worker(i + 1) for i in range(num_workers)]
        self._indices = list(range(1, num_workers + 1))
        # Elastic weight-group state (set by init_weight_group): the
        # (group_name, backend, base_rank) triple plus a positional list of
        # each worker's rank in the group. _replace_worker re-registers a
        # respawned replacement at its OLD rank; resize() joins/evicts
        # ranks at the tail. None = no weight group (host sync mode).
        self._weight_group: Optional[tuple] = None
        self._group_ranks: List[int] = []
        # Async env-runner mode (None = sync). Set by start_async; replaced
        # workers are restarted into the same mode.
        self._async_fragment_len: Optional[int] = None
        self._pending_stats = {"episode_rewards": [], "episode_lens": []}

    @property
    def num_workers(self) -> int:
        return len(self._workers)

    def _replace_worker(self, pos: int):
        """Respawn the worker at list position `pos`. The old actor MUST be
        killed first: a merely-slow actor that we abandoned would keep its
        CPU reservation forever and starve future creations. When the
        restart budget is spent (or recreation is disabled), the dead
        worker is dropped instead and the set degrades — unless it was the
        LAST one, where degrading means silently training on nothing."""
        old = self._workers[pos]
        try:
            ray_tpu.kill(old)
        except Exception:
            pass
        if (not self.recreate_failed_workers or self._restarts >= self.max_worker_restarts):
            if len(self._workers) <= 1:
                raise RuntimeError(
                    "last rollout worker died and the restart budget is spent "
                    f"(restarts={self._restarts}, recreate={self.recreate_failed_workers})"
                )
            logger.warning(
                "dropping dead rollout worker %d (restarts=%d, budget=%d)",
                self._indices[pos], self._restarts, self.max_worker_restarts,
            )
            del self._workers[pos]
            del self._indices[pos]
            self._evict_rank(pos)
            return None
        self._restarts += 1
        self._workers[pos] = self._make_worker(self._indices[pos])
        self._reregister_worker(pos)
        if self._async_fragment_len is not None:
            # Restarted into async mode; its runner idles until the next
            # weight broadcast delivers params.
            try:
                self._workers[pos].start_async.remote(self._async_fragment_len)
            except Exception:
                pass
        return self._workers[pos]

    def _reregister_worker(self, pos: int):
        """Put a respawned replacement back into the learner↔sampler weight
        group AT ITS OLD RANK. roster_join bumps the roster epoch, so the
        learner's next broadcast snapshots a membership that includes the
        replacement — the first post-respawn sync is already back on the
        device_broadcast fast path (the degradation used to be permanent:
        replacements stayed outside the static group forever). Best-effort:
        a failed re-register leaves the worker on the pull path, which is
        correct, just slower."""
        if self._weight_group is None or pos >= len(self._group_ranks):
            return
        group_name, backend, _ = self._weight_group
        rank = self._group_ranks[pos]
        world = max(self._group_ranks) + 1
        try:
            ray_tpu.get(
                self._workers[pos].init_collective.remote(world, rank, backend, group_name),
                timeout=60,
            )
        except Exception:
            logger.warning(
                "re-register of respawned worker into weight group %r at rank "
                "%d failed; it stays on the pull path", group_name, rank,
            )

    def _evict_rank(self, pos: int):
        """Driver-side LEAVE for a worker dropped from the set: a killed
        actor can't unregister itself, so the driver evicts its rank from
        the roster (epoch bump) — the learner's next broadcast stops
        addressing it instead of timing out against a ghost."""
        if self._weight_group is None or pos >= len(self._group_ranks):
            return
        group_name = self._weight_group[0]
        rank = self._group_ranks.pop(pos)
        try:
            from ray_tpu.util import collective as col

            col.evict_member(group_name, rank, reason="death")
        except Exception:
            logger.debug("roster eviction of rank %d from %r failed", rank, group_name, exc_info=True)

    def _replace_by_identity(self, w):
        """_replace_worker keyed by actor handle (safe across drops that
        shift positional indices)."""
        try:
            return self._replace_worker(self._workers.index(w))
        except ValueError:
            return None

    def sync_weights(self, weights):
        self._sync_weights_via(lambda w: w.set_weights.remote(weights))

    def sync_packed_weights(self, ref):
        """Podracer path: every worker sets weights from the SAME packed
        device-object ref (the learner already group-broadcast the payload,
        so group members resolve from their inbox). Membership is elastic:
        a respawned replacement was re-registered at its old rank, so it
        resolves from the broadcast plane too — at most the one sync that
        raced the respawn rides the pull path."""
        self._sync_weights_via(lambda w: w.set_packed_weights.remote(ref))

    def _sync_weights_via(self, submit):
        """Shared fault-tolerant sync loop: a dead worker is respawned and
        fed the same weights before the round completes."""
        for w in list(self._workers):
            try:
                ray_tpu.get(submit(w), timeout=120)
            except Exception:
                # Position by identity: a drop earlier in this loop shifts
                # positional indices.
                try:
                    pos = self._workers.index(w)
                except ValueError:
                    continue
                logger.warning("sync_weights: worker %d dead; respawning", self._indices[pos])
                replacement = self._replace_worker(pos)
                if replacement is not None:
                    ray_tpu.get(submit(replacement), timeout=120)

    def init_weight_group(self, group_name: str, *, backend: str = "cpu",
                          world_size: int | None = None, base_rank: int = 1):
        """Gang-join every rollout worker into the learner↔sampler weight
        group at ranks base_rank..base_rank+N-1 (rank 0 is the learner/
        holder). Membership is ELASTIC: each join lands in the group's
        GCS roster, `_replace_worker` re-registers respawned replacements
        at their old rank, and `resize` grows/shrinks the roster at the
        tail — every broadcast snapshots the roster at send time, so the
        fleet never falls off the fast path permanently."""
        world = world_size or (base_rank + len(self._workers))
        ray_tpu.get(
            [
                w.init_collective.remote(world, base_rank + i, backend, group_name)
                for i, w in enumerate(self._workers)
            ],
            timeout=120,
        )
        self._weight_group = (group_name, backend, base_rank)
        self._group_ranks = [base_rank + i for i in range(len(self._workers))]
        return world

    def resize(self, num_workers: int) -> int:
        """Grow or shrink the sampler fleet mid-training WITHOUT leaving
        the broadcast fast path. Growing spawns workers at fresh worker
        indices and gang-joins them into the weight group at fresh ranks
        (each join bumps the roster epoch; the learner's next broadcast
        snapshots the bigger membership). Shrinking stops + kills the tail
        workers and evicts their ranks from the roster driver-side (a
        killed actor can't leave for itself). New workers have no params
        until the next weight sync — callers should sync immediately after
        a grow. Returns the new worker count."""
        target = int(num_workers)
        if target < 1:
            raise ValueError("resize needs at least one rollout worker")
        if target == len(self._workers):
            return target
        if target < len(self._workers):
            victims = self._workers[target:]
            dropped_ranks = self._group_ranks[target:] if self._weight_group else []
            for w in victims:
                try:
                    w.stop.remote()
                except Exception:
                    pass
            for w in victims:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
            del self._workers[target:]
            del self._indices[target:]
            if self._weight_group is not None:
                del self._group_ranks[target:]
                group_name = self._weight_group[0]
                from ray_tpu.util import collective as col

                for rank in dropped_ranks:
                    try:
                        col.evict_member(group_name, rank, reason="leave")
                    except Exception:
                        logger.debug(
                            "shrink: roster eviction of rank %d from %r failed",
                            rank, group_name, exc_info=True,
                        )
            logger.info("worker set shrunk to %d samplers", target)
            return target
        # Grow: fresh worker indices (never reuse — env seeds derive from
        # them) and, when a weight group exists, fresh tail ranks.
        next_idx = max(self._indices, default=0) + 1
        new_positions = []
        while len(self._workers) < target:
            self._workers.append(self._make_worker(next_idx))
            self._indices.append(next_idx)
            new_positions.append(len(self._workers) - 1)
            next_idx += 1
        if self._weight_group is not None:
            group_name, backend, base_rank = self._weight_group
            next_rank = max(self._group_ranks, default=base_rank - 1) + 1
            new_ranks = list(range(next_rank, next_rank + len(new_positions)))
            self._group_ranks.extend(new_ranks)
            world = max(self._group_ranks) + 1
            refs = [
                self._workers[pos].init_collective.remote(world, rank, backend, group_name)
                for pos, rank in zip(new_positions, new_ranks)
            ]
            for rank, ref in zip(new_ranks, refs):
                try:
                    ray_tpu.get(ref, timeout=120)
                except Exception:
                    logger.warning(
                        "grow: weight-group join at rank %d failed; that "
                        "worker rides the pull path until re-registered", rank,
                    )
        if self._async_fragment_len is not None:
            for pos in new_positions:
                try:
                    self._workers[pos].start_async.remote(self._async_fragment_len)
                except Exception:
                    pass
        logger.info("worker set grown to %d samplers", target)
        return target

    def ensure_registered(self):
        """Self-healing pre-sync check: a transient stall can get a LIVE
        worker evicted from the weight-group roster (a broadcast that
        timed out on it batch-evicts all failed ranks). One cheap roster
        read; any live worker whose rank fell off re-joins before the next
        broadcast, so a stall costs at most one pull-path sync instead of
        a permanent fast-path exit."""
        if self._weight_group is None:
            return
        from ray_tpu.util import collective as col

        group_name, _, _ = self._weight_group
        try:
            snap = col.roster(group_name)
        except Exception:
            return
        if snap is None:
            return
        listed = set(snap["ranks"])
        for pos, rank in enumerate(self._group_ranks):
            if rank in listed or pos >= len(self._workers):
                continue
            logger.warning(
                "live worker at rank %d fell off weight-group %r roster; re-joining",
                rank, group_name,
            )
            try:
                ok = ray_tpu.get(
                    self._workers[pos].rejoin_collective.remote(group_name), timeout=60
                )
            except Exception:
                ok = False
            if not ok:
                # The worker never held the group locally (e.g. a respawn
                # whose re-register failed) — full init at its old rank.
                self._reregister_worker(pos)

    def coll_stats(self) -> List[Optional[dict]]:
        """Per-worker collective counters (None for unreachable workers) —
        the elastic-membership observability hook tests assert against."""
        refs = [w.get_coll_stats.remote() for w in self._workers]
        out: List[Optional[dict]] = []
        for ref in refs:
            try:
                out.append(ray_tpu.get(ref, timeout=30))
            except Exception:
                out.append(None)
        return out

    def sample(self, steps_per_worker: int, explore: bool = True) -> List[SampleBatch]:
        """Synchronous parallel sampling with fault tolerance: a worker that
        dies mid-round is replaced and the round proceeds without it
        (reference: execution/rollout_ops.py:21 + actor_manager probe).
        ``explore=False`` samples greedily (evaluation rollouts)."""
        refs: dict = {}
        results: List[SampleBatch] = []
        dead: list = []
        for i, w in zip(self._indices, self._workers):
            try:
                refs[w.sample.remote(steps_per_worker, explore)] = (i, w)
            except Exception:
                logger.warning("rollout worker %d unreachable at submit; respawning", i)
                dead.append((i, w))
        for ref, (idx, w) in refs.items():
            try:
                results.append(ray_tpu.get(ref, timeout=300))
            except Exception:
                logger.warning("rollout worker %d failed; respawning", idx)
                dead.append((idx, w))
        for idx, w in dead:
            self._replace_by_identity(w)
        return results

    # -- async env-runner orchestration (reference: AsyncSampler) --------
    @property
    def is_async(self) -> bool:
        return self._async_fragment_len is not None

    def start_async(self, fragment_len: int):
        """Flip every worker into continuous background sampling."""
        self._async_fragment_len = fragment_len
        refs = [w.start_async.remote(fragment_len) for w in self._workers]
        for ref in refs:
            try:
                ray_tpu.get(ref, timeout=60)
            except Exception:
                pass  # dead worker surfaces at the next gather

    def sample_async(self, min_steps: int, timeout: float = 60.0) -> List[SampleBatch]:
        """Gather fragments from the background runners until ``min_steps``
        rows arrive (or timeout). Episode stats ride with the fragments —
        they are accumulated here and served by episode_stats(), because in
        async mode the env belongs to the runner thread."""
        import time as _time

        assert self._async_fragment_len is not None, "start_async first"
        batches: List[SampleBatch] = []
        total = 0
        deadline = _time.monotonic() + timeout
        while total < min_steps and _time.monotonic() < deadline:
            refs = {}
            for w in list(self._workers):
                try:
                    refs[w.get_async.remote(timeout=5.0)] = w
                except Exception:
                    self._replace_by_identity(w)
            for ref, w in refs.items():
                try:
                    items = ray_tpu.get(ref, timeout=120)
                except Exception:
                    logger.warning("async rollout worker failed; respawning")
                    self._replace_by_identity(w)
                    continue
                for item in items:
                    batches.append(item["batch"])
                    total += len(item["batch"])
                    self._pending_stats["episode_rewards"] += item["episode_rewards"]
                    self._pending_stats["episode_lens"] += item["episode_lens"]
        return batches

    def stop_async(self):
        if self._async_fragment_len is None:
            return
        self._async_fragment_len = None
        for w in self._workers:
            try:
                w.stop_async.remote()
            except Exception:
                pass

    def sync_filters(self):
        """Merge per-worker filter DELTAS into the shared base and
        redistribute (reference: FilterManager.synchronize — deltas, not full
        states, so shared history is never double-counted)."""
        if not self.observation_filter or not self._workers:
            return
        from ray_tpu.rllib.connectors import MeanStdFilter

        # Fan out, then gather (a slow worker must not serialize the sync).
        pop_refs = [w.pop_filter_delta.remote() for w in self._workers]
        deltas = []
        for ref in pop_refs:
            try:
                deltas.append(ray_tpu.get(ref, timeout=60))
            except Exception:
                pass
        merger = MeanStdFilter()
        states = [self._filter_base] + [d for d in deltas if d]
        merger.merge_states([st for st in states if st])
        self._filter_base = merger.get_state()
        set_refs = [w.set_filter_state.remote(self._filter_base) for w in self._workers]
        for ref in set_refs:
            try:
                ray_tpu.get(ref, timeout=60)
            except Exception:
                pass

    def episode_stats(self) -> dict:
        if self._async_fragment_len is not None:
            # Async mode: the env belongs to the runner thread, so stats
            # travel WITH the fragments and were accumulated by
            # sample_async — polling the workers would race the runner.
            stats, self._pending_stats = self._pending_stats, {
                "episode_rewards": [], "episode_lens": [],
            }
            return stats
        stats = {"episode_rewards": [], "episode_lens": []}
        for ref in [w.episode_stats.remote() for w in self._workers]:
            try:
                s = ray_tpu.get(ref, timeout=60)
                stats["episode_rewards"] += s["episode_rewards"]
                stats["episode_lens"] += s["episode_lens"]
            except Exception:
                pass
        return stats

    def stop(self):
        for w in self._workers:
            try:
                w.stop.remote()
            except Exception:
                pass
        for w in self._workers:
            try:
                ray_tpu.kill(w)  # release the actor's CPU hold
            except Exception:
                pass
        self._workers = []
