"""RolloutWorker + WorkerSet — CPU actors stepping vectorized envs.

Reference: rllib/evaluation/rollout_worker.py:166 (RolloutWorker, sample
:666), worker_set.py:80 (WorkerSet), utils/actor_manager.py:189
(FaultTolerantActorManager — lost workers are respawned and the round
continues with the survivors).
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core import rl_module
from ray_tpu.rllib.env.vector_env import make_vector_env
from ray_tpu.rllib.policy.sample_batch import (
    ACTIONS,
    DONES,
    EPS_ID,
    LOGPS,
    NEXT_OBS,
    OBS,
    REWARDS,
    VF_PREDS,
    SampleBatch,
    compute_gae,
)

logger = logging.getLogger(__name__)


class RolloutWorker:
    """One actor: vector env + policy forward, producing GAE-postprocessed
    SampleBatches."""

    def __init__(self, env_spec, spec, worker_index: int = 0, num_envs: int = 1,
                 env_config: Optional[dict] = None, gamma: float = 0.99,
                 lambda_: float = 0.95, seed: int = 0, observation_filter: Optional[str] = None):
        import jax

        jax.config.update("jax_platforms", "cpu")  # rollouts stay off-chip
        # make_vector_env flattens MultiAgentEnvs into per-agent slots
        # (shared-policy training, reference's default policy mapping).
        self.env = make_vector_env(env_spec, num_envs, env_config, worker_index, seed=seed + worker_index * 1000)
        # Slot multiplier (n_agents for multi-agent envs): sample() divides
        # requested steps by it so the row count an algorithm asked for via
        # train_batch_size stays agent-count-invariant.
        self._rows_per_step = max(1, self.env.num_envs // max(num_envs, 1))
        self.spec = spec
        self.obs_filter = None
        self._filter_delta = None
        if observation_filter in ("MeanStdFilter", "mean_std"):
            from ray_tpu.rllib.connectors import MeanStdFilter

            self.obs_filter = MeanStdFilter()
            # Local-only accumulation since the last sync; the driver merges
            # DELTAS (reference: FilterManager flushes buffers), because
            # re-merging full states would double-count shared history.
            self._filter_delta = MeanStdFilter()
        self.gamma = gamma
        self.lambda_ = lambda_
        self._rng = jax.random.PRNGKey(seed + worker_index)
        self._params = None
        self._sample_fn = jax.jit(
            lambda p, o, r, explore: rl_module.sample_actions(p, o, r, self.spec, explore),
            static_argnames=("explore",),
        )

    def set_weights(self, weights) -> bool:
        import jax.numpy as jnp
        import jax

        self._params = jax.tree_util.tree_map(jnp.asarray, weights)
        return True

    def sample(self, num_steps: int, explore: bool = True) -> SampleBatch:
        """Collect `num_steps` per sub-env; GAE over each env's fragment."""
        import jax

        assert self._params is not None, "set_weights before sample"
        num_steps = max(1, num_steps // self._rows_per_step)
        n_envs = self.env.num_envs
        cols: dict = {k: [] for k in (OBS, ACTIONS, REWARDS, DONES, LOGPS, VF_PREDS, EPS_ID)}
        for _ in range(num_steps):
            obs = self.env.current_obs().astype(np.float32)
            if self.obs_filter is not None:
                if explore:
                    self._filter_delta(obs)  # stats only; result unused
                    obs = self.obs_filter(obs)
                else:
                    obs = self.obs_filter.transform(obs)
            self._rng, key = jax.random.split(self._rng)
            actions, logp, value = self._sample_fn(self._params, obs, key, explore)
            actions_np = np.asarray(actions)
            cols[OBS].append(obs)
            cols[EPS_ID].append(self.env.eps_ids())
            _, rewards, dones, _ = self.env.step(actions_np)
            cols[ACTIONS].append(actions_np)
            cols[REWARDS].append(rewards)
            cols[DONES].append(dones)
            cols[LOGPS].append(np.asarray(logp))
            cols[VF_PREDS].append(np.asarray(value))
        # Bootstrap value for the final obs of each env.
        self._rng, key = jax.random.split(self._rng)
        final_obs = self.env.current_obs().astype(np.float32)
        if self.obs_filter is not None:
            final_obs = self.obs_filter.transform(final_obs)
        _, _, last_values = self._sample_fn(self._params, final_obs, key, False)
        last_values = np.asarray(last_values)
        # [T, N, ...] -> per-env fragments -> GAE -> concat.
        frags = []
        for e in range(n_envs):
            frag = SampleBatch({k: np.stack([step[e] for step in v]) for k, v in cols.items()})
            frag = compute_gae(frag, last_values[e], self.gamma, self.lambda_)
            frags.append(frag)
        batch = SampleBatch.concat_samples(frags)
        return batch

    def episode_stats(self) -> dict:
        rewards, lens = self.env.pop_episode_stats()
        return {"episode_rewards": rewards, "episode_lens": lens}

    def get_filter_state(self):
        return self.obs_filter.get_state() if self.obs_filter is not None else None

    def pop_filter_delta(self):
        """Return accumulation since the last sync and reset it."""
        if self._filter_delta is None:
            return None
        from ray_tpu.rllib.connectors import MeanStdFilter

        state = self._filter_delta.get_state()
        self._filter_delta = MeanStdFilter()
        return state

    def set_filter_state(self, state) -> bool:
        if self.obs_filter is not None and state is not None:
            self.obs_filter.set_state(state)
        return True

    def ping(self) -> bool:
        return True

    def stop(self):
        self.env.close()
        return True


class WorkerSet:
    """Fault-tolerant gang of rollout workers (reference: worker_set.py:80 +
    FaultTolerantActorManager)."""

    def __init__(self, env_spec, spec, *, num_workers: int, num_envs_per_worker: int = 1,
                 env_config: Optional[dict] = None, gamma: float = 0.99, lambda_: float = 0.95,
                 seed: int = 0, num_cpus_per_worker: float = 1,
                 observation_filter: Optional[str] = None):
        self.observation_filter = observation_filter
        self._filter_base = None  # merged filter history (driver-side)
        self._make_worker = lambda idx: ray_tpu.remote(num_cpus=num_cpus_per_worker)(RolloutWorker).remote(
            env_spec, spec, idx, num_envs_per_worker, env_config, gamma, lambda_, seed,
            observation_filter
        )
        self._workers = [self._make_worker(i + 1) for i in range(num_workers)]
        self._indices = list(range(1, num_workers + 1))

    @property
    def num_workers(self) -> int:
        return len(self._workers)

    def _replace_worker(self, pos: int):
        """Respawn the worker at list position `pos`. The old actor MUST be
        killed first: a merely-slow actor that we abandoned would keep its
        CPU reservation forever and starve future creations."""
        old = self._workers[pos]
        try:
            ray_tpu.kill(old)
        except Exception:
            pass
        self._workers[pos] = self._make_worker(self._indices[pos])
        return self._workers[pos]

    def sync_weights(self, weights):
        for i, w in enumerate(list(self._workers)):
            try:
                ray_tpu.get(w.set_weights.remote(weights), timeout=120)
            except Exception:
                logger.warning("sync_weights: worker %d dead; respawning", i)
                replacement = self._replace_worker(i)
                ray_tpu.get(replacement.set_weights.remote(weights), timeout=120)

    def sample(self, steps_per_worker: int, explore: bool = True) -> List[SampleBatch]:
        """Synchronous parallel sampling with fault tolerance: a worker that
        dies mid-round is replaced and the round proceeds without it
        (reference: execution/rollout_ops.py:21 + actor_manager probe).
        ``explore=False`` samples greedily (evaluation rollouts)."""
        refs: dict = {}
        results: List[SampleBatch] = []
        dead: list = []
        for i, w in zip(self._indices, self._workers):
            try:
                refs[w.sample.remote(steps_per_worker, explore)] = (i, w)
            except Exception:
                logger.warning("rollout worker %d unreachable at submit; respawning", i)
                dead.append((i, w))
        for ref, (idx, w) in refs.items():
            try:
                results.append(ray_tpu.get(ref, timeout=300))
            except Exception:
                logger.warning("rollout worker %d failed; respawning", idx)
                dead.append((idx, w))
        for idx, w in dead:
            self._replace_worker(self._workers.index(w))
        return results

    def sync_filters(self):
        """Merge per-worker filter DELTAS into the shared base and
        redistribute (reference: FilterManager.synchronize — deltas, not full
        states, so shared history is never double-counted)."""
        if not self.observation_filter or not self._workers:
            return
        from ray_tpu.rllib.connectors import MeanStdFilter

        # Fan out, then gather (a slow worker must not serialize the sync).
        pop_refs = [w.pop_filter_delta.remote() for w in self._workers]
        deltas = []
        for ref in pop_refs:
            try:
                deltas.append(ray_tpu.get(ref, timeout=60))
            except Exception:
                pass
        merger = MeanStdFilter()
        states = [self._filter_base] + [d for d in deltas if d]
        merger.merge_states([st for st in states if st])
        self._filter_base = merger.get_state()
        set_refs = [w.set_filter_state.remote(self._filter_base) for w in self._workers]
        for ref in set_refs:
            try:
                ray_tpu.get(ref, timeout=60)
            except Exception:
                pass

    def episode_stats(self) -> dict:
        stats = {"episode_rewards": [], "episode_lens": []}
        for ref in [w.episode_stats.remote() for w in self._workers]:
            try:
                s = ray_tpu.get(ref, timeout=60)
                stats["episode_rewards"] += s["episode_rewards"]
                stats["episode_lens"] += s["episode_lens"]
            except Exception:
                pass
        return stats

    def stop(self):
        for w in self._workers:
            try:
                w.stop.remote()
            except Exception:
                pass
        for w in self._workers:
            try:
                ray_tpu.kill(w)  # release the actor's CPU hold
            except Exception:
                pass
        self._workers = []
